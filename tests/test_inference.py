"""Inference tests (reference pattern: tests/unit/inference/test_inference.py
— HF model matrix vs baseline outputs). Tiny randomly-initialized HF models
are converted via module_inject and their logits compared against the torch
forward pass."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


def _tiny_gpt2():
    cfg = transformers.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                                  n_layer=2, n_head=4)
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval()


def _tiny_llama(**kw):
    cfg = transformers.LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                                   num_hidden_layers=2, num_attention_heads=4,
                                   num_key_value_heads=kw.pop("kvh", 2),
                                   max_position_embeddings=64, **kw)
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval()


def _compare_logits(hf_model, atol=2e-3):
    engine = ds.init_inference(hf_model, dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (2, 16))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    got = np.asarray(engine.forward(ids))
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return engine


def test_gpt2_injection_logits_match():
    _compare_logits(_tiny_gpt2())


def test_llama_injection_logits_match():
    _compare_logits(_tiny_llama())


def test_mistral_injection_logits_match():
    cfg = transformers.MistralConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                                     num_hidden_layers=2, num_attention_heads=4,
                                     num_key_value_heads=2, max_position_embeddings=64)
    torch.manual_seed(0)
    _compare_logits(transformers.MistralForCausalLM(cfg).eval())


def test_mixtral_injection_logits_match():
    cfg = transformers.MixtralConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                                     num_hidden_layers=2, num_attention_heads=4,
                                     num_key_value_heads=2, max_position_embeddings=64,
                                     num_local_experts=4, num_experts_per_tok=2)
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(cfg).eval()
    # MoE token-drop under tiny capacity: compare loosely on logits magnitude
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (1, 8))
    got = np.asarray(engine.forward(ids))
    assert np.all(np.isfinite(got))


def test_generate_greedy_deterministic():
    engine = ds.init_inference(_tiny_llama(), dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (2, 8))
    out1 = np.asarray(engine.generate(ids, max_new_tokens=8))
    out2 = np.asarray(engine.generate(ids, max_new_tokens=8))
    assert out1.shape == (2, 16)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :8], ids)


def test_generate_matches_hf_greedy():
    """Greedy continuation must match HF's greedy generate."""
    hf = _tiny_llama()
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(3).integers(0, 100, (1, 8))
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids), max_new_tokens=8, do_sample=False,
                           pad_token_id=0).numpy()
    got = np.asarray(engine.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(got, want)


def test_generate_sampling_controls():
    engine = ds.init_inference(_tiny_llama(), dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (2, 8))
    out = engine.generate(ids, max_new_tokens=4, temperature=0.8, top_k=10, top_p=0.9)
    assert out.shape == (2, 12)


def test_native_model_inference():
    engine = ds.init_inference(build_model("tiny"), dtype="float32")
    ids = np.random.default_rng(0).integers(0, 200, (2, 8))
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (2, 12)


def test_decode_matches_forward_stacked_cache(rng):
    """Scan-based KV decode == full forward (replaces the old list-cache test)."""
    model = build_model("tiny")
    params = model.init(rng)
    ids = jax.random.randint(rng, (2, 8), 0, model.cfg.vocab_size)
    full = model.apply(params, ids)
    cache = model.init_cache(2, 16)
    cache_len = jnp.zeros((2,), jnp.int32)
    outs = []
    for t in range(8):
        logits, cache = model.apply_decode(params, ids[:, t:t + 1], cache, cache_len)
        cache_len = cache_len + 1
        outs.append(logits[:, 0])
    np.testing.assert_allclose(np.asarray(full), np.asarray(jnp.stack(outs, 1)),
                               atol=2e-4, rtol=1e-4)


def test_init_inference_from_checkpoint_files(tmp_path):
    """init_inference(checkpoint=dir) serves from sharded checkpoint FILES
    (safetensors index + config.json) without touching the torch module's
    weights — greedy output must match the module-injected engine."""
    from transformers import LlamaConfig, LlamaForCausalLM
    import torch
    torch.manual_seed(0)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=64))
    hf.eval()
    # force multiple shards to exercise the index.json path
    hf.save_pretrained(str(tmp_path), max_shard_size="50KB")
    import os
    assert os.path.exists(tmp_path / "model.safetensors.index.json")

    ref_eng = ds.init_inference(hf, dtype="float32")
    ckpt_eng = ds.init_inference(model=None, checkpoint=str(tmp_path),
                                 dtype="float32")
    ids = np.random.default_rng(0).integers(0, 128, (2, 8))
    ref = np.asarray(ref_eng.generate(ids, max_new_tokens=6))
    got = np.asarray(ckpt_eng.generate(ids, max_new_tokens=6))
    np.testing.assert_array_equal(ref, got)


def test_init_inference_from_torch_bin_manifest(tmp_path):
    """The reference-style JSON manifest ('checkpoints': [files]) over torch
    .bin shards also loads; model passed as HF config only."""
    from transformers import GPT2Config, GPT2LMHeadModel
    import torch, json
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                                    n_layer=2, n_head=4))
    hf.eval()
    torch.save(hf.state_dict(), str(tmp_path / "weights.bin"))
    with open(tmp_path / "ckpt.json", "w") as f:
        json.dump({"checkpoints": ["weights.bin"]}, f)

    eng = ds.init_inference(model=hf.config, checkpoint=str(tmp_path / "ckpt.json"),
                            dtype="float32")
    ids = np.random.default_rng(0).integers(0, 128, (1, 8))
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids), max_new_tokens=6,
                          do_sample=False).numpy()
    got = np.asarray(eng.generate(ids, max_new_tokens=6))
    np.testing.assert_array_equal(ref, got)


def test_checkpoint_files_bf16_upcast(tmp_path):
    """bf16 checkpoints load through the file path (numpy has no bf16; the
    mapping upcasts on read)."""
    from transformers import LlamaConfig, LlamaForCausalLM
    import torch
    torch.manual_seed(0)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=32))
    hf.to(torch.bfloat16)
    hf.save_pretrained(str(tmp_path))
    eng = ds.init_inference(model=None, checkpoint=str(tmp_path), dtype="float32")
    out = eng.forward(np.zeros((1, 4), np.int32))
    assert np.all(np.isfinite(np.asarray(out)))
