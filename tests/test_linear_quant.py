"""ZeRO-Inference quantized layers + DS-LoRA + activation checkpointing tests
(reference: tests/unit/inference/quantization, tests/unit/linear)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.usefixtures("mesh_8dp")


def test_quantized_parameter_roundtrip(rng):
    from deepspeed_tpu.inference.quantization.layers import QuantizedParameter
    w = jax.random.normal(rng, (64, 48))
    for bits in (8, 4):
        qp = QuantizedParameter.quantize(w, bits=bits, group_size=64)
        back = qp.dequantized()
        assert back.shape == w.shape
        tol = float(jnp.max(jnp.abs(w))) / (127 if bits == 8 else 7) * 1.1
        assert float(jnp.max(jnp.abs(back - w))) < tol


def test_quantized_linear_close(rng):
    from deepspeed_tpu.inference.quantization.layers import QuantizedLinear
    w = jax.random.normal(rng, (32, 16))
    x = jax.random.normal(jax.random.fold_in(rng, 1), (4, 32))
    lin = QuantizedLinear(w, bits=8, group_size=64)
    got = lin(x)
    want = x @ w
    assert float(jnp.max(jnp.abs(got - want))) < 0.15 * float(jnp.max(jnp.abs(want)))


def test_quantize_model_params(rng):
    from deepspeed_tpu.inference.quantization.layers import (dequantize_model_params,
                                                             quantize_model_params)
    from deepspeed_tpu.models import build_model
    model = build_model("tiny")
    params = model.init(rng)
    qparams = quantize_model_params(params, bits=8, min_size=1024)
    deq = dequantize_model_params(qparams)
    ids = jnp.zeros((1, 8), jnp.int32)
    ref = model.apply(params, ids)
    got = model.apply(deq, ids)
    assert jnp.all(jnp.isfinite(got))
    # quantized model stays predictive-close on logit scale
    assert float(jnp.mean(jnp.abs(got - ref))) < 0.2


def test_lora_linear(rng):
    from deepspeed_tpu.linear.optimized_linear import LoRAConfig, OptimizedLinear
    lin = OptimizedLinear(32, 16, lora_config=LoRAConfig(lora_r=4, lora_alpha=8))
    params = lin.init(rng)
    x = jax.random.normal(rng, (4, 32))
    y = lin.apply(params, x)
    assert y.shape == (4, 16)
    # lora_b starts at zero → output equals frozen base
    base_y = x @ params["base"].astype(x.dtype)
    np.testing.assert_allclose(np.asarray(y), np.asarray(base_y), atol=1e-5)
    # base is frozen: grads flow only to adapters
    g = jax.grad(lambda p: jnp.sum(lin.apply(p, x) ** 2))(params)
    assert float(jnp.max(jnp.abs(g["base"]))) == 0.0
    # with B=0, gradient reaches B first (dL/dB = (xA)^T g); A follows later
    assert float(jnp.max(jnp.abs(g["lora_b"]))) > 0.0


def test_lora_quantized_base(rng):
    from deepspeed_tpu.linear.optimized_linear import (LoRAConfig, OptimizedLinear,
                                                       QuantizationConfig)
    lin = OptimizedLinear(64, 32, lora_config=LoRAConfig(lora_r=4),
                          quantization_config=QuantizationConfig(q_bits=8, group_size=64))
    params = lin.init(rng)
    x = jax.random.normal(rng, (2, 64))
    y = lin.apply(params, x)
    assert y.shape == (2, 32) and bool(jnp.all(jnp.isfinite(y)))


def test_activation_checkpointing_api(rng):
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt

    def layer(x):
        return jnp.tanh(x @ jnp.ones((8, 8)))

    x = jax.random.normal(rng, (4, 8))
    plain = layer(x)
    wrapped = ckpt.checkpoint(layer, x)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(wrapped), atol=1e-6)
    g1 = jax.grad(lambda x: jnp.sum(layer(x)))(x)
    g2 = jax.grad(lambda x: jnp.sum(ckpt.checkpoint_wrapper(layer)(x)))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)
    ckpt.configure(partition_activations=True)
    assert ckpt.partition_activations_spec() is not None


def test_zero_init_and_gathered_params(rng):
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    with ds.zero.Init(config_dict_or_path={"zero_optimization": {"stage": 3}}):
        model = build_model("tiny")
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3}, "steps_per_print": 10 ** 9}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    tok = engine.module_params["embed"]["tok"]
    assert not tok.sharding.is_fully_replicated
    with ds.zero.GatheredParameters({"tok": tok}) as full:
        assert full["tok"].sharding.is_fully_replicated


def test_fp6_weight_only_quantization():
    """FP6 (e3m2) weight-only format (reference v2 cuda_linear FP6 GEMM):
    4 codes pack into 3 bytes, per-group absmax scaling, dequant through the
    64-entry codebook. Representable values round-trip exactly."""
    from deepspeed_tpu.inference.quantization.layers import QuantizedParameter
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(512, 256)) * 0.05, jnp.float32)
    qp = QuantizedParameter.quantize(w, bits=6, group_size=256)
    deq = qp.dequantized()
    rel = float(jnp.sqrt(jnp.mean((deq - w) ** 2)) / jnp.sqrt(jnp.mean(w ** 2)))
    assert rel < 0.08            # 2-bit mantissa noise floor
    assert qp.nbytes < w.size    # < 1 byte per weight, packed
    # values on the fp6 grid round-trip exactly (x1 scale group)
    exact = jnp.asarray([[28.0, -1.75, 0.25 * 0.5, 0.0]])
    qp2 = QuantizedParameter.quantize(exact, bits=6, group_size=4)
    np.testing.assert_allclose(np.asarray(qp2.dequantized()), np.asarray(exact),
                               atol=1e-6)


def test_woq_fused_matmul_matches_dequant():
    """Fused mixed-input GEMM == x @ dequantized(W) exactly (same quant
    grid), for all three bit widths and a non-divisible block_n fallback."""
    import numpy as np
    from deepspeed_tpu.ops.pallas.woq_matmul import (quantize_woq, woq_matmul,
                                                     woq_dequantize)
    rng = np.random.default_rng(0)
    K, N, M = 512, 384, 4
    w = jnp.asarray(rng.standard_normal((K, N)), jnp.float32) * 0.1
    x = jnp.asarray(rng.standard_normal((M, K)), jnp.float32) * 0.5
    for bits in (8, 4, 6):
        qs = quantize_woq(w, bits=bits, group_size=128)
        wd = woq_dequantize(qs, jnp.float32)
        got = woq_matmul(x, qs, block_n=128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(x @ wd),
                                   atol=1e-5, rtol=1e-5, err_msg=f"bits={bits}")
        got2 = woq_matmul(x, qs, block_n=250)   # falls back to one N tile
        np.testing.assert_allclose(np.asarray(got2), np.asarray(x @ wd),
                                   atol=1e-5, rtol=1e-5)


def test_woq_quant_error_bounds():
    """Quantization error ordering: int8 < fp6 ~ int4 on gaussian weights."""
    import numpy as np
    from deepspeed_tpu.ops.pallas.woq_matmul import quantize_woq, woq_dequantize
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
    errs = {}
    for bits in (8, 6, 4):
        qs = quantize_woq(w, bits=bits, group_size=128)
        errs[bits] = float(jnp.mean(jnp.abs(woq_dequantize(qs, jnp.float32) - w)))
    assert errs[8] < errs[6] <= errs[4] * 1.5
    assert errs[8] < 0.02 and errs[4] < 0.3


def test_quantized_linear_uses_fused_path():
    """Aligned 2-D weights route through the fused kernel; misaligned fall
    back to the flat path — outputs stay close to the dense linear."""
    import numpy as np
    from deepspeed_tpu.inference.quantization.layers import QuantizedLinear
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.standard_normal((512, 128)), jnp.float32) * 0.1
    b = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((3, 512)), jnp.float32)
    ql = QuantizedLinear(w, bias=b, bits=8)
    assert ql.fused is not None
    np.testing.assert_allclose(np.asarray(ql(x)), np.asarray(x @ w + b),
                               atol=0.15, rtol=0.05)
    # batched leading dims
    xb = x.reshape(1, 3, 512)
    np.testing.assert_allclose(np.asarray(ql(xb))[0], np.asarray(ql(x)),
                               atol=1e-6)
    # odd K: flat fallback
    w_odd = jnp.asarray(rng.standard_normal((100, 64)), jnp.float32) * 0.1
    ql2 = QuantizedLinear(w_odd, bits=8)
    assert ql2.fused is None
    y2 = ql2(jnp.asarray(rng.standard_normal((2, 100)), jnp.float32))
    assert y2.shape == (2, 64)
