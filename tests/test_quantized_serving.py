"""Quantized serving: int8 weights, int8 paged KV, low-precision collectives.

The low-precision serving configs (``RaggedInferenceEngineConfig.kv_dtype``
/ ``weight_dtype`` / ``tp_collective_payload``) trade precision for HBM and
wire bytes, and each trade ships with an explicit tolerance contract these
tests pin:

- **int8 KV pages** quantize at append with a per-(token, head) scale
  packed into the page row (write-once, so a token's stored representation
  never depends on when it is read): greedy serving is TOKEN-IDENTICAL to
  the f32 engine — at tp=1, at tp=8, and with the prefix cache republishing
  quantized pages.
- **int8 weights** (per-output-channel absmax) bound the single-forward
  logit error to <= 5% of the logit scale, and a teacher-forced perplexity
  smoke stays within 10% of the f32 engine's — close in distribution, not
  just argmax.
- **fp8 (e4m3) collective payloads** ride the same quantized-exchange
  machinery as int8 and must complete every generation budget.
- The pool's resident representation is a CONTRACT across the memory
  hierarchy: swap-tier records carry a versioned layout stamp and refuse
  to restore into a differently-quantized pool, page movers refuse
  mixed-dtype scatters, and the byte-denominated telemetry
  (``kv_swap_bytes`` / ``kv_resident_bytes``) prices blocks at the
  resident footprint — the >= 1.8x int8 page saving is asserted here.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier
from deepspeed_tpu.models import build_model

MAX_NEW = 8


@pytest.fixture(scope="module")
def model_params():
    """8 heads so the SAME model serves the tp=1 contracts and the tp=8
    parity leg (every sharded axis divides the 8-way mesh)."""
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=128)


PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (200,))
           .astype(np.int32)[o:o + n]
           for u, (o, n) in enumerate(((0, 7), (10, 24), (40, 33), (80, 5)))}


def _arrivals():
    return iter([[(u, PROMPTS[u]) for u in PROMPTS]])


@pytest.fixture(scope="module")
def greedy_base(model_params):
    """f32 tp=1 greedy serve() outputs — the reference every quantized
    variant is measured against."""
    model, params = model_params
    return dict(_engine(model, params).serve(_arrivals(),
                                             max_new_tokens=MAX_NEW))


def _one_forward_logits(e, width=1):
    """Single ragged forward through the engine's runner (tp=1): the
    logit-tolerance surface, decoupled from sampling."""
    ids = np.asarray([PROMPTS[1][:width]], np.int32)
    pos = np.asarray([np.arange(width)], np.int32)
    tbl = np.asarray([[1, 2]], np.int32)[:, :max(1, (width + 15) // 16)]
    n = np.asarray([width], np.int32)
    fwd = jax.jit(functools.partial(e.runner._forward, all_logits=True))
    logits, _, _ = fwd(e.params, jnp.asarray(ids), jnp.asarray(pos),
                       jnp.asarray(tbl), jnp.asarray(n), e.kv.k, e.kv.v)
    return np.asarray(logits)


# ---------------------------------------------------------------------------
# int8 KV pages: exact greedy parity
# ---------------------------------------------------------------------------

def test_int8_kv_greedy_token_parity(model_params, greedy_base):
    """int8 KV pages are write-once (scale packed beside the quantized
    row), so greedy decoding is token-identical to the f32 pool — the
    strongest contract a lossy representation can offer."""
    model, params = model_params
    e = _engine(model, params, kv_dtype="int8")
    assert e.kv.k.dtype == jnp.int8
    got = dict(e.serve(_arrivals(), max_new_tokens=MAX_NEW))
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    assert not e.state.seqs


def test_int8_kv_prefix_cache_parity(model_params, greedy_base):
    """The prefix cache publishes/restores QUANTIZED pages: a second pass
    over the same prompts (served from cache hits) is still
    token-identical to the f32 baseline."""
    model, params = model_params
    e = _engine(model, params, kv_dtype="int8", prefix_cache=True)
    first = dict(e.serve(_arrivals(), max_new_tokens=MAX_NEW))
    second = dict(e.serve(_arrivals(), max_new_tokens=MAX_NEW))
    assert e.telemetry.counters["prefix_hits"] > 0, \
        "second pass must actually hit the cache"
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], first[u],
                                      err_msg=f"cache-cold uid={u}")
        np.testing.assert_array_equal(greedy_base[u], second[u],
                                      err_msg=f"cache-hot uid={u}")


@pytest.mark.multichip
def test_tp8_int8_kv_token_parity(model_params, greedy_base):
    """Head-sharded int8 pools (scale lanes ride the head_dim axis, which
    is unsharded) keep the tp=8 engine token-identical too."""
    model, params = model_params
    e = _engine(model, params, tp=8, kv_dtype="int8")
    got = dict(e.serve(_arrivals(), max_new_tokens=MAX_NEW))
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    assert e.kv.free_blocks == e.kv.num_blocks - 1


# ---------------------------------------------------------------------------
# int8 weights: bounded logit error, ppl smoke
# ---------------------------------------------------------------------------

def test_int8_weights_logit_error_within_5pct(model_params):
    """Per-channel absmax int8 weights: one ragged forward's logits track
    the f32 engine within 5% of the logit scale, and a full quantized
    serve still completes every generation budget."""
    model, params = model_params
    ef = _engine(model, params)
    eq = _engine(model, params, weight_dtype="int8")
    exact = _one_forward_logits(ef)
    quant = _one_forward_logits(eq)
    scale = np.abs(exact).max()
    assert np.abs(exact - quant).max() <= 0.05 * scale, \
        (np.abs(exact - quant).max(), scale)
    got = dict(eq.serve(_arrivals(), max_new_tokens=MAX_NEW))
    assert set(got) == set(PROMPTS)
    assert all(len(v) == MAX_NEW for v in got.values())


def test_full_quant_ppl_smoke(model_params):
    """Teacher-forced perplexity over a real prompt: the fully quantized
    engine (int8 weights + int8 KV) stays within 10% of the f32 engine's
    ppl — the distribution-level smoke behind the argmax contracts."""
    model, params = model_params
    toks = PROMPTS[2][:16]

    def ppl(e):
        logits = _one_forward_logits(e, width=len(toks))[0]
        logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        nll = -np.asarray(logp)[np.arange(len(toks) - 1), toks[1:]]
        return float(np.exp(nll.mean()))

    base = ppl(_engine(model, params))
    quant = ppl(_engine(model, params, weight_dtype="int8",
                        kv_dtype="int8"))
    assert abs(quant - base) <= 0.10 * base, (base, quant)


# ---------------------------------------------------------------------------
# fp8 collective payloads
# ---------------------------------------------------------------------------

@pytest.mark.multichip
def test_tp8_fp8_collectives_complete_budgets(model_params):
    """The e4m3 payload variant of the quantized exchanges completes every
    generation budget and drains clean (same contract shape as the int8
    payload: near-ties may flip, budgets may not)."""
    model, params = model_params
    e = _engine(model, params, tp=8, tp_quantized_collectives=True,
                tp_collective_payload="fp8")
    got = dict(e.serve(_arrivals(), max_new_tokens=MAX_NEW))
    assert set(got) == set(PROMPTS)
    assert all(len(v) == MAX_NEW for v in got.values())
    assert e.kv.free_blocks == e.kv.num_blocks - 1


# ---------------------------------------------------------------------------
# configuration and representation contracts
# ---------------------------------------------------------------------------

def test_quant_config_validation(model_params):
    """Unsupported dtypes fail at CONSTRUCTION, not mid-serve."""
    model, params = model_params
    for bad in (dict(kv_dtype="int4"), dict(weight_dtype="fp4"),
                dict(tp_collective_payload="int4")):
        with pytest.raises(ValueError):
            _engine(model, params, **bad)


def _pool(kv_dtype=None):
    kv = BlockedKVCache(num_layers=2, kv_heads=2, head_dim=4, num_blocks=8,
                        block_size=4, dtype=jnp.float32, kv_dtype=kv_dtype)
    kv.reserve_trash_block()
    return kv


def test_tier_layout_mismatch_fails_loudly(tmp_path):
    """A tier record committed from an int8 pool restores bit-identically
    into an int8 pool, and REFUSES (IOError, not a silent astype) to
    restore into an f32 pool: the record's versioned layout stamp is
    checked against the destination's resident representation."""
    kv = _pool("int8")
    blocks = kv.allocator.allocate(2)
    payload = np.random.default_rng(3).integers(
        -127, 127, (2, 2, 2, 4, kv.lanes)).astype(np.int8)
    kv.k = kv.k.at[:, :, blocks].set(payload)
    kv.v = kv.v.at[:, :, blocks].set(-payload)
    tier = KVSwapTier(str(tmp_path))
    tier.put_request(7, tokens=8, kv=kv, blocks=blocks)

    dst = kv.allocator.allocate(2)
    KVSwapTier(str(tmp_path)).restore_request(7, kv, dst)
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, dst]), payload)
    np.testing.assert_array_equal(np.asarray(kv.v[:, :, dst]), -payload)

    raw = _pool()
    with pytest.raises(IOError, match="int8"):
        tier.restore_request(7, raw, raw.allocator.allocate(2))


def test_scatter_pages_dtype_mismatch_fails_loudly():
    """Cross-pool page moves never coerce dtypes: an f32 page scattered
    into an int8 pool (a stale mover wiring two differently-quantized
    engines) raises instead of silently astype-ing garbage."""
    src, dst = _pool(), _pool("int8")
    pages_k, pages_v = src.read_pages([1])
    with pytest.raises(ValueError, match="dtype"):
        dst.scatter_pages(dst.k, dst.v, [1], pages_k, pages_v)


def test_quantized_pool_block_bytes_and_telemetry(model_params):
    """The resident block footprint drops >= 1.8x under int8 pages (the
    GL201 carry-bytes claim, asserted at the pool), and the serve-time
    telemetry prices blocks at that footprint: ``kv_resident_bytes`` and
    ``kv_swap_bytes`` expose HBM/tier pressure in bytes, not blocks."""
    model, params = model_params
    ef = _engine(model, params)
    eq = _engine(model, params, kv_dtype="int8")
    ratio = ef.kv.block_bytes / eq.kv.block_bytes
    assert ratio >= 1.8, ratio
    dict(eq.serve(iter([[(0, PROMPTS[0])]]), max_new_tokens=MAX_NEW))
    assert eq.telemetry._kv_block_bytes == eq.kv.block_bytes
    assert "kv_resident_bytes" in eq.telemetry.gauges
    assert "kv_swap_bytes" in eq.telemetry.counters
    prom = eq.telemetry.render_prometheus()
    assert "ds_serving_kv_swap_bytes_total" in prom
    assert "ds_serving_kv_resident_bytes" in prom
