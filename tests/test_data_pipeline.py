"""Data pipeline tests: indexed dataset round-trips, analyzer map/reduce,
curriculum wiring (reference pattern: tests/unit/runtime/test_data.py and
data-sampling unit tests)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, best_fitting_dtype,
    dataset_exists, make_builder, make_dataset)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    ACCUMULATE, DataAnalyzer, DistributedDataAnalyzer, curriculum_difficulty_fn)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler


def _write(prefix, samples, dtype=np.int32, docs=None):
    b = MMapIndexedDatasetBuilder(prefix, dtype)
    for i, s in enumerate(samples):
        b.add_item(s)
        if docs and i in docs:
            b.end_document()
    return b.finalize()


def test_indexed_dataset_roundtrip(tmp_path):
    samples = [np.arange(n, dtype=np.int32) * 3 for n in (5, 1, 17, 128)]
    prefix = str(tmp_path / "ds")
    ds = _write(prefix, samples)
    assert dataset_exists(prefix)
    assert len(ds) == 4
    for i, s in enumerate(samples):
        np.testing.assert_array_equal(ds[i], s)
        assert ds.num_tokens(i) == len(s)
    # windowed read
    np.testing.assert_array_equal(ds.get(2, offset=3, length=4), samples[2][3:7])
    # reopen fresh
    ds2 = make_dataset(prefix)
    np.testing.assert_array_equal(ds2[3], samples[3])
    np.testing.assert_array_equal(ds2.sizes, [5, 1, 17, 128])


def test_indexed_dataset_dtypes_and_docs(tmp_path):
    assert best_fitting_dtype(30000) == np.uint16
    assert best_fitting_dtype(100000) == np.int32
    prefix = str(tmp_path / "docs")
    b = make_builder(prefix, vocab_size=30000)
    for s in ([1, 2, 3], [4], [5, 6]):
        b.add_item(s)
    b.end_document()
    b.add_item([7, 8])
    ds = b.finalize()
    assert ds.dtype == np.uint16
    np.testing.assert_array_equal(ds.doc_idx, [0, 3, 4])


def test_indexed_dataset_merge(tmp_path):
    a = [np.arange(4, dtype=np.int64), np.arange(2, dtype=np.int64) + 10]
    c = [np.arange(3, dtype=np.int64) + 100]
    _write(str(tmp_path / "a"), a, np.int64)
    _write(str(tmp_path / "c"), c, np.int64)
    b = MMapIndexedDatasetBuilder(str(tmp_path / "m"), np.int64)
    b.merge_file_(str(tmp_path / "a"))
    b.merge_file_(str(tmp_path / "c"))
    merged = b.finalize()
    assert len(merged) == 3
    np.testing.assert_array_equal(merged[1], a[1])
    np.testing.assert_array_equal(merged[2], c[0])


def test_data_analyzer_map_reduce(tmp_path):
    rng = np.random.default_rng(0)
    data = [rng.integers(0, 50, rng.integers(1, 40)) for _ in range(200)]

    def seqlen(batch):
        return [len(s) for s in batch]

    def total_tokens(batch):
        return sum(len(s) for s in batch)

    an = DataAnalyzer(data, ["seqlen", "total"], [seqlen, total_tokens],
                      metric_types=["single_value_per_sample", ACCUMULATE],
                      save_path=str(tmp_path), num_workers=3, batch_size=32)
    an.run_map_reduce()

    s2m = MMapIndexedDataset(str(tmp_path / "seqlen_sample_to_metric"))
    assert len(s2m) == 200
    for i in (0, 57, 199):
        assert int(s2m[i][0]) == len(data[i])
    # inverse index groups samples by value, ascending
    i2m = MMapIndexedDataset(str(tmp_path / "seqlen_index_to_metric"))
    i2s = MMapIndexedDataset(str(tmp_path / "seqlen_index_to_sample"))
    vals = [int(i2m[k][0]) for k in range(len(i2m))]
    assert vals == sorted(set(len(s) for s in data))
    covered = np.concatenate([np.asarray(i2s[k]) for k in range(len(i2s))])
    assert sorted(covered) == list(range(200))
    for k in range(len(i2m)):
        for si in np.asarray(i2s[k]):
            assert len(data[si]) == vals[k]
    acc = MMapIndexedDataset(str(tmp_path / "total_accumulated"))
    assert int(acc[0][0]) == sum(len(s) for s in data)


def test_distributed_data_analyzer_matches_single(tmp_path):
    rng = np.random.default_rng(1)
    data = [rng.integers(0, 9, rng.integers(1, 20)) for _ in range(101)]

    def seqlen(batch):
        return [len(s) for s in batch]

    def total_tokens(batch):
        return sum(len(s) for s in batch)

    names = ["seqlen", "total"]
    fns = [seqlen, total_tokens]
    types = ["single_value_per_sample", ACCUMULATE]
    # every "rank" maps its shard; rank 0 merges (incl. accumulate shards)
    for r in range(1, 4):
        DistributedDataAnalyzer(data, names, fns, metric_types=types,
                                save_path=str(tmp_path / "dist"),
                                rank=r, world_size=4).run_map()
    DistributedDataAnalyzer(data, names, fns, metric_types=types,
                            save_path=str(tmp_path / "dist"),
                            rank=0, world_size=4).run_map_reduce()
    DataAnalyzer(data, names, fns, metric_types=types,
                 save_path=str(tmp_path / "single")).run_map_reduce()
    a = MMapIndexedDataset(str(tmp_path / "dist" / "seqlen_sample_to_metric"))
    b = MMapIndexedDataset(str(tmp_path / "single" / "seqlen_sample_to_metric"))
    for i in range(len(data)):
        assert int(a[i][0]) == int(b[i][0])
    # accumulate aggregates over ALL ranks' shards, not just rank 0's slice
    acc = MMapIndexedDataset(str(tmp_path / "dist" / "total_accumulated"))
    assert int(acc[0][0]) == sum(len(s) for s in data)


def test_curriculum_sampler_uses_analysis(tmp_path):
    data = [np.zeros(n, np.int32) for n in range(1, 41)]  # difficulty = length

    def seqlen(batch):
        return [len(s) for s in batch]

    DataAnalyzer(data, ["seqlen"], [seqlen],
                 save_path=str(tmp_path)).run_map_reduce()
    diff = curriculum_difficulty_fn(str(tmp_path), "seqlen")
    assert diff(0) == 1 and diff(39) == 40

    sched = CurriculumScheduler({"curriculum_type": "seqlen",
                                 "min_difficulty": 8, "max_difficulty": 40,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 10,
                                                     "difficulty_step": 8}})
    sampler = DeepSpeedDataSampler(total_samples=len(data), micro_batch_size=2,
                                   data_parallel_size=2, shuffle=False,
                                   curriculum_scheduler=sched, difficulty_of=diff)
    first = next(iter(sampler))
    # at min difficulty only samples with len <= 8 are eligible
    assert all(len(data[i]) <= 8 for i in first)


def test_pack_sequences_per_doc_independence():
    """Packed documents must behave exactly as if each ran alone: identical
    per-token logits (segment mask blocks cross-doc attention; positions
    restart per doc), and padding contributes nothing to the loss."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime.data_pipeline import pack_sequences

    rng = np.random.default_rng(0)
    docs = [list(rng.integers(0, 200, n)) for n in (12, 9, 7, 20, 5)]
    packed = pack_sequences(docs, seq_len=32)
    assert packed["input_ids"].shape[1] == 32
    assert packed["segment_ids"].max() >= 2       # something actually packed

    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    logits = model.apply(params, jnp.asarray(packed["input_ids"]),
                         positions=jnp.asarray(packed["positions"]),
                         segment_ids=jnp.asarray(packed["segment_ids"]))

    # every doc, wherever it was packed, matches its solo forward
    for doc in docs:
        solo = model.apply(params, jnp.asarray([doc], jnp.int32))[0]
        found = False
        for r in range(packed["input_ids"].shape[0]):
            row = packed["input_ids"][r]
            seg = packed["segment_ids"][r]
            for s_idx in range(1, seg.max() + 1):
                sel = seg == s_idx
                if sel.sum() == len(doc) and np.array_equal(row[sel], doc):
                    np.testing.assert_allclose(
                        np.asarray(logits[r][sel]), np.asarray(solo),
                        atol=2e-4)
                    found = True
        assert found, "doc not found in packed batch"

    # loss ignores padding: corrupting pad-token ids must not change it,
    # and it must equal the mask-weighted mean NLL computed from the logits
    batch = {k: jnp.asarray(v) for k, v in packed.items()}
    loss = float(model.loss(params, batch))
    corrupted = dict(batch)
    pad = packed["segment_ids"] == 0
    corrupted["input_ids"] = jnp.asarray(
        np.where(pad, 17, packed["input_ids"]))
    corrupted["labels"] = corrupted["input_ids"]
    np.testing.assert_allclose(loss, float(model.loss(params, corrupted)),
                               rtol=1e-6)
    lp = jax.nn.log_softmax(np.asarray(logits, np.float32), axis=-1)
    nll = -np.take_along_axis(lp, packed["labels"][..., None], axis=-1)[..., 0]
    manual = (nll * packed["loss_mask"]).sum() / packed["loss_mask"].sum()
    np.testing.assert_allclose(loss, manual, rtol=1e-5)
