"""Service-edge suite (ISSUE 14): threaded fleet driver, HTTP/SSE
front-end, edge admission, autoscaling.

Pins the tentpole contracts:

* the thread-per-replica ``FleetDriver`` is TOKEN-IDENTICAL to the
  serial cooperative router on the same schedule — plain, and through a
  scripted kill/failover (timing differs; token identity is
  timing-independent by the resume-arrival construction);
* ``ServeBoundary.emissions`` streams exactly the tokens the final
  ``(uid, tokens)`` yield reports (the SSE feed's correctness root);
* an SSE stream over the real HTTP endpoint is byte-identical to a
  direct ``serve()`` of the same request;
* a client disconnect cancels through the engine's deadline/cancel path:
  the ledger empties and every KV block returns to the allocator;
* scripted overload sheds at the EDGE with a numeric ``Retry-After``
  while every replica's local scheduler sheds nothing;
* the autoscaler's prefill<->decode flip round-trips (flip under
  queued-prompt-token pressure, flip back when it drains) with outputs
  token-identical throughout.

Wall-clock waits use generous poll-until deadlines, never timing
asserts, so the suite stays deterministic-in-outcome on slow boxes.
"""

import http.client
import json
import socket
import threading
import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig,
                                                  ServeBoundary)
from deepspeed_tpu.inference.v2.faults import RouterFaultInjector
from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier
from deepspeed_tpu.inference.v2.router import EngineRouter, RouterConfig
from deepspeed_tpu.inference.v2.service import (AutoscaleConfig,
                                                AutoscaleController,
                                                EdgeConfig, FleetDriver,
                                                ServiceEdge)
from deepspeed_tpu.models import build_model

pytestmark = pytest.mark.service

BS, CHUNK, MAX_NEW = 16, 8, 8
RNG = np.random.default_rng(14)
PROMPTS = {u: RNG.integers(0, 200, (12,)).astype(np.int32)
           for u in range(8)}


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=BS, prefill_chunk_size=CHUNK,
              max_tokens_per_step=512, dtype="float32",
              max_ragged_batch_size=4, frame_steps=2,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=160)


def _wait(cond, timeout=60.0, interval=0.05):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _assert_clean(eng):
    assert not eng._ledger
    assert not eng.state.seqs
    assert eng.kv.free_blocks == eng.kv.num_blocks - 1


# ----------------------------------------------------------------------
# boundary emissions: the streaming contract at the engine level
# ----------------------------------------------------------------------

def test_boundary_emissions_match_final_output(tiny_model_params):
    model, params = tiny_model_params
    eng = _engine(model, params)

    def arrivals():
        yield [(0, PROMPTS[0]), (1, PROMPTS[1])]
        yield [(2, PROMPTS[2])]

    streamed = {0: [], 1: [], 2: []}
    finals = {}
    for ev in eng.serve(arrivals(), max_new_tokens=MAX_NEW,
                        yield_boundaries=True):
        if isinstance(ev, ServeBoundary):
            if ev.dispatched:
                assert ev.emissions is not None
                for uid, toks in ev.emissions.items():
                    streamed[uid].extend(int(t) for t in toks)
            else:
                assert ev.emissions is None
        else:
            finals[ev[0]] = [int(t) for t in ev[1]]
    assert set(finals) == {0, 1, 2}
    for uid, toks in finals.items():
        assert streamed[uid] == toks, \
            f"uid={uid}: boundary emissions {streamed[uid]} != final {toks}"
    _assert_clean(eng)


# ----------------------------------------------------------------------
# threaded driver vs serial driver
# ----------------------------------------------------------------------

def _burst():
    yield [(u, PROMPTS[u]) for u in range(4)]
    yield []
    yield [(u, PROMPTS[u]) for u in range(4, 8)]


def test_threaded_driver_parity_with_serial(tiny_model_params):
    model, params = tiny_model_params
    ref = dict(EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)}
    ).serve(_burst(), max_new_tokens=MAX_NEW))
    router = EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)},
        RouterConfig(driver="threaded"))
    out = dict(router.serve(_burst(), max_new_tokens=MAX_NEW))
    assert set(out) == set(ref)
    for u in ref:
        assert np.array_equal(out[u], ref[u]), f"uid={u}"
    assert router.counters["completions"] == len(ref)
    for r in router._replicas.values():
        _assert_clean(r.engine)


def test_threaded_driver_kill_failover_parity(tiny_model_params):
    """A scripted engine_kill mid-run: in-flight requests fail over as
    resume arrivals and the fleet's outputs stay token-identical to a
    serial NO-failure run (the serial driver is the reference, per the
    ISSUE: threaded-driver kill parity vs the serial driver)."""
    model, params = tiny_model_params

    def arrivals():
        yield [(u, PROMPTS[u]) for u in range(6)]

    ref = dict(EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)}
    ).serve(arrivals(), max_new_tokens=48))
    router = EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)},
        RouterConfig(driver="threaded", quarantine_backoff_ticks=10 ** 9))
    faults = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 6, "engine": "a"}])
    out = dict(router.serve(arrivals(), max_new_tokens=48, faults=faults))
    assert faults.fired, "scripted kill never fired"
    assert router.counters["engine_kills"] == 1
    assert router.counters["failovers"] == 1
    assert router.replica_status()["a"] == "quarantined"
    assert set(out) == set(ref)
    for u in ref:
        assert np.array_equal(out[u], ref[u]), f"uid={u} diverged"


def test_threaded_driver_scheduler_path(tiny_model_params):
    """Scheduler-driven replicas under the threaded driver: metadata
    arrivals flow, outputs match the serial scheduler run."""
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    model, params = tiny_model_params

    def arrivals():
        yield [{"uid": u, "tokens": PROMPTS[u], "tenant": f"t{u % 2}",
                "priority": "interactive" if u % 2 else "batch"}
               for u in range(6)]

    mk_sched = lambda: RequestScheduler(SchedulerConfig())   # noqa: E731
    ref = dict(EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)}
    ).serve(arrivals(), max_new_tokens=MAX_NEW,
            scheduler_factory=mk_sched))
    out = dict(EngineRouter(
        {"a": _engine(model, params), "b": _engine(model, params)},
        RouterConfig(driver="threaded")
    ).serve(arrivals(), max_new_tokens=MAX_NEW,
            scheduler_factory=mk_sched))
    assert set(out) == set(ref)
    for u in ref:
        assert np.array_equal(out[u], ref[u])


# ----------------------------------------------------------------------
# HTTP/SSE edge
# ----------------------------------------------------------------------

def _sse_collect(host, port, body, timeout=120):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, resp.read().decode(), \
                dict(resp.getheaders())
        streamed, done, buf = [], None, b""
        while True:
            line = resp.readline()
            if not line:
                break
            buf += line
            if line != b"\n":
                continue
            ev, data = None, None
            for ln in buf.decode().strip().splitlines():
                if ln.startswith("event: "):
                    ev = ln[7:]
                elif ln.startswith("data: "):
                    data = json.loads(ln[6:])
            buf = b""
            if ev == "token":
                streamed.extend(data["tokens"])
            elif ev in ("done", "error"):
                done = (ev, data)
                break
        return 200, (streamed, done), {}
    finally:
        conn.close()


@pytest.fixture
def served_fleet(tiny_model_params):
    """A started 2-replica threaded fleet + edge; torn down after."""
    model, params = tiny_model_params
    router = EngineRouter({"a": _engine(model, params),
                           "b": _engine(model, params)})
    driver = FleetDriver(router)
    driver.start(max_new_tokens=MAX_NEW)
    edge = ServiceEdge(driver, EdgeConfig(keepalive_s=0.5)).start()
    yield router, driver, edge
    edge.shutdown()
    driver.stop()


def test_sse_stream_token_identical_to_direct_serve(tiny_model_params,
                                                    served_fleet):
    model, params = tiny_model_params
    _, _, edge = served_fleet
    eng = _engine(model, params)
    ref = {}
    for uid, toks in eng.serve(
            iter([[(u, PROMPTS[u]) for u in range(4)]]),
            max_new_tokens=MAX_NEW):
        ref[uid] = [int(t) for t in toks]

    outs = {}
    errs = []

    def client(u):
        status, payload, _ = _sse_collect(
            "127.0.0.1", edge.edge_port,
            {"prompt": [int(t) for t in PROMPTS[u]],
             "max_new_tokens": MAX_NEW, "session": f"s{u}"})
        if status != 200:
            errs.append((u, status, payload))
            return
        streamed, (kind, data) = payload
        if kind != "done":
            errs.append((u, kind, data))
            return
        outs[u] = (streamed, data["tokens"])

    threads = [threading.Thread(target=client, args=(u,))
               for u in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    for u in range(4):
        streamed, done = outs[u]
        assert streamed == done == ref[u], \
            f"uid={u}: streamed {streamed} vs direct {ref[u]}"
    # the handler thread increments AFTER writing the done event the
    # client just read — poll, don't race it
    assert _wait(lambda: edge.counters["completed"] == 4, timeout=10)


def test_client_disconnect_frees_slots_and_kv(served_fleet):
    """Drop the socket mid-stream: the cancel must travel
    edge -> driver -> engine.cancel_request -> deadline machinery, and
    every slot, ledger row, and KV block must come back (allocator
    refcount assert: free == total)."""
    router, driver, edge = served_fleet
    body = json.dumps({"prompt": [int(t) for t in PROMPTS[0]],
                       "max_new_tokens": 120}).encode()
    s = socket.create_connection(("127.0.0.1", edge.edge_port))
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    buf = b""
    while b"event: token" not in buf:
        chunk = s.recv(4096)
        assert chunk, f"stream ended early: {buf!r}"
        buf += chunk
    s.close()                        # client vanishes mid-stream

    engines = [r.engine for r in router._replicas.values()]
    assert _wait(lambda: all(not e._ledger for e in engines)
                 and all(e.kv.free_blocks == e.kv.num_blocks - 1
                         for e in engines)), \
        ("disconnect did not free serving state: "
         + str([(list(e._ledger),
                 e.kv.free_blocks, e.kv.num_blocks - 1) for e in engines]))
    assert _wait(lambda: driver.in_flight() == 0)
    assert edge.counters["disconnects"] == 1
    kinds = [f.kind for e in engines for f in e.fault_log]
    assert "cancelled" in kinds
    assert sum(e.telemetry.counters["cancelled"] for e in engines) == 1


def test_edge_sheds_429_with_retry_after(tiny_model_params):
    """Scripted overload against a one-slot edge budget: excess requests
    get 429 + a numeric Retry-After BEFORE any replica's scheduler sheds
    locally; a retry after the fleet drains succeeds."""
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    model, params = tiny_model_params
    router = EngineRouter({"a": _engine(model, params)})
    driver = FleetDriver(router)
    driver.start(max_new_tokens=MAX_NEW,
                 scheduler_factory=lambda: RequestScheduler(
                     SchedulerConfig(tenant_max_queued=16)))
    edge = ServiceEdge(driver, EdgeConfig(
        max_queued_tokens=24, retry_after_min_s=1.0)).start()
    try:
        # hold the fleet busy with slow work so pressure sustains
        hold_done = threading.Event()
        for i in range(6):
            driver.submit({"uid": 10_000 + i, "tokens": PROMPTS[i % 8],
                           "max_new_tokens": 64},
                          subscriber=lambda ev: (
                              hold_done.set()
                              if ev["type"] == "done" else None))
        assert _wait(lambda: driver.queued_tokens_estimate() > 24)
        status, bodytext, headers = _sse_collect(
            "127.0.0.1", edge.edge_port,
            {"prompt": [int(t) for t in PROMPTS[7]],
             "max_new_tokens": 4})
        assert status == 429, (status, bodytext)
        retry_after = headers.get("Retry-After")
        assert retry_after is not None and float(retry_after) >= 1
        payload = json.loads(bodytext)
        assert payload["error"] == "overloaded"
        assert payload["retry_after_s"] >= 1.0
        assert edge.counters["sheds"] == 1
        # the edge shed BEFORE any local scheduler shed
        assert all(r.engine.telemetry.counters["requests_shed"] == 0
                   for r in router._replicas.values())
        # capacity returns -> the retry is admitted and completes
        assert _wait(lambda: driver.in_flight() == 0, timeout=180)
        status, payload, _ = _sse_collect(
            "127.0.0.1", edge.edge_port,
            {"prompt": [int(t) for t in PROMPTS[7]],
             "max_new_tokens": 4})
        assert status == 200 and payload[1][0] == "done"
    finally:
        edge.shutdown()
        driver.stop()


def test_edge_rejects_malformed_requests(served_fleet):
    _, _, edge = served_fleet
    for bad in ({"prompt": []}, {"prompt": "text"}, {},
                {"prompt": [1, 2], "max_new_tokens": 0}):
        status, body, _ = _sse_collect("127.0.0.1", edge.edge_port, bad)
        assert status == 400, (bad, status, body)
    # unknown path
    conn = http.client.HTTPConnection("127.0.0.1", edge.edge_port,
                                      timeout=10)
    conn.request("GET", "/nope")
    assert conn.getresponse().status == 404
    conn.close()


def test_edge_metrics_and_health(served_fleet):
    _, _, edge = served_fleet
    conn = http.client.HTTPConnection("127.0.0.1", edge.edge_port,
                                      timeout=10)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    assert resp.status == 200
    assert "ds_edge_requests_total" in text
    assert "ds_edge_sheds_total" in text
    assert "ds_edge_streams_active" in text
    assert "ds_router_placements_total" in text
    assert "ds_router_scale_up_total" in text
    conn.request("GET", "/healthz")
    health = json.loads(conn.getresponse().read())
    assert set(health["replicas"]) == {"a", "b"}
    conn.close()


# ----------------------------------------------------------------------
# autoscaling
# ----------------------------------------------------------------------

def test_autoscale_flip_round_trip(tiny_model_params, tmp_path):
    """Prefill<->decode flip round trip: queued-prompt-token pressure
    flips a unified replica to prefill; once the backlog drains, the
    controller flips it back to its original role. Outputs stay
    token-identical to a direct serve throughout."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    engines = {}
    for n in ("r0", "r1"):
        e = _engine(model, params, max_tokens_per_step=2048)
        e.attach_kv_tier(tier, tag=n)
        engines[n] = e
    router = EngineRouter(engines)
    ctl = AutoscaleController(AutoscaleConfig(
        evaluate_every_s=0.1, sustain=2, min_live_replicas=1,
        flip_prefill_high=64, flip_dwell_s=1.0,
        scale_up_queued_tokens=10 ** 9))
    driver = FleetDriver(router, autoscaler=ctl)
    driver.start(max_new_tokens=4)
    results = {}
    lock = threading.Lock()

    def sub_for(uid):
        def sub(ev):
            if ev["type"] == "done":
                with lock:
                    results[uid] = ev["tokens"]
        return sub

    rng = np.random.default_rng(21)
    longs = {100 + i: [int(t) for t in rng.integers(0, 200, (96,))]
             for i in range(12)}
    try:
        for u, p in longs.items():
            driver.submit({"uid": u, "tokens": p, "max_new_tokens": 4},
                          sub_for(u))
        assert _wait(lambda: router.counters["scale_role_flips"] >= 1,
                     timeout=120), \
            f"no flip: events={ctl.events} " \
            f"queued={driver.queued_tokens_estimate()}"
        flipped = next(e["replica"] for e in ctl.events
                       if e["action"] == "role_flip")
        assert _wait(lambda: len(results) == len(longs), timeout=180), \
            f"only {len(results)}/{len(longs)} completed"
        # backlog drained -> the controller flips it back
        assert _wait(lambda: router._roles[flipped] == "unified",
                     timeout=60), \
            f"never flipped back: roles={dict(router._roles)} " \
            f"events={ctl.events}"
        assert router.counters["scale_role_flips"] >= 2
    finally:
        driver.stop()
    eng = _engine(model, params, max_tokens_per_step=2048)
    ref = {}
    for uid, toks in eng.serve(
            iter([[{"uid": u, "tokens": p, "max_new_tokens": 4}
                   for u, p in sorted(longs.items())]]),
            max_new_tokens=4):
        ref[uid] = [int(t) for t in toks]
    for u in longs:
        assert results[u] == ref[u], f"uid={u} diverged after flips"


def test_autoscale_scale_down_and_up(tiny_model_params):
    """Idle fleet drains a replica; a later backlog rejoins it."""
    model, params = tiny_model_params
    router = EngineRouter({"r0": _engine(model, params),
                           "r1": _engine(model, params)})
    ctl = AutoscaleController(AutoscaleConfig(
        evaluate_every_s=0.1, sustain=2, min_live_replicas=1,
        scale_up_queued_tokens=32, role_flip=False))
    driver = FleetDriver(router, autoscaler=ctl)
    driver.start(max_new_tokens=MAX_NEW)
    done = []
    try:
        driver.submit({"uid": 0, "tokens": [int(t) for t in PROMPTS[0]]},
                      subscriber=lambda ev: done.append(ev)
                      if ev["type"] == "done" else None)
        assert _wait(lambda: len(done) == 1, timeout=120)
        assert _wait(lambda: router.counters["scale_down"] >= 1,
                     timeout=60), f"no scale_down: {ctl.events}"
        assert "drained" in router.replica_status().values()
        # burst: oversubscribe the surviving replica so queued tokens
        # sustain past the watermark
        n_done = []
        for i in range(12):
            driver.submit(
                {"uid": 50 + i, "tokens": [int(t) for t in PROMPTS[i % 8]],
                 "max_new_tokens": 32},
                subscriber=lambda ev: n_done.append(ev)
                if ev["type"] == "done" else None)
        assert _wait(lambda: router.counters["scale_up"] >= 1,
                     timeout=120), \
            f"no scale_up: {ctl.events} " \
            f"queued={driver.queued_tokens_estimate()}"
        assert _wait(lambda: len(n_done) == 12, timeout=180)
    finally:
        driver.stop()
