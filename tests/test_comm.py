"""Comm layer tests over the virtual 8-device mesh.

Models reference tests/unit/comm/test_dist.py — but collectives run for real
over 8 XLA CPU devices instead of spawned NCCL processes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.comm as dist
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


def test_mesh_build_8dp(mesh_8dp):
    assert groups.get_world_size() == 8
    assert groups.get_data_parallel_world_size() == 8
    assert groups.get_model_parallel_world_size() == 1


def test_mesh_build_2x4(mesh_2x4):
    assert groups.get_data_parallel_world_size() == 2
    assert groups.get_model_parallel_world_size() == 4


def test_mesh_invalid():
    with pytest.raises(groups.MeshBuildError):
        groups.build_mesh(data=3, tensor=4)  # 12 != 8


def test_all_reduce(mesh_8dp):
    x = jnp.ones((16, 4))
    out = dist.all_reduce(x, op=dist.ReduceOp.SUM, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full((16, 4), 8.0))


def test_all_reduce_max(mesh_8dp):
    x = jnp.arange(8.0)
    out = dist.all_reduce(x, op=dist.ReduceOp.MAX, group="data")
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_all_gather_into_tensor(mesh_8dp):
    # tensor sharded over data axis on dim0 → gathered full on every device
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, groups.named_sharding("data"))
    out = dist.all_gather_into_tensor(xs, group="data")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_tensor(mesh_8dp):
    x = jnp.ones((16, 2))
    out = dist.reduce_scatter_tensor(x, group="data")
    assert out.shape == (16, 2)  # global view keeps shape; each shard holds sum
    np.testing.assert_allclose(np.asarray(out), np.full((16, 2), 8.0))


def test_all_to_all_single(mesh_8dp):
    x = jnp.arange(64.0).reshape(64, 1)
    xs = jax.device_put(x, groups.named_sharding("data"))
    out = dist.all_to_all_single(xs, scatter_dim=0, gather_dim=0, group="data")
    assert out.shape == (64, 1)
    # all_to_all twice = identity
    out2 = dist.all_to_all_single(out, scatter_dim=0, gather_dim=0, group="data")
    np.testing.assert_allclose(np.asarray(out2), np.asarray(x))


def test_barrier(mesh_8dp):
    dist.barrier()  # must not hang/throw


def test_in_trace_collectives(mesh_8dp):
    """psum/all_gather/psum_scatter inside shard_map (the hot-path API)."""
    from deepspeed_tpu.comm import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = groups.get_mesh()

    def body(x):
        s = dist.psum(x, "data")
        g = dist.all_gather(x, "data", axis=0, tiled=True)
        return s, g

    f = jax.jit(shard_map(body, mesh, (P("data"),), (P("data"), P())))
    x = jnp.arange(8.0).reshape(8, 1)
    s, g = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full((8, 1), 28.0))
    np.testing.assert_allclose(np.asarray(g), np.asarray(x))


def test_ring_send_recv(mesh_8dp):
    from deepspeed_tpu.comm import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = groups.get_mesh()

    f = jax.jit(shard_map(lambda x: dist.ring_send_recv(x, "data", shift=1),
                          mesh, (P("data"),), P("data")))
    x = jnp.arange(8.0).reshape(8, 1)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.roll(np.arange(8.0), 1))


def test_comms_logger(mesh_8dp):
    dist.configure(enabled=True, verbose=False)
    x = jnp.ones((128,))
    dist.all_reduce(x, group="data")
    summary = dist.log_summary()
    assert "all_reduce" in summary


def test_broadcast(mesh_8dp):
    x = jnp.full((4,), 3.0)
    out = dist.broadcast(x, src=0, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 3.0))


def test_topology_ranks():
    topo = groups.PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size() == 8
    assert topo.get_rank(pipe=0, data=0, model=0) == 0
    assert topo.get_dim("pipe") == 2
    lists = topo.get_axis_comm_lists("pipe")
    assert len(lists) == 4 and all(len(l) == 2 for l in lists)


def test_async_op_handles(mesh_8dp):
    """async_op=True returns a work handle whose wait() yields the result
    (reference handle contract; dispatch is already async under XLA)."""
    import deepspeed_tpu.comm as dist
    x = jnp.ones((64,))
    h = dist.all_reduce(x, async_op=True)
    assert hasattr(h, "wait")
    out = h.wait()
    np.testing.assert_allclose(np.asarray(out), 8.0)
    assert h.is_completed()


def test_coalescing_manager(mesh_8dp):
    """Collectives inside coalescing_manager batch into ONE backend call per
    kind and resolve through their handles (reference comm/torch.py:41)."""
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.comm import comm as comm_mod
    backend = comm_mod._ensure_backend()
    calls = {"n": 0}
    orig = backend.all_reduce

    def counting(tensor, **kw):
        calls["n"] += 1
        return orig(tensor, **kw)

    backend.all_reduce = counting
    try:
        xs = [jnp.full((n,), float(i + 1)) for i, n in enumerate((8, 16, 32))]
        with dist.coalescing_manager() as cm:
            handles = [dist.all_reduce(x) for x in xs]
        assert calls["n"] == 1          # one flat exchange
        for i, h in enumerate(handles):
            np.testing.assert_allclose(np.asarray(h.wait()), 8.0 * (i + 1))
    finally:
        backend.all_reduce = orig


def test_coalescing_manager_all_gather_shape(mesh_8dp):
    """Coalesced all_gather handles resolve to the same dim-0-tiled shape as
    the direct call."""
    import deepspeed_tpu.comm as dist
    x = jnp.arange(32.0).reshape(8, 4)
    direct = dist.all_gather_into_tensor(x)
    with dist.coalescing_manager():
        h = dist.all_gather_into_tensor(x)
    out = h.wait()
    assert out.shape == direct.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(direct))


def test_multiprocess_rendezvous_and_allreduce(tmp_path):
    """TRUE multi-process bring-up (SURVEY §4: multi-node simulated by
    multi-process on one host): two OS processes rendezvous through
    init_distributed (MASTER_ADDR/RANK/WORLD_SIZE contract, Gloo CPU
    backend) and a cross-process allreduce produces the global sum."""
    import os
    import subprocess
    import sys
    import textwrap

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import deepspeed_tpu.comm as dist
        import jax.numpy as jnp
        import numpy as np

        dist.init_distributed(verbose=False, distributed_port=29876)
        assert jax.process_count() == 2, jax.process_count()
        out = dist.all_reduce(jnp.ones((8,)) * (jax.process_index() + 1))
        val = float(np.asarray(out)[0])
        assert val == 3.0, val
        print("OK", jax.process_index())
    """) % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update(MASTER_ADDR="127.0.0.1", WORLD_SIZE="2", JAX_PLATFORMS="cpu")
    procs = []
    for r in range(2):
        e = dict(env, RANK=str(r))
        procs.append(subprocess.Popen([sys.executable, str(worker)], env=e,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT))
    for p in procs:
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0, out.decode()[-500:]
        assert b"OK" in out


# ---- sparse (row-wise) embedding-gradient allreduce (r5) -------------------

def test_sparse_embedding_allreduce_matches_psum():
    """The touched-rows all-gather exchange equals a dense psum, including
    duplicate token ids within and across ranks."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.runtime.comm.sparse import sparse_embedding_allreduce
    groups.reset_mesh()
    mesh = groups.set_mesh(groups.build_mesh(data=8))
    V, E, N = 64, 16, 24
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (8, N)), jnp.int32)
    # per-rank dense grads that are sparse BY CONSTRUCTION: scatter-adds of
    # random rows at the rank's token ids (an embedding lookup's vjp)
    rows = jnp.asarray(rng.normal(size=(8, N, E)), jnp.float32)
    dense = jax.vmap(lambda i, r: jnp.zeros((V, E)).at[i].add(r))(ids, rows)

    def body(g, i):
        return (sparse_embedding_allreduce(g[0], i[0], "data"),
                jax.lax.psum(g[0], "data"))

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("data"), P("data")),
                       out_specs=(P(), P()), axis_names=set(mesh.shape),
                       check_vma=False)
    got, want = fn(dense, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_sparse_gradients_engine_matches_dense():
    """config sparse_gradients=true (reference engine.py:2518): training
    trajectory equals the dense fused step, and the compiled step's
    collectives move rows, not the (V, E) table."""
    def run(sparse):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=8))
        model = build_model("tiny", tie_embeddings=False, vocab_size=2048)
        cfg = {
            "train_batch_size": 16, "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "sparse_gradients": sparse,
            "steps_per_print": 10 ** 9, "seed": 9,
        }
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(3):
            ids = rng.integers(0, 2048, (16, 32))
            losses.append(float(engine.train_batch({"input_ids": ids,
                                                    "labels": ids})))
        return losses, engine

    dense_losses, _ = run(False)
    sparse_losses, engine = run(True)
    assert engine._sparse_grads
    np.testing.assert_allclose(dense_losses, sparse_losses,
                               rtol=2e-4, atol=2e-4)

    # comm-volume: the sparse grad program all-reduces no (V, E)-sized
    # operand; the table's rows travel as (N, E) all-gathers
    import re
    batch = {"input_ids": np.zeros((2, 8, 32), np.int64),
             "labels": np.zeros((2, 8, 32), np.int64)}
    batch = jax.tree.map(engine._stage_leaf, batch)
    hlo = engine._sparse_grad_fn.lower(
        engine.module_params, batch, gas=2).compile().as_text()
    table_reduces = [ln for ln in hlo.splitlines()
                     if "all-reduce" in ln and re.search(r"f32\[2048,\d+", ln)]
    assert not table_reduces, table_reduces[:2]
    assert "all-gather" in hlo
