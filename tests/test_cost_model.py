"""graft-cost (analysis Family C): the static jaxpr cost model's own suite.

Three layers, mirroring ``test_static_analysis.py``:

1. **Golden-value units** — the counting rules of ``cost_model`` pinned on
   hand-built jaxprs with exact expected numbers: a single ``dot_general``
   (FLOPs + HBM bytes), a ``psum`` on the 8-way mesh (ring wire bytes),
   and a 2-trip ``scan`` (consts charged once per frame, carries per
   step). Change a counting rule and these fail loudly with the arithmetic
   in front of you.
2. **Rule fixtures** — GL204 fires on the duplicated-psum /
   double-reduce / gather-then-reduce fixtures and stays silent on the
   clean twin; GL202/GL201/GL203 are exercised on synthetic reports and a
   doctored baseline, including the CLI exiting 1 on a cost regression.
3. **The repo gate** — every registered serving program (tp=1 AND tp=8,
   quantized and ring twins included) measures into a CostReport, the
   committed ``.graft-cost-baseline.json`` matches, the quantized program
   provably moves <= 0.5x the exact program's wire bytes, and the ring
   program moves EXACTLY the exact program's wire bytes.
"""

import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis import cost_model as C
from deepspeed_tpu.analysis.ast_checks import DISPATCH_DONATIONS
from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "deepspeed_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "graft_lint")
COST_BASELINE = os.path.join(ROOT, ".graft-cost-baseline.json")


def _fixture(name):
    spec = importlib.util.spec_from_file_location(
        f"graft_cost_fixture_{name}", os.path.join(FIXTURES, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _measure(fn, *args):
    return C.measure_jaxpr(jax.make_jaxpr(fn)(*args))


# ---------------------------------------------------------------------------
# golden-value units: the counting rules, with the arithmetic spelled out
# ---------------------------------------------------------------------------


def test_dot_general_flops_and_hbm_golden():
    a = jnp.ones((4, 8), jnp.float32)
    b = jnp.ones((8, 16), jnp.float32)
    m = _measure(jnp.dot, a, b)
    assert m.flops == 2 * 4 * 16 * 8                 # 2 x M x N x K = 1024
    assert m.hbm_read == (4 * 8 + 8 * 16) * 4        # operands once = 640
    assert m.hbm_write == 4 * 16 * 4                 # result once = 256
    assert m.coll_payload == {} and m.unbounded_loops == 0


def test_batched_dot_general_flops_golden():
    a = jnp.ones((2, 4, 8), jnp.float32)
    b = jnp.ones((2, 8, 16), jnp.float32)
    m = _measure(lambda x, y: jnp.einsum("bmk,bkn->bmn", x, y), a, b)
    assert m.flops == 2 * 2 * 4 * 16 * 8             # batch dim multiplies


def test_psum_ring_wire_bytes_golden():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))
    mapped = shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                       in_specs=P(), out_specs=P(), check_rep=False)
    m = C.measure_jaxpr(jax.make_jaxpr(mapped)(jnp.ones((16,), jnp.float32)))
    # ring all-reduce: each device sends 2(N-1)/N x operand bytes
    assert m.coll_payload == {"tp": 2 * 7 / 8 * 64}  # = 112.0
    assert m.coll_ops == {"tp": 1}
    assert m.payload_by_dtype == {"float32": 112.0}


def test_all_gather_wire_bytes_golden():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))
    mapped = shard_map(
        lambda x: jax.lax.all_gather(x, "tp", axis=0, tiled=True),
        mesh=mesh, in_specs=P("tp"), out_specs=P(), check_rep=False)
    m = C.measure_jaxpr(jax.make_jaxpr(mapped)(jnp.ones((8, 4), jnp.float32)))
    # each device forwards its (1, 4) f32 shard to the N-1 others
    assert m.coll_payload == {"tp": 7 * 16}


def test_scan_consts_once_carries_per_step_golden():
    """THE scan-carry analysis: a 2-trip scan charges its const (the param
    analog) ONCE per frame and its carry (the KV-pool analog) per step."""
    w = jnp.ones((4, 4), jnp.float32)
    c0 = jnp.ones((4, 4), jnp.float32)

    def f(w, c0):
        return jax.lax.scan(lambda c, _: (jnp.dot(c, w), None), c0, None,
                            length=2)

    m = _measure(f, w, c0)
    assert m.flops == 2 * (2 * 4 * 4 * 4)            # one matmul per trip
    # read: w once (64B, scan const) + carry per trip (2 x 64B) = 192
    assert m.hbm_read == 64 + 2 * 64
    assert m.hbm_write == 2 * 64                     # carry written per trip


def test_while_loop_flagged_unbounded():
    def f(x):
        return jax.lax.while_loop(lambda c: c[0, 0] < 3.0,
                                  lambda c: c + 1.0, x)
    m = _measure(f, jnp.zeros((2, 2), jnp.float32))
    assert m.unbounded_loops == 1


# ---------------------------------------------------------------------------
# GL204 fixtures
# ---------------------------------------------------------------------------


def test_gl204_fires_on_duplicated_psum():
    got = C.check_redundant_collectives(_fixture("bad_cost").dup_psum())
    assert [f.rule for f in got] == ["GL204"]
    assert "psummed twice" in got[0].message


def test_gl204_fires_on_double_reduction():
    got = C.check_redundant_collectives(_fixture("bad_cost").double_reduce())
    assert [f.rule for f in got] == ["GL204"]
    assert "already replica-invariant" in got[0].message


def test_gl204_fires_on_gather_then_reduce():
    got = C.check_redundant_collectives(
        _fixture("bad_cost").gather_then_reduce())
    assert [f.rule for f in got] == ["GL204"]
    assert "summed straight back down" in got[0].message


def test_gl204_clean_negative():
    assert C.check_redundant_collectives(_fixture("bad_cost").clean()) == []


# ---------------------------------------------------------------------------
# GL201 / GL202 / GL203 on synthetic reports
# ---------------------------------------------------------------------------


def _report(name, variant="exact", counterpart="", **over):
    base = dict(flops=1000, hbm_read=2000, hbm_write=1000, d2h_bytes=64,
                coll_ops={"tp": 4}, coll_payload={"tp": 1000},
                payload_by_dtype={"float32": 1000})
    base.update(over)
    return C.CostReport(name=name, variant=variant, counterpart=counterpart,
                        **base)


def test_gl201_flags_drift_in_both_directions(tmp_path):
    r = _report("frame_loop[w=1]")
    path = str(tmp_path / "cost.json")
    C.write_cost_baseline(path, [r])
    base = C.load_cost_baseline(path)
    assert C.check_cost_baseline([r], base) == []
    grown = dataclasses.replace(r, flops=1100)
    got = C.check_cost_baseline([grown], base)
    assert [f.rule for f in got] == ["GL201"] and "grew" in got[0].message
    shrunk = dataclasses.replace(r, flops=900)
    got = C.check_cost_baseline([shrunk], base)
    assert [f.rule for f in got] == ["GL201"] and "shrank" in got[0].message
    within = dataclasses.replace(r, flops=1010)    # 1% < 2% tolerance
    assert C.check_cost_baseline([within], base) == []


def test_gl201_flags_missing_and_stale_programs(tmp_path):
    r = _report("frame_loop[w=1]")
    path = str(tmp_path / "cost.json")
    C.write_cost_baseline(path, [r])
    base = C.load_cost_baseline(path)
    got = C.check_cost_baseline([r, _report("new_loop")], base)
    assert [f.rule for f in got] == ["GL201"]
    assert "no cost-baseline entry" in got[0].message
    got = C.check_cost_baseline([], base)
    assert "stale" in got[0].message
    # tp entries are legitimately absent from a --no-tp run
    C.write_cost_baseline(path, [r, _report("frame_loop[w=1][tp=8]")])
    base = C.load_cost_baseline(path)
    assert C.check_cost_baseline([r], base, include_tp=False) == []


def test_gl202_quantized_contract_synthetic():
    exact = _report("frame_loop[w=1][tp=8]")
    good = _report("frame_loop[w=1][tp=8,quant]", variant="quantized",
                   counterpart="frame_loop[w=1][tp=8]",
                   coll_payload={"tp": 450},
                   payload_by_dtype={"int8": 300, "float32": 150})
    assert C.check_collective_contracts([exact, good]) == []
    # int8 above half the exact total: the claim is broken
    fat = dataclasses.replace(good, coll_payload={"tp": 800},
                              payload_by_dtype={"int8": 700,
                                                "float32": 100})
    got = C.check_collective_contracts([exact, fat])
    assert [f.rule for f in got] == ["GL202"]
    assert "exceed 0.5x" in got[0].message
    # int8 wire absent entirely: the flag is dead
    dead = dataclasses.replace(good, payload_by_dtype={"float32": 450})
    got = C.check_collective_contracts([exact, dead])
    assert any("no int8 payload" in f.message for f in got)
    # no counterpart in the registry: loud, not vacuous
    got = C.check_collective_contracts([good])
    assert any("no exact counterpart" in f.message for f in got)


def test_gl202_overlap_contract_synthetic():
    exact = _report("frame_loop[w=1][tp=8]")
    ring = _report("frame_loop[w=1][tp=8,ring]", variant="overlap",
                   counterpart="frame_loop[w=1][tp=8]",
                   coll_ops={"tp": 15})
    assert C.check_collective_contracts([exact, ring]) == []
    short = dataclasses.replace(ring, coll_payload={"tp": 875})
    got = C.check_collective_contracts([exact, short])
    assert [f.rule for f in got] == ["GL202"]
    assert "chunking bug" in got[0].message


def _frame_like_program(cached_shape):
    """A 12-output program shaped like frame_loop's return tuple, with the
    ``cached`` output (host-read index 2) at an arbitrary shape."""
    b = 4

    def f(x):
        toks = jnp.zeros((2, b), jnp.int32)
        emit = jnp.zeros((2, b), bool)
        cached = jnp.zeros(cached_shape, jnp.int32)
        row_i = jnp.zeros((b,), jnp.int32)
        row_b = jnp.zeros((b,), bool)
        stats = jnp.zeros((7,), jnp.int32)
        return (toks, emit, cached, row_i, row_i, row_b, row_b, row_b,
                stats, x, x, x)

    def trace():
        return jax.make_jaxpr(f)(jnp.zeros((2,), jnp.uint32))

    return TracedProgram(name="frame_loop[w=1]", trace=trace, retrace=trace)


def test_gl203_bounds_boundary_reads_to_the_batch():
    ok = _frame_like_program((4,))
    rep = C.measure_program(ok)
    assert C.check_d2h_budget(rep, ok) == []
    # a host-read output that scales with sequence length blows the budget
    bad = _frame_like_program((4, 4096))
    rep = C.measure_program(bad)
    got = C.check_d2h_budget(rep, bad)
    assert [f.rule for f in got] == ["GL203"]
    assert "boundary budget" in got[0].message


def test_gl203_detects_host_read_table_drift():
    b = 4

    def f(x):
        return (jnp.zeros((2, b), jnp.int32),)      # 1 output, table wants 9

    prog = TracedProgram(name="frame_loop[w=1]",
                         trace=lambda: jax.make_jaxpr(f)(jnp.zeros((2,))),
                         retrace=None)
    rep = C.measure_program(prog)
    got = C.check_d2h_budget(rep, prog)
    assert [f.rule for f in got] == ["GL203"]
    assert "table drifted" in got[0].message


# ---------------------------------------------------------------------------
# the repo gate: every registered program, against the committed baseline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cost_programs():
    from deepspeed_tpu.analysis.programs import build_cost_programs
    return build_cost_programs(include_tp=True)


@pytest.fixture(scope="module")
def cost_reports(cost_programs):
    return [C.measure_program(p) for p in cost_programs]


def test_every_registered_program_measures(cost_reports):
    """Acceptance: the cost table has a row — FLOPs, HBM bytes, collective
    payload, D2H bytes — for every registered serving program at tp=1 AND
    tp=8, and the measurement itself is deterministic."""
    assert all(r is not None for r in cost_reports)
    names = {r.name for r in cost_reports}
    for base in ("frame_loop[w=1]", "frame_loop[w=8]", "frame_loop_spec[w=1]",
                 "frame_loop_spec[w=8]", "mixed_loop", "mixed_loop_spec"):
        assert base in names and f"{base}[tp=8]" in names, base
    for r in cost_reports:
        assert r.hbm_read > 0 and r.hbm_write > 0
        assert r.unbounded_loops == 0, (r.name, "while_loop in a frame?")
        if "[tp=8" in r.name:
            assert r.total_payload > 0, (r.name, "tp program, no wire bytes")


def test_cost_registry_covers_every_dispatch_site(cost_programs):
    """Family C coverage completeness: every runner entry point with a
    donation contract (= every dispatch site) is cost-measured too, so a
    new serving loop cannot skip the ledger."""
    bases = {p.name.split("[")[0] for p in cost_programs}
    missing = {k for k in DISPATCH_DONATIONS if k not in bases}
    assert not missing, f"dispatch sites with no cost coverage: {missing}"


def test_host_read_table_matches_live_traces(cost_programs):
    """HOST_READ_OUTPUTS honesty (the GL203 analog of the donation-table
    cross-check): the indices resolve on every live trace, and the
    emission stream leads the outputs with the (steps, B[, gamma+1])
    shapes the budget formula assumes."""
    from deepspeed_tpu.analysis.jaxpr_checks import _closed
    checked = set()
    for prog in cost_programs:
        base = prog.name.split("[")[0]
        if base not in C.HOST_READ_OUTPUTS:
            continue
        checked.add(base)
        outs = list(_closed(prog.traced()).out_avals)
        reads = C.HOST_READ_OUTPUTS[base]
        assert all(i < len(outs) for i in reads), (prog.name, reads)
        if base in C.D2H_BUDGET_SCOPE:
            toks = outs[0]
            # (steps, B[, gamma+1]): 2 frame steps, or 1+2 mixed steps
            assert toks.shape[0] in (2, 3) and len(toks.shape) in (2, 3), \
                prog.name
            for i in reads:
                # every boundary lane beyond the stream is O(batch)-small
                if i > 1:
                    assert C._aval_bytes(outs[i]) <= 64 * toks.shape[1], \
                        (prog.name, i)
    assert checked == set(C.HOST_READ_OUTPUTS), (
        f"untraced HOST_READ_OUTPUTS entries: "
        f"{set(C.HOST_READ_OUTPUTS) - checked}")


def test_repo_cost_gate_clean(cost_programs):
    """THE acceptance gate: Family C over the full registry vs the
    committed baseline — zero findings, with GL202 proving the int8 path
    <= 0.5x and the ring path == 1.0x of the exact wire bytes."""
    baseline = C.load_cost_baseline(COST_BASELINE)
    findings, reports = C.run_cost_checks(cost_programs, baseline=baseline)
    assert not findings, "graft-cost findings:\n" + "\n".join(
        f.render() for f in findings)
    by_name = {r.name: r for r in reports}
    quant = [r for r in reports if r.variant == "quantized"]
    ring = [r for r in reports if r.variant == "overlap"]
    assert quant and ring, "variant twins missing from the cost registry"
    for r in quant:
        exact = by_name[r.counterpart]
        assert 0 < r.int8_payload <= 0.5 * exact.total_payload, (
            r.name, r.int8_payload, exact.total_payload)
        assert r.total_payload < exact.total_payload
    for r in ring:
        exact = by_name[r.counterpart]
        assert r.total_payload == exact.total_payload, (
            r.name, r.total_payload, exact.total_payload)
        # the ring IS chunked: 2(N-1) ppermute hops replace each psum
        assert sum(r.coll_ops.values()) > sum(exact.coll_ops.values())


def test_cost_report_table_lists_every_program(cost_reports):
    table = C.render_cost_table(cost_reports)
    for r in cost_reports:
        assert r.name in table
    header = table.splitlines()[0]
    for col in ("flops", "hbm_read", "hbm_write", "coll_payload",
                "d2h_bytes"):
        assert col in header


def test_cli_exits_1_on_cost_regression(tmp_path, cost_reports, capsys):
    """Acceptance: GL201 exits 1 when a program's cost regresses beyond
    tolerance. Runs the real CLI main() against a doctored baseline whose
    frame_loop[w=1] flops claim is 10% below the live trace (scoped
    --no-tp so only the tp=1 engine re-traces)."""
    from deepspeed_tpu.analysis.lint import main
    doctored = {r.name: r.metrics() for r in cost_reports
                if "[tp=8" not in r.name}
    doctored["frame_loop[w=1]"] = dict(doctored["frame_loop[w=1]"],
                                       flops=int(
        doctored["frame_loop[w=1]"]["flops"] * 0.9))
    path = tmp_path / "cost.json"
    path.write_text(json.dumps({"version": C.COST_BASELINE_VERSION,
                                "tolerance": 0.02,
                                "programs": doctored}))
    scan = tmp_path / "empty.py"
    scan.write_text("")
    rc = main(["--no-tp", "--cost-baseline", str(path), str(scan)])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "GL201" in out and "flops grew" in out
    assert "frame_loop[w=1]" in out
