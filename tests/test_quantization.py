"""Quantization kernel + quantized collective tests (reference pattern:
tests/unit/ops/quantizer, tests/unit/runtime/comm)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.pallas.quantizer import (dequantize_int4, dequantize_int8,
                                                quantize_int4, quantize_int8)
from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_flat
from deepspeed_tpu.ops.pallas.grouped_gemm import grouped_gemm
from deepspeed_tpu.runtime.comm.coalesced_collectives import (
    quantized_all_gather, quantized_reduce_scatter, reduce_scatter_coalesced)
from deepspeed_tpu.utils import groups


def test_int8_roundtrip(rng):
    x = jax.random.normal(rng, (64, 256))
    q, s = quantize_int8(x, group_size=256)
    back = dequantize_int8(q, s, group_size=256)
    err = jnp.max(jnp.abs(back - x))
    # max error bounded by scale/2 per group
    assert float(err) <= float(jnp.max(s)) * 0.51, (float(err), float(jnp.max(s)))


def test_int4_roundtrip(rng):
    x = jax.random.normal(rng, (16, 256))
    packed, s, shape = quantize_int4(x, group_size=256)
    assert packed.shape[-1] == 128  # two nibbles per byte
    back = dequantize_int4(packed, s, shape, group_size=256)
    err = jnp.max(jnp.abs(back - x))
    assert float(err) <= float(jnp.max(s)) * 0.51


def test_quantized_reduce_scatter_close_to_exact(mesh_8dp, rng):
    mesh = groups.get_mesh()
    x = jax.random.normal(rng, (8, 2048))

    def body(x):
        return quantized_reduce_scatter(x[0], "data")

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                       axis_names={"data"}, check_vma=True)
    got = np.asarray(fn(x)).reshape(-1)
    exact = np.asarray(jnp.sum(x, axis=0))
    # int8 quantization error accumulates over 8 ranks; tolerance ~ 8 * scale/2
    scale_bound = float(jnp.max(jnp.abs(x))) / 127
    np.testing.assert_allclose(got, exact, atol=8 * scale_bound * 0.6)


def test_quantized_all_gather(mesh_8dp, rng):
    mesh = groups.get_mesh()
    x = jax.random.normal(rng, (8, 256))

    def body(shard):
        # leading axis collects each rank's gathered copy
        return quantized_all_gather(shard[0], "data").reshape(1, 8, 256)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                       axis_names={"data"}, check_vma=True)
    got = np.asarray(fn(x))                     # (8 ranks, 8, 256)
    scale_bound = float(jnp.max(jnp.abs(x))) / 127
    for r in range(8):
        np.testing.assert_allclose(got[r], np.asarray(x), atol=scale_bound * 0.6)


def test_reduce_scatter_coalesced(mesh_8dp, rng):
    mesh = groups.get_mesh()
    a = jax.random.normal(rng, (8, 64))
    b = jax.random.normal(jax.random.fold_in(rng, 1), (8, 32))

    def body(a, b):
        reduced, sizes = reduce_scatter_coalesced([a[0], b[0]], "data")
        return reduced

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("data"), P("data")),
                       out_specs=P("data"), axis_names={"data"}, check_vma=True)
    got = np.asarray(fn(a, b)).reshape(-1)
    exact = np.concatenate([np.asarray(jnp.sum(a, 0)), np.asarray(jnp.sum(b, 0))])
    np.testing.assert_allclose(got[:96], exact, atol=1e-5)


def test_fused_adam_flat_matches_optimizer(rng):
    from deepspeed_tpu.ops.optimizers import FusedAdam
    n = 1024
    p = jax.random.normal(rng, (n,))
    g = jax.random.normal(jax.random.fold_in(rng, 1), (n,))
    m = jnp.zeros((n,))
    v = jnp.zeros((n,))
    new_p, new_m, new_v = fused_adam_flat(p, g, m, v, step=1, lr=1e-2, weight_decay=0.01)

    opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    params = {"x": p}
    state = opt.init(params)
    ref, _ = opt.apply({"x": g}, state, params)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(ref["x"]), atol=1e-6)


def test_grouped_gemm_matches_dense(rng):
    t, x, e, f = 32, 4, 16, 24
    tokens = jax.random.normal(rng, (t, e))
    w = jax.random.normal(jax.random.fold_in(rng, 1), (x, e, f))
    sizes = jnp.asarray([8, 8, 8, 8])
    out = grouped_gemm(tokens, w, sizes)
    want = jnp.concatenate([tokens[i * 8:(i + 1) * 8] @ w[i] for i in range(4)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_fp8_roundtrip(rng):
    from deepspeed_tpu.ops.pallas.fp_quantizer import dequantize_fp8, quantize_fp8
    x = jax.random.normal(rng, (8, 256))
    q, s = quantize_fp8(x, group_size=256, stochastic=False)
    back = dequantize_fp8(q, s, group_size=256)
    # e4m3 has ~2 decimal digits; relative error bounded by ~6%
    rel = jnp.max(jnp.abs(back - x) / (jnp.abs(x) + 1e-3))
    assert float(rel) < 0.13, float(rel)
