"""Multi-engine router chaos suite: placement, failover, drain.

Deterministic throughout — placement is consistent hashing + a pure
least-loaded score with name tie-breaks, failure is driven by the scripted
``RouterFaultInjector`` (tick-keyed, no wall clocks), and backoffs are in
router ticks — so every scenario pins exact outputs:

* kill-one-of-two mid-stream: every accepted request completes and greedy
  outputs are token-identical to the no-failure run (the failed engine's
  snapshot splits per-request and re-admits on the healthy peer as resume
  arrivals), including across heterogeneous TP degrees (tp=1 <-> tp=8);
* graceful drain: placement stops, live rows finish, the held queue
  migrates via snapshot — token parity again;
* a flapping replica is quarantined with exponential tick backoff and
  bounded per-request re-routes: capacity degrades, availability does not;
* affinity stickiness and least-loaded placement determinism.

Engines are module-scoped and REUSED across router instances (a completed
or failed-over serve leaves the engine clean — the abandonment/ledger
cleanup contract the fault suite pins), so the suite compiles each frame
program once.
"""

import numpy as np
import jax
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig,
                                                  ServeBoundary)
from deepspeed_tpu.inference.v2.faults import (RouterFaultInjector,
                                               RouterFaultSpec,
                                               snapshot_split)
from deepspeed_tpu.inference.v2.router import (CLOSED, DEAD, DRAINED,
                                               HEALTHY, QUARANTINED,
                                               EngineRouter, RouterConfig,
                                               placement_score)
from deepspeed_tpu.inference.v2.scheduler import RequestScheduler
from deepspeed_tpu.models import build_model

pytestmark = pytest.mark.chaos

MAX_NEW = 8


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


@pytest.fixture(scope="module")
def tiny_model_params():
    # 8 heads: the tp=8 replica's sharded axes divide the virtual mesh
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, max_seq_len=128, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=max_seq_len)


@pytest.fixture(scope="module")
def engine_pool(tiny_model_params):
    """Module-scoped engines, reused across routers (compile once)."""
    model, params = tiny_model_params
    return {
        "a": _engine(model, params),
        "b": _engine(model, params),
        "tp8": _engine(model, params, tp=8),
    }


PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (200,))
           .astype(np.int32)[o:o + n]
           for u, (o, n) in enumerate(((0, 7), (10, 24), (40, 33), (80, 5),
                                       (120, 18), (150, 11)))}
SCHEDULE = {0: [0, 1], 2: [2], 3: [3], 4: [4, 5]}


def _arrivals(schedule=None, session=None, max_new=None):
    schedule = SCHEDULE if schedule is None else schedule
    for k in range(max(schedule) + 2):
        batch = []
        for u in schedule.get(k, []):
            if session is None:
                batch.append((u, PROMPTS[u]))
            else:
                item = {"uid": u, "tokens": PROMPTS[u], "session": session}
                if max_new is not None:
                    item["max_new_tokens"] = max_new
                batch.append(item)
        yield batch


@pytest.fixture(scope="module")
def greedy_base(engine_pool):
    """Single-engine no-failure outputs — THE reference every router
    scenario's completions must match token-for-token."""
    return dict(engine_pool["a"].serve(_arrivals(), max_new_tokens=MAX_NEW))


def _assert_clean(eng):
    assert eng.kv.free_blocks == eng.kv.num_blocks - 1
    assert not eng.state.seqs
    assert not eng._ledger


def _assert_parity(outs, base, uids=None):
    uids = set(base) if uids is None else set(uids)
    assert set(outs) >= uids
    for u in uids:
        assert np.array_equal(outs[u], base[u]), \
            f"uid={u}: {outs[u]} != {base[u]}"


# ---------------------------------------------------------------------------
# placement units (no engines served)
# ---------------------------------------------------------------------------


def test_placement_score_pure_and_monotone():
    idle = placement_score(0, 0, 8, 1.0, None, 1000.0)
    busy = placement_score(4, 8, 8, 0.2, 1500.0, 1000.0)
    assert idle < busy
    # deterministic: same inputs, same score
    assert busy == placement_score(4, 8, 8, 0.2, 1500.0, 1000.0)


def test_least_loaded_placement_determinism(engine_pool):
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    # equal load: tie breaks by name, repeatably
    assert all(router._least_loaded(
        {n: router._replicas[n] for n in ("a", "b")}) == "a"
        for _ in range(5))
    # loading a's feed flips the choice
    router._replicas["a"].feed.extend([(90, PROMPTS[0]), (91, PROMPTS[1])])
    assert router._least_loaded(
        {n: router._replicas[n] for n in ("a", "b")}) == "b"
    router._replicas["a"].feed.clear()


def test_affinity_stickiness(engine_pool):
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    # one session key always lands on the same replica
    picks = {router._pick("session-42") for _ in range(10)}
    assert len(picks) == 1
    # the keyspace as a whole spreads over both replicas
    spread = {router._pick(f"s{i}") for i in range(64)}
    assert spread == {"a", "b"}
    # a quarantined affinity target falls over to the healthy peer,
    # deterministically
    target = router._pick("session-42")
    router._replicas[target].status = QUARANTINED
    other = ({"a", "b"} - {target}).pop()
    assert router._pick("session-42") == other
    router._replicas[target].status = HEALTHY


def test_heartbeat_threshold_unit(engine_pool):
    # the gap charged to a replica is its OWN frame time (boundary t minus
    # the step start the router recorded), NOT boundary-to-boundary wall
    # clock — in the serial stepping loop the latter would include every
    # peer's frame time and a single slow replica would cascade the whole
    # fleet into quarantine
    cfg = RouterConfig(heartbeat_timeout_s=1.0, max_missed_heartbeats=2)
    router = EngineRouter({"a": engine_pool["a"]}, cfg)
    r = router._replicas["a"]

    def beat(step_t0, t, dispatched=True):
        return router._note_heartbeat(r, ServeBoundary(
            index=0, dispatched=dispatched, live=1, queued=0, free_slots=7,
            t=t), tick=0, step_t0=step_t0)

    assert beat(0.0, 0.5) is None        # own frame within budget
    assert beat(2.0, 4.0) is None        # miss 1 (2s own frame)
    assert r.missed_heartbeats == 1
    assert beat(4.0, 4.5) is None        # healthy frame resets
    assert r.missed_heartbeats == 0
    beat(5.0, 7.0)                       # miss 1
    detail = beat(7.0, 9.0)              # miss 2 -> threshold
    assert detail is not None and "heartbeat" in detail
    assert router.counters["heartbeat_misses"] == 3
    # a slow PEER tick between this replica's boundaries never counts:
    # 10s elapse before the router steps it again, but its own frame is
    # fast — no miss, and the consecutive-miss counter resets
    r.missed_heartbeats = 1
    assert beat(19.0, 19.2) is None
    assert r.missed_heartbeats == 0
    # missing step_t0 (first step after construction/rejoin) never counts
    assert beat(None, 99.0) is None
    assert r.missed_heartbeats == 0


def test_snapshot_split_resume_arrivals():
    snap = {"version": 1, "requests": [
        {"uid": 7, "prompt": [1, 2, 3], "generated": [9, 8], "limit": 6,
         "temp": 0.0, "eos": None, "deadline_remaining_ms": 0.0,
         "tenant": "t0", "priority": "batch", "slo_ms": None,
         "swapped_tokens": None},
    ]}
    (item,) = snapshot_split(snap)
    assert item["uid"] == 7 and item["generated"] == [9, 8]
    assert item["max_new_tokens"] == 6 and item["tokens"] == [1, 2, 3]
    assert item["eos_token_id"] == -1          # resolved no-EOS, explicit
    assert item["deadline_ms"] > 0             # expired -> epsilon, not None
    assert item["tenant"] == "t0" and item["priority"] == "batch"
    with pytest.raises(ValueError, match="version"):
        snapshot_split({"version": 2})


def test_router_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown router fault kind"):
        RouterFaultSpec(kind="meteor", tick=0, engine="a")
    with pytest.raises(ValueError, match="tick"):
        RouterFaultSpec(kind="engine_kill", tick=-1, engine="a")


# ---------------------------------------------------------------------------
# serving scenarios
# ---------------------------------------------------------------------------


def test_router_no_failure_parity(engine_pool, greedy_base):
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    outs = dict(router.serve(_arrivals(), max_new_tokens=MAX_NEW))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["placements"] == len(PROMPTS)
    assert st["counters"]["failovers"] == 0
    assert st["counters"]["completions"] == len(PROMPTS)
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_kill_one_of_two_midstream_parity(engine_pool, greedy_base):
    """The acceptance scenario: two replicas, all requests pinned to one by
    session affinity, that replica killed mid-stream — every request
    completes on the survivor, token-identical to the no-failure run."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          RouterConfig(quarantine_backoff_ticks=64))
    victim = router._pick("pinned")
    survivor = ({"a", "b"} - {victim}).pop()
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 3, "engine": victim}])
    outs = dict(router.serve(_arrivals(session="pinned"),
                             max_new_tokens=MAX_NEW, faults=inj))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["engine_kills"] == 1
    assert st["counters"]["failovers"] == 1
    assert st["counters"]["reroutes"] >= 1
    assert st["counters"]["requests_failed"] == 0
    assert st["replicas"][victim] == QUARANTINED
    assert st["replicas"][survivor] in (HEALTHY, CLOSED)
    assert router.last_recovery_ms >= 0.0
    assert any(f.kind == "engine_kill" for f in router.fault_log)
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


@pytest.mark.multichip
def test_kill_heterogeneous_tp_parity(engine_pool, greedy_base):
    """Failover ACROSS TP degrees: everything pinned to the tp=8 replica,
    which is killed mid-stream; the tp=1 peer resumes every in-flight
    request token-identically (the snapshot is engine-shape-agnostic), and
    vice versa is covered by the snapshot resume tests in
    tests/test_serving_tp.py."""
    router = EngineRouter({"a": engine_pool["a"], "tp8": engine_pool["tp8"]},
                          RouterConfig(quarantine_backoff_ticks=64))
    # pin to the tp=8 replica regardless of ring layout: find a session
    # key that hashes onto it (deterministic search)
    key = next(f"sess{i}" for i in range(256)
               if router._pick(f"sess{i}") == "tp8")
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 3, "engine": "tp8"}])
    outs = dict(router.serve(_arrivals(session=key),
                             max_new_tokens=MAX_NEW, faults=inj))
    _assert_parity(outs, greedy_base)
    assert router.stats()["replicas"]["tp8"] == QUARANTINED
    assert router.stats()["counters"]["requests_failed"] == 0


def test_drain_and_migrate_parity(engine_pool, greedy_base):
    """Planned removal: the pinned replica drains mid-stream — placement
    stops, live rows finish there, the held queue migrates to the peer via
    snapshot_split — and outputs stay token-identical. frame_slots=2 keeps
    a queue behind the live rows so the migration path actually carries
    requests."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    victim = router._pick("pinned")
    # four pinned arrivals up front against frame_slots=2: two go live,
    # the rest are QUEUED on the victim when the drain starts at tick 1
    inj = RouterFaultInjector(
        [{"kind": "engine_drain", "tick": 1, "engine": victim}])
    outs = dict(router.serve(
        _arrivals(schedule={0: [0, 1, 2, 3], 4: [4, 5]}, session="pinned"),
        max_new_tokens=MAX_NEW, faults=inj,
        engine_kwargs={"frame_slots": 2}))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["drains"] == 1
    assert st["counters"]["drain_migrated"] >= 1   # the queue MOVED
    assert st["counters"]["failovers"] == 0
    assert st["replicas"][victim] == DRAINED
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_flapping_replica_bounded_retry(engine_pool, greedy_base):
    """A replica that dies every time it rejoins degrades CAPACITY, not
    availability: every request still completes (on the healthy peer),
    re-routes stay bounded, and the flapper ends DEAD after its strike
    budget."""
    cfg = RouterConfig(quarantine_backoff_ticks=2, max_engine_failures=1)
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          cfg)
    victim = router._pick("pinned")
    # kill at 1; rejoin at 3 (backoff 2); second kill at 5 exceeds the
    # one-failure strike budget -> DEAD, deterministically
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": t, "engine": victim}
         for t in (1, 5)])
    outs = dict(router.serve(_arrivals(session="pinned"),
                             max_new_tokens=MAX_NEW, faults=inj))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["requests_failed"] == 0
    assert st["counters"]["rejoins"] >= 1
    assert st["replicas"][victim] == DEAD
    # kills only fire while the replica is up; every one that fired is a
    # failover, and the strike budget caps the damage
    assert st["counters"]["failovers"] == st["counters"]["engine_kills"]
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_reroute_budget_exhausts_to_failed_request(engine_pool):
    """Kill BOTH replicas while one long request is in flight: the second
    failover exceeds max_reroute_retries=1, the request is failed loudly
    (router fault log + counter) instead of looping forever."""
    cfg = RouterConfig(max_reroute_retries=1, quarantine_backoff_ticks=64)
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          cfg)
    first = router._pick("pinned")
    second = ({"a", "b"} - {first}).pop()
    inj = RouterFaultInjector([
        {"kind": "engine_kill", "tick": 2, "engine": first},
        {"kind": "engine_kill", "tick": 5, "engine": second},
    ])
    outs = dict(router.serve(
        iter([[{"uid": 0, "tokens": PROMPTS[1], "session": "pinned",
                "max_new_tokens": 64}]]),
        max_new_tokens=64, faults=inj))
    assert outs == {}
    st = router.stats()
    assert st["counters"]["requests_failed"] == 1
    assert any(f.kind == "request_failed" and f.uid == 0
               for f in router.fault_log)
    assert st["in_flight"] == 0


def test_scheduler_path_failover_parity(engine_pool, greedy_base):
    """Kill-and-failover with a RequestScheduler per replica: resume
    arrivals re-enter through sched.submit(bypass_quota=True) and outputs
    stay token-identical."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    victim = router._pick("pinned")
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 3, "engine": victim}])
    outs = dict(router.serve(_arrivals(session="pinned"),
                             max_new_tokens=MAX_NEW, faults=inj,
                             scheduler_factory=RequestScheduler))
    _assert_parity(outs, greedy_base)
    assert router.stats()["counters"]["requests_failed"] == 0


# ---------------------------------------------------------------------------
# engine-level router hooks
# ---------------------------------------------------------------------------


def test_boundary_events_parity(engine_pool, greedy_base):
    eng = engine_pool["a"]
    outs, events = {}, []
    for item in eng.serve(_arrivals(), max_new_tokens=MAX_NEW,
                          yield_boundaries=True):
        if isinstance(item, ServeBoundary):
            events.append(item)
        else:
            outs[item[0]] = item[1]
    _assert_parity(outs, greedy_base)
    assert events and all(e.index >= 0 for e in events)
    assert events[-1].live == 0
    # the boundary clock is monotonic and ends drained
    assert all(a.index < b.index for a, b in zip(events, events[1:]))


def test_resume_arrival_midrun_parity(engine_pool, greedy_base):
    """A dict arrival carrying ``generated`` resumes mid-run: committed
    tokens fold into the re-prefill and the completion equals the
    uninterrupted run (the failover currency, tested without a router)."""
    eng = engine_pool["a"]
    base = greedy_base[1]
    item = {"uid": 1, "tokens": PROMPTS[1], "generated": [int(t) for t in
                                                          base[:3]],
            "max_new_tokens": MAX_NEW}
    outs = dict(eng.serve(iter([[item]]), max_new_tokens=MAX_NEW))
    assert np.array_equal(outs[1], base)
    _assert_clean(eng)
    # already-complete resume yields immediately
    done = {"uid": 2, "tokens": PROMPTS[1],
            "generated": [int(t) for t in base],
            "max_new_tokens": MAX_NEW}
    outs2 = dict(eng.serve(iter([[done]]), max_new_tokens=MAX_NEW))
    assert np.array_equal(outs2[2], base)
    _assert_clean(eng)


def test_engine_drain_holds_queue(engine_pool, greedy_base):
    """begin_drain() stops admission at the next boundary while live rows
    finish; the held queue is exactly the ledger, and end_drain() releases
    it."""
    eng = engine_pool["a"]
    gen = eng.serve(_arrivals(schedule={0: [0, 1, 2]},
                              max_new=None, session="s"),
                    max_new_tokens=MAX_NEW, frame_slots=2,
                    yield_boundaries=True)
    outs = {}
    drained_at = None
    for item in gen:
        if isinstance(item, ServeBoundary):
            if item.index == 1 and drained_at is None:
                eng.begin_drain()
                drained_at = item.index
            if drained_at is not None and item.live == 0 and item.queued:
                # live rows done, queue held: snapshot == the queue
                snap = eng.snapshot_serving_state()
                assert {r["uid"] for r in snap["requests"]} == {2}
                assert snap["requests"][0]["generated"] == []
                eng.end_drain()
        else:
            outs[item[0]] = item[1]
    _assert_parity(outs, greedy_base, uids=[0, 1, 2])
    assert drained_at is not None
    _assert_clean(eng)


def test_router_prometheus_exposition(engine_pool):
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          model_labels={"a": "tiny", "b": "tiny"})
    dict(router.serve(_arrivals(schedule={0: [0]}), max_new_tokens=4))
    text = router.render_prometheus()
    assert "# TYPE ds_router_placements_total counter" in text
    # per-engine ds_router_* samples carry the replica ROLE base label
    # (prefill/decode/unified) so heterogeneous fleets are separable
    assert 'ds_router_placements_total{engine="a",role="unified"}' in text
    assert 'ds_router_replica_up{engine="a",role="unified"} 1' in text
    # per-replica serving series carry the engine/model/role identity
    assert ('ds_serving_frames_total{engine="a",model="tiny",'
            'role="unified"}' in text) \
        or ('ds_serving_frames_total{engine="b",model="tiny",'
            'role="unified"}' in text)
    # scheduler-style labels merge AFTER the identity labels
    assert "ds_serving_ttft_seconds_bucket{engine=" in text
    # ONE # TYPE line per metric family across the whole fleet, with every
    # replica's samples grouped under it (the exposition format requires
    # all lines of one metric in a single group — duplicated headers or
    # interleaved families make a strict scraper reject the payload)
    type_lines = [l for l in text.splitlines() if l.startswith("# TYPE ")]
    assert len(type_lines) == len(set(type_lines))
    blocks = [b for b in text.split("# TYPE ")
              if b.startswith("ds_serving_frames_total ")]
    (frames_block,) = blocks      # one block holds BOTH replicas' samples
    assert 'engine="a"' in frames_block and 'engine="b"' in frames_block
    for eng in (engine_pool["a"], engine_pool["b"]):
        eng.telemetry.set_base_labels(engine=None, model=None, role=None)


def test_engine_side_retirement_does_not_hang_router(engine_pool):
    """Engines retire some requests WITHOUT yielding them (deadline
    expiry here; poison quarantine and scheduler sheds take the same
    path): the router must reconcile those assignments — not spin forever
    waiting for a completion that can never come."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    outs = dict(router.serve(
        iter([[{"uid": 0, "tokens": PROMPTS[1], "deadline_ms": 1e-3},
               {"uid": 1, "tokens": PROMPTS[2]}]]),
        max_new_tokens=MAX_NEW))
    assert 0 not in outs and 1 in outs       # expired dropped, peer fine
    st = router.stats()
    assert st["counters"]["engine_retired"] == 1
    assert st["in_flight"] == 0
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_all_replicas_drained_raises(engine_pool):
    """Draining EVERY replica while arrivals keep coming is an operator
    error the router surfaces loudly — terminal-state replicas never
    accept again, so unplaceable work must not cycle silently forever."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    inj = RouterFaultInjector(
        [{"kind": "engine_drain", "tick": 0, "engine": "a"},
         {"kind": "engine_drain", "tick": 0, "engine": "b"}])
    with pytest.raises(RuntimeError, match="drained"):
        list(router.serve(
            _arrivals(schedule={0: [0], 4: [1]}, session="pinned"),
            max_new_tokens=MAX_NEW, faults=inj))
    for eng in (engine_pool["a"], engine_pool["b"]):
        eng.end_drain()
        _assert_clean(eng)


def test_abandoned_router_serve_cleans_up(engine_pool, greedy_base):
    """Breaking out of router.serve() mid-stream must close every replica
    generator (running the engines' own cleanup) and leave the router
    reusable — a second serve starts fresh generators with its own
    parameters."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    gen = router.serve(_arrivals(), max_new_tokens=MAX_NEW)
    next(gen)               # at least one completion, then walk away
    gen.close()
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)  # engine serve finally-blocks ran
    assert all(r.gen is None for r in router._replicas.values())
    outs = dict(router.serve(_arrivals(), max_new_tokens=MAX_NEW))
    _assert_parity(outs, greedy_base)
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_drain_intent_survives_midDrain_failure(engine_pool, greedy_base):
    """A replica killed WHILE draining must not rejoin as an accepting
    replica — the operator's decommission intent is re-armed, so after the
    quarantine backoff it drains (empty) instead of taking placements."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          RouterConfig(quarantine_backoff_ticks=2))
    victim = router._pick("pinned")
    inj = RouterFaultInjector(
        [{"kind": "engine_drain", "tick": 1, "engine": victim},
         {"kind": "engine_kill", "tick": 2, "engine": victim}])
    outs = dict(router.serve(
        _arrivals(schedule={0: [0, 1, 2, 3], 8: [4, 5]}, session="pinned"),
        max_new_tokens=MAX_NEW, faults=inj,
        engine_kwargs={"frame_slots": 2}))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["requests_failed"] == 0
    # the rejoined replica drained instead of re-entering rotation
    assert st["replicas"][victim] == DRAINED
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_resume_truncated_fault_recorded(tiny_model_params):
    """A failover resume landing on a peer whose max_seq_len cannot hold
    the original budget is recorded loudly (resume_truncated fault) — the
    shortened output must not pass as a normal completion."""
    model, params = tiny_model_params
    small = _engine(model, params, )
    small.max_seq_len = 48            # peer with a smaller context window
    small.telemetry.reset()
    small.fault_log.clear()
    item = {"uid": 0, "tokens": PROMPTS[2], "generated": [1, 2],
            "max_new_tokens": 32}     # 33 prompt + 32 + 1 > 48
    outs = dict(small.serve(iter([[item]]), max_new_tokens=32))
    assert 0 in outs                  # serves what fits...
    assert any(f.kind == "resume_truncated" and f.uid == 0
               for f in small.fault_log)   # ...but says so


def test_unservable_prompt_fails_loudly_not_fleetwide(tiny_model_params):
    """Placement screens prompt size against each replica's max_seq_len:
    a long prompt never lands on a too-small heterogeneous peer (where
    arrival validation would hard-raise INSIDE its serve generator and
    tear the whole fleet serve down), and when the only replica that
    could hold it dies for good, the request fails loudly
    (requests_failed) while everything else keeps completing."""
    model, params = tiny_model_params
    small = _engine(model, params, max_seq_len=32)   # 33-tok prompt: never
    big = _engine(model, params)
    router = EngineRouter({"big": big, "small": small},
                          RouterConfig(rejoin=False))
    key = next(f"s{i}" for i in range(256)
               if router._pick(f"s{i}") == "big")
    inj = RouterFaultInjector([{"kind": "engine_kill", "tick": 1,
                                "engine": "big"}])
    outs = dict(router.serve(
        iter([[{"uid": 2, "tokens": PROMPTS[2], "session": key},
               {"uid": 3, "tokens": PROMPTS[3], "session": key}]]),
        max_new_tokens=MAX_NEW, faults=inj))
    st = router.stats()
    # uid 2 (33-token prompt) could only ever run on the dead replica
    assert 2 not in outs
    assert st["counters"]["requests_failed"] == 1
    assert any(f.kind == "request_failed" and f.uid == 2
               for f in router.fault_log)
    assert st["replicas"]["big"] == DEAD
    # uid 3 failed over to the small peer, token-identical
    solo = dict(_engine(model, params).serve(
        iter([[(3, PROMPTS[3])]]), max_new_tokens=MAX_NEW))
    assert np.array_equal(outs[3], solo[3])
    _assert_clean(small)


def test_router_serve_resets_stale_state(engine_pool, greedy_base):
    """serve() is re-entrant: per-request routing state parked by an
    earlier (abandoned) serve — orphaned failover resumes in
    _deferred/_unplaced, assignments, re-route budgets — must not leak
    ghost requests into the next call, and a quarantined replica's
    rejoin tick (relative to the PREVIOUS run's tick clock) is re-armed
    on the new one."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]},
                          RouterConfig(quarantine_backoff_ticks=2))
    ghost = {"uid": 99, "tokens": PROMPTS[0], "generated": [5],
             "max_new_tokens": MAX_NEW}
    router._deferred.append((7, ghost, frozenset(("a",))))
    router._unplaced.append((dict(ghost, uid=98), frozenset()))
    router._assignment[99] = "a"
    router._reroute_hops[99] = 2
    ra = router._replicas["a"]
    ra.status = QUARANTINED
    ra.failures = 1
    ra.rejoin_tick = 500          # stale: relative to a dead tick clock
    outs = dict(router.serve(_arrivals(), max_new_tokens=MAX_NEW))
    assert set(outs) == set(greedy_base)       # no ghost uids 98/99
    _assert_parity(outs, greedy_base)
    assert router.replica_status()["a"] == HEALTHY   # re-armed, rejoined
    assert not router._deferred and not router._unplaced
    assert 99 not in router._reroute_hops
    for eng in (engine_pool["a"], engine_pool["b"]):
        _assert_clean(eng)


def test_transfer_guard_router_failover(engine_pool, frame_transfer_guard,
                                        greedy_base):
    """Routing, failover, and resume re-admission are frame-BOUNDARY work:
    the in-frame device->host transfer guard stays green through a kill."""
    router = EngineRouter({"a": engine_pool["a"], "b": engine_pool["b"]})
    victim = router._pick("pinned")
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 3, "engine": victim}])
    outs = dict(router.serve(_arrivals(session="pinned"),
                             max_new_tokens=MAX_NEW, faults=inj))
    _assert_parity(outs, greedy_base)
