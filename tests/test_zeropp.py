"""MiCS, ZeRO++ hpZ, and quantized-collective tests on the 8-device CPU mesh.

Reference semantics:
- MiCS (``runtime/zero/mics.py:64,357``): ZeRO-3 within subgroups of
  ``mics_shard_size`` devices, replicated across groups; gradient reduction is
  hierarchical (reduce-scatter within group + all-reduce across groups).
  Numerically identical to plain ZeRO-3/DP.
- hpZ (``groups.py:529``, ``partition_parameters.py:1653``): optimizer state
  partitioned over the full DP world, params keep a within-group secondary
  partition for cheap gathers. Numerically identical to DP.
- qwZ/qgZ (``engine.py:901``, ``coalesced_collectives.py:31``): int8
  quantized weight allgather / gradient reduction — approximate; loss must
  track the exact run within tolerance while collectives carry int8.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


def _config(stage=3, **zero_over):
    zo = {"stage": stage}
    zo.update(zero_over)
    return {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }


def _make_batch(seed=0, bs=16, seq=32, vocab=256):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (bs, seq))
    return {"input_ids": ids, "labels": ids}


def _train(config, steps=4):
    groups.reset_mesh()
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=config)
    losses = [float(engine.train_batch(_make_batch(seed=i))) for i in range(steps)]
    return losses, engine


def _shard_count(leaf):
    """Number of distinct shards (total elements / elements per shard)."""
    per_shard = np.prod(leaf.sharding.shard_shape(leaf.shape))
    return int(np.prod(leaf.shape) // per_shard)


def test_mics_matches_zero3():
    ref, _ = _train(_config(stage=3))
    got, engine = _train(_config(stage=3, mics_shard_size=4))
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
    assert engine.mesh.shape["zrep"] == 2 and engine.mesh.shape["data"] == 4
    # params sharded 1/4 within a group (not 1/8 over the full dp world)
    big = engine.module_params["layers"]["attn"]["wq"]
    assert _shard_count(big) == 4, big.sharding
    # optimizer state follows the MiCS subgroup too
    mast = engine.opt_state["slots"]["layers"]["attn"]["wq"]["m"]
    assert _shard_count(mast) == 4, mast.sharding


def test_hpz_matches_dp():
    ref, _ = _train(_config(stage=3))
    got, engine = _train(_config(stage=3, zero_hpz_partition_size=4))
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
    assert engine.mesh.shape["zrep"] == 2 and engine.mesh.shape["data"] == 4
    # secondary (param) partition: 1/4; primary (optimizer) partition: 1/8
    big = engine.module_params["layers"]["attn"]["wq"]
    assert _shard_count(big) == 4, big.sharding
    mast = engine.opt_state["slots"]["layers"]["attn"]["wq"]["m"]
    assert _shard_count(mast) == 8, mast.sharding


def test_mics_rejects_indivisible():
    groups.reset_mesh()
    model = build_model("tiny")
    with pytest.raises(ValueError, match="not divisible"):
        ds.initialize(model=model, config=_config(stage=3, mics_shard_size=3))


@pytest.mark.parametrize("stage,hpz", [(2, 0), (3, 0), (3, 4)])
def test_quantized_collectives_track_exact(stage, hpz):
    """qwZ+qgZ: int8 wire format must track the exact run within quant noise
    (reference ZeRO++ claims convergence parity at int8). hpz=4 exercises the
    reference's flagship combo: secondary partition + quantized gather."""
    ref, _ = _train(_config(stage=stage), steps=4)
    over = dict(zero_quantized_weights=(stage == 3), zero_quantized_gradients=True)
    if hpz:
        over["zero_hpz_partition_size"] = hpz
    got, engine = _train(_config(stage=stage, **over), steps=4)
    assert engine._zeropp_enabled
    if hpz:
        assert engine.mesh.shape["zrep"] == 2
    np.testing.assert_allclose(ref, got, rtol=0.05, atol=0.05)
    # training still works (losses finite and decreasing-ish)
    assert all(np.isfinite(got))


def test_quantized_collectives_int8_on_wire():
    """Comm-volume check: the compiled step must carry s8 collectives and no
    full-precision all-gather of ZeRO-3 param shards."""
    groups.reset_mesh()
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(
        model=model, config=_config(stage=3, zero_quantized_weights=True,
                                    zero_quantized_gradients=True))
    batch = engine.stage_batch(_make_batch())
    lowered = engine._train_step_fn.lower(
        engine.module_params, engine.opt_state, engine.scaler_state, batch,
        jnp.float32(1e-3), gas=1)
    txt = lowered.compile().as_text()
    import re
    coll = [ln for ln in txt.splitlines()
            if re.search(r"\b(all-gather|all-to-all)\b", ln) and "s8" in ln]
    assert coll, "no int8 collectives found in compiled step"
    # exact-dtype param allgathers should be gone for big (sharded) params:
    f32_ag = [ln for ln in txt.splitlines()
              if "all-gather" in ln and "f32[" in ln and "s8" not in ln]
    big = [ln for ln in f32_ag if any(int(m) > 100_000 for m in
                                      re.findall(r"f32\[([0-9,]+)", ln.replace(",", ""))
                                      if m.isdigit())]
    assert not big, f"large fp32 all-gathers remain: {big[:3]}"



# ---- round-3: qwZ/qgZ composing with expert and seq mesh axes ------------

def _train_mesh(config, mesh_kw, model_name="tiny", steps=3, bs=16):
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(**mesh_kw))
    model = build_model(model_name)
    engine, _, _, _ = ds.initialize(model=model, config=config)
    losses = [float(engine.train_batch(_make_batch(seed=i, bs=bs)))
              for i in range(steps)]
    return losses, engine


def test_zeropp_on_expert_mesh():
    """qwZ+qgZ on a data x expert mesh must track the exact run (MoE expert
    dispatch rides the auto expert axis inside the data-manual region)."""
    cfg = _config(stage=3)
    ref, _ = _train_mesh(cfg, {"data": 4, "expert": 2}, model_name="tiny-moe")
    qcfg = _config(stage=3, zero_quantized_weights=True,
                   zero_quantized_gradients=True)
    got, engine = _train_mesh(qcfg, {"data": 4, "expert": 2},
                              model_name="tiny-moe")
    assert engine.mesh.shape["expert"] == 2
    np.testing.assert_allclose(ref, got, rtol=0.05, atol=0.05)


def test_zeropp_on_seq_mesh():
    """qwZ+qgZ on a data x seq mesh (Ulysses SP inside the manual region).

    The seq axis does NOT consume batch: train_batch = micro * gas * dp_world
    with dp_world = 4 (the data axis alone), so train_batch is 8 here, and
    the batches are bs=8 to match.
    """
    def cfg(**over):
        c = _config(stage=3, **over)
        c["train_batch_size"] = 8
        return c

    mesh_kw = {"data": 4, "seq": 2}
    ref, _ = _train_mesh(cfg(), mesh_kw, bs=8)
    got, engine = _train_mesh(cfg(zero_quantized_weights=True,
                                  zero_quantized_gradients=True),
                              mesh_kw, bs=8)
    assert engine.mesh.shape["seq"] == 2
    assert engine._zeropp_enabled
    np.testing.assert_allclose(ref, got, rtol=0.05, atol=0.05)

    # The Ulysses head/seq exchange must survive the manual region as real
    # all-to-alls — numerics alone can't distinguish it from XLA silently
    # gathering KV over seq (sharding-in-types reshard, see
    # ops/attention.py::_ulysses_exchange).
    batch = engine.stage_batch(_make_batch(bs=8))
    lowered = engine._train_step_fn.lower(
        engine.module_params, engine.opt_state, engine.scaler_state, batch,
        jnp.float32(1e-3), gas=1)
    txt = lowered.compile().as_text()
    assert any("all-to-all" in ln for ln in txt.splitlines()), \
        "no all-to-all in the compiled ZeRO++ x SP step"
