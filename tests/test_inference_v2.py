"""FastGen-analog tests (reference pattern: tests/unit/inference/v2/**):
allocator/paged-cache unit tests + ragged engine output equivalence against
the dense v1 engine."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache
from deepspeed_tpu.models import build_model


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


def _engine(block_size=16, budget=256, chunk=32):
    model = build_model("tiny")
    cfg = RaggedInferenceEngineConfig(kv_block_size=block_size, prefill_chunk_size=chunk,
                                      max_tokens_per_step=budget, dtype="float32",
                                      max_ragged_batch_size=8)
    return InferenceEngineV2(model, cfg, max_seq_len=128)


def test_blocked_allocator():
    a = BlockedAllocator(10)
    got = a.allocate(4)
    assert len(got) == 4 and a.free_blocks == 6
    a.free(got[:2])
    assert a.free_blocks == 8
    with pytest.raises(RuntimeError):
        a.allocate(100)
    with pytest.raises(RuntimeError):
        a.free(got[:1] + got[:1])  # double free detected via free list
    # (second free of same id)


def test_kv_cache_write_gather():
    kv = BlockedKVCache(num_layers=2, kv_heads=2, head_dim=4, num_blocks=8,
                        block_size=4, dtype=jnp.float32)
    blocks = kv.allocator.allocate(2)
    table = jnp.asarray(blocks + [0, 0], jnp.int32)
    new_k = jnp.arange(2 * 6 * 2 * 4, dtype=jnp.float32).reshape(2, 6, 2, 4)
    kv.write(table, 0, new_k, new_k * 2)
    k, v = kv.gather(table[None])
    np.testing.assert_allclose(np.asarray(k[:, 0, :6]), np.asarray(new_k))
    np.testing.assert_allclose(np.asarray(v[:, 0, :6]), np.asarray(new_k * 2))


def test_ragged_generate_matches_dense():
    """v2 paged/ragged greedy output == v1 dense-cache greedy output."""
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))

    v1 = ds.init_inference(model, dtype="float32")
    v1.module_params = jax.device_put(params, v1.param_shardings)

    v2 = _engine()
    v2.params = jax.device_put(params)

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 200, (1, 24))
    dense = np.asarray(v1.generate(prompt, max_new_tokens=8))[0, 24:]
    ragged = v2.generate([prompt[0]], max_new_tokens=8)[0]
    np.testing.assert_array_equal(dense, ragged)


def test_ragged_mixed_lengths():
    """Prompts of different lengths generate the same as one-by-one."""
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 200, (n,)) for n in (7, 24, 50)]

    solo = []
    for p in prompts:
        e = _engine()
        e.params = jax.device_put(params)
        solo.append(e.generate([p], max_new_tokens=6)[0])

    e = _engine()
    e.params = jax.device_put(params)
    batch = e.generate(prompts, max_new_tokens=6)
    for s, b in zip(solo, batch):
        np.testing.assert_array_equal(s, b)


def test_split_fuse_chunking():
    """A prompt longer than the chunk size prefills over multiple steps."""
    e = _engine(chunk=16)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 200, (40,))
    e.put([7], [prompt])
    pending0 = e.query(7)[0]
    assert pending0 == 40
    e.step()
    assert e.query(7)[0] == 24     # one 16-token chunk consumed
    e.step()
    assert e.query(7)[0] == 8
    e.step()
    assert e.query(7)[0] == 0      # final chunk → first token sampled
    assert len(e.query(7)[1]) == 1


def test_can_schedule_block_exhaustion():
    e = _engine(block_size=16)
    assert e.can_schedule([1], [32])
    assert not e.can_schedule([1], [100000])


def test_flush_releases_blocks():
    e = _engine()
    free0 = e.kv.free_blocks
    e.put([1], [np.arange(40)])
    assert e.kv.free_blocks < free0
    e.flush([1])
    assert e.kv.free_blocks == free0


def test_generate_compiled_loop_matches_stepwise():
    """generate() (one jitted lax.scan decode loop) must produce the same
    greedy tokens as per-token step() serving."""
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, RaggedInferenceEngineConfig
    from deepspeed_tpu.models import build_model

    model = build_model("tiny")
    cfg = RaggedInferenceEngineConfig(dtype="float32")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, (n,)).astype(np.int32) for n in (5, 12, 3)]

    eng1 = InferenceEngineV2(build_model("tiny"), cfg)
    params = eng1.params
    outs_loop = eng1.generate(prompts, max_new_tokens=8, temperature=0.0)

    # stepwise baseline on a fresh engine with the SAME params
    eng2 = InferenceEngineV2(build_model("tiny"), cfg, params=params)
    uids = [0, 1, 2]
    eng2.put(uids, prompts)
    counts = {u: 0 for u in uids}
    while not all(counts[u] >= 8 for u in uids):
        out = eng2.step(temperature=0.0)
        for u in out:
            counts[u] += 1
            if counts[u] >= 8:
                eng2.state.seqs[u].done = True
    outs_step = [np.asarray(eng2.state.seqs[u].generated[:8]) for u in uids]

    for a, b in zip(outs_loop, outs_step):
        np.testing.assert_array_equal(a, b)


def test_build_hf_engine_from_checkpoint_dir(tmp_path):
    """build_hf_engine(path) boots the ragged engine straight from an HF
    checkpoint directory — no torch module instantiated."""
    from transformers import LlamaConfig, LlamaForCausalLM
    import torch
    torch.manual_seed(0)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=64))
    hf.eval()
    hf.save_pretrained(str(tmp_path))

    from deepspeed_tpu.inference.v2.engine_v2 import (build_hf_engine,
                                                      RaggedInferenceEngineConfig)
    eng = build_hf_engine(str(tmp_path),
                          RaggedInferenceEngineConfig(kv_block_size=16,
                                                      dtype="float32"),
                          max_seq_len=64)
    prompt = np.random.default_rng(0).integers(0, 128, (1, 8))
    out = eng.generate([prompt[0]], max_new_tokens=6)[0]
    # parity vs the module-injected v1 engine
    v1 = ds.init_inference(hf, dtype="float32")
    ref = np.asarray(v1.generate(prompt, max_new_tokens=6))[0, 8:]
    np.testing.assert_array_equal(ref, out)


def _het_cfg(layer_types):
    from deepspeed_tpu.models.config import TransformerConfig
    return TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=len(layer_types),
        num_heads=4, intermediate_size=128, max_seq_len=128, num_experts=2,
        num_experts_per_tok=1, layer_types=tuple(layer_types),
        dtype="float32", param_dtype="float32")


@pytest.mark.parametrize("layer_types", [
    ("dense", "moe", "dense", "moe"),   # Qwen2-MoE decoder_sparse_step (periodic)
    ("dense", "dense", "moe", "moe"),   # mlp_only prefix (contiguous segments)
])
def test_ragged_heterogeneous_stack_matches_dense(layer_types):
    """Heterogeneous stacks (cfg.layer_types) serve through the paged v2
    runner (reference FastGen serves Qwen2-MoE sparse stacks,
    ``inference/v2/model_implementations/qwen_v2_moe/model.py``): greedy
    output must match the v1 dense-cache engine for both layer plans."""
    model = build_model(_het_cfg(layer_types))
    params = model.init(jax.random.PRNGKey(0))

    v1 = ds.init_inference(model, dtype="float32")
    v1.module_params = jax.device_put(params, v1.param_shardings)

    cfg = RaggedInferenceEngineConfig(kv_block_size=16, prefill_chunk_size=32,
                                      max_tokens_per_step=256, dtype="float32",
                                      max_ragged_batch_size=8)
    v2 = InferenceEngineV2(model, cfg, max_seq_len=128)
    v2.params = jax.device_put(params)

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 200, (1, 24))
    dense = np.asarray(v1.generate(prompt, max_new_tokens=8))[0, 24:]
    ragged = v2.generate([prompt[0]], max_new_tokens=8)[0]
    np.testing.assert_array_equal(dense, ragged)


def test_generate_compiled_mixed_matches_stepwise():
    """The fully-compiled SplitFuse loop (chunked prefill + staggered
    transitions + decode in ONE jit) produces exactly what the host-driven
    scheduler produces, including prompts that straddle chunk boundaries."""
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    # lengths chosen to stagger prefill completion across wide steps
    prompts = [rng.integers(0, 200, (n,)) for n in (7, 24, 50, 33)]

    def engine():
        cfg = RaggedInferenceEngineConfig(
            kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
            dtype="float32", max_ragged_batch_size=8)
        e = InferenceEngineV2(model, cfg, max_seq_len=128)
        e.params = jax.device_put(params)
        return e

    ref = engine().generate(prompts, max_new_tokens=8)
    got = engine().generate_compiled(prompts, max_new_tokens=8)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
