"""Sequence parallelism tests (reference pattern:
tests/unit/sequence_parallelism): Ulysses and ring attention must match the
non-parallel computation, and SP training must match DP training."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.ops.attention import reference_attention
from deepspeed_tpu.sequence.layer import DistributedAttention, seq_all_to_all
from deepspeed_tpu.sequence.ring_attention import ring_attention
from deepspeed_tpu.sequence.cross_entropy import sequence_parallel_cross_entropy
from deepspeed_tpu.utils import groups


def _mesh_sp(sp=4, data=2):
    groups.reset_mesh()
    return groups.set_mesh(groups.build_mesh(data=data, seq=sp))


def _qkv(rng, b=2, s=32, h=4, kvh=None, d=16):
    kvh = kvh or h
    ks = jax.random.split(rng, 3)
    return (jax.random.normal(ks[0], (b, s, h, d)),
            jax.random.normal(ks[1], (b, s, kvh, d)),
            jax.random.normal(ks[2], (b, s, kvh, d)))


def test_ring_attention_matches_reference(rng):
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng)
    out = ring_attention(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_attention_gqa(rng):
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, h=4, kvh=2)
    out = ring_attention(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_attention_grads(rng):
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng)

    gr = jax.grad(lambda q, k, v: jnp.sum(reference_attention(q, k, v, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(lambda q, k, v: jnp.sum(ring_attention(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gg, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{n}")


def test_distributed_attention_ulysses(rng):
    """DistributedAttention wrapper == plain attention (sharding constraints
    change layout, not values)."""
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng)

    def local_attn(q, k, v):
        return reference_attention(q, k, v, causal=True)

    dist_attn = DistributedAttention(local_attn)
    out = jax.jit(dist_attn)(q, k, v)
    ref = local_attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_seq_all_to_all_roundtrip(rng):
    """Explicit all-to-all: scatter heads/gather seq then inverse == identity."""
    mesh = _mesh_sp(sp=4, data=2)
    x = jax.random.normal(rng, (2, 32, 4, 8))
    from jax.sharding import PartitionSpec as P

    def body(x):
        y = seq_all_to_all(x, "seq", scatter_idx=2, gather_idx=1)
        return seq_all_to_all(y, "seq", scatter_idx=1, gather_idx=2)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
                       axis_names={"seq"}, check_vma=True)
    np.testing.assert_allclose(np.asarray(fn(x)), np.asarray(x), atol=1e-6)


def test_sp_cross_entropy(rng):
    _mesh_sp(sp=4, data=2)
    logits = jax.random.normal(rng, (2, 32, 64))
    labels = jax.random.randint(rng, (2, 32), 0, 64)
    got = sequence_parallel_cross_entropy(logits, labels)
    logp = jax.nn.log_softmax(logits, axis=-1)
    want = -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


def _config(stage=2):
    return {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }


def _batch(seed, n=16, seq=32):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, (n, seq))
    return {"input_ids": ids, "labels": ids}


def test_sp_training_matches_dp():
    """Ulysses SP training trajectory == pure DP trajectory."""
    groups.reset_mesh()
    model = build_model("tiny")
    eng_dp, _, _, _ = ds.initialize(model=model, config=_config())
    ref = [float(eng_dp.train_batch(_batch(i))) for i in range(3)]

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=2, seq=4))
    model2 = build_model("tiny")
    eng_sp, _, _, _ = ds.initialize(model=model2, config=_config())
    got = [float(eng_sp.train_batch(_batch(i))) for i in range(3)]
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)


def test_ring_training_matches_dp():
    """Ring-attention CP training trajectory == pure DP trajectory."""
    groups.reset_mesh()
    model = build_model("tiny", attn_impl="reference")
    eng_dp, _, _, _ = ds.initialize(model=model, config=_config())
    ref = [float(eng_dp.train_batch(_batch(i))) for i in range(3)]

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=2, seq=4))
    model2 = build_model("tiny", attn_impl="ring")
    eng_cp, _, _, _ = ds.initialize(model=model2, config=_config())
    got = [float(eng_cp.train_batch(_batch(i))) for i in range(3)]
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)


# ---- ring attention feature parity (round-3: window/ALiBi/segments) ------

def test_ring_attention_sliding_window(rng):
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, s=32)
    out = ring_attention(q, k, v, window=10)
    want = reference_attention(q, k, v, causal=True, window=10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_alibi(rng):
    from deepspeed_tpu.models.layers import alibi_slopes
    from deepspeed_tpu.ops.attention import _alibi_bias_from_slopes
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, s=32)
    sl = alibi_slopes(4)
    out = ring_attention(q, k, v, alibi_slopes=sl)
    bias = _alibi_bias_from_slopes(sl, 32, 32)
    want = reference_attention(q, k, v, causal=True, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_segment_ids(rng):
    """Packed sequences: ids rotate with their KV shard around the ring."""
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, s=32)
    seg = jnp.asarray(np.repeat([[0, 1, 2, 3]], 2, axis=0).repeat(8, axis=1))
    out = ring_attention(q, k, v, segment_ids=seg)
    want = reference_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_window_alibi_segments_combined(rng):
    """All three features at once, with GQA, against the XLA reference."""
    from deepspeed_tpu.models.layers import alibi_slopes
    from deepspeed_tpu.ops.attention import _alibi_bias_from_slopes
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, s=32, h=4, kvh=2)
    seg = jnp.asarray(np.repeat([[0, 0, 1, 1]], 2, axis=0).repeat(8, axis=1))
    sl = alibi_slopes(4)
    out = ring_attention(q, k, v, window=12, alibi_slopes=sl, segment_ids=seg)
    # reference takes a bias tensor; window goes through its own mask
    bias = _alibi_bias_from_slopes(sl, 32, 32)
    want = reference_attention(q, k, v, causal=True, bias=bias,
                               segment_ids=seg, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


# ---- ring attention with the Pallas flash inner kernel (round-5) ---------
# head dim 64 makes the ring eligible for the fused kernel path; a spy
# asserts the kernel body (not the einsum fallback) actually ran.

def _ring_flash_spy(monkeypatch):
    from deepspeed_tpu.sequence import ring_attention as ra
    from deepspeed_tpu.sequence import ring_flash as rf
    calls = []
    orig = rf.ring_flash_body

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ra, "ring_flash_body", spy)
    return calls


@pytest.mark.parametrize("kvh", [4, 2])
def test_ring_flash_matches_einsum_ring(rng, monkeypatch, kvh):
    _mesh_sp(sp=4, data=2)
    calls = _ring_flash_spy(monkeypatch)
    q, k, v = _qkv(rng, s=32, h=4, kvh=kvh, d=64)
    out = ring_attention(q, k, v)
    assert calls, "flash ring body was not taken at d=64"
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # einsum ring agrees too (same cache key modulo the path flag)
    monkeypatch.setenv("DS_TPU_RING_FLASH", "0")
    out2 = ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_flash_window_alibi_segments(rng, monkeypatch):
    from deepspeed_tpu.models.layers import alibi_slopes
    from deepspeed_tpu.ops.attention import _alibi_bias_from_slopes
    _mesh_sp(sp=4, data=2)
    calls = _ring_flash_spy(monkeypatch)
    q, k, v = _qkv(rng, s=32, h=4, kvh=2, d=64)
    seg = jnp.asarray(np.repeat([[0, 0, 1, 1]], 2, axis=0).repeat(8, axis=1))
    sl = alibi_slopes(4)
    out = ring_attention(q, k, v, window=12, alibi_slopes=sl, segment_ids=seg)
    assert calls
    bias = _alibi_bias_from_slopes(sl, 32, 32)
    want = reference_attention(q, k, v, causal=True, bias=bias,
                               segment_ids=seg, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_flash_grads(rng, monkeypatch):
    """The hand-written ring backward (rotating dK/dV accumulators) matches
    the XLA reference gradients, with GQA and a window."""
    _mesh_sp(sp=4, data=2)
    calls = _ring_flash_spy(monkeypatch)
    q, k, v = _qkv(rng, s=32, h=4, kvh=2, d=64)

    def f_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, window=9) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True, window=9) ** 2)

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    assert calls
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5, err_msg=f"d{n}")


def test_ring_flash_segmented_grads(rng, monkeypatch):
    _mesh_sp(sp=4, data=2)
    calls = _ring_flash_spy(monkeypatch)
    q, k, v = _qkv(rng, s=32, h=4, d=64)
    seg = jnp.asarray(np.repeat([[0, 1, 2, 3]], 2, axis=0).repeat(8, axis=1))
    g_ring = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, segment_ids=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert calls
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        reference_attention(q, k, v, causal=True, segment_ids=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5, err_msg=f"d{n}")


def test_ring_attention_windowed_grads(rng):
    _mesh_sp(sp=4, data=2)
    q, k, v = _qkv(rng, s=32)

    def f_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, window=9) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True, window=9) ** 2)

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)
