"""SLO-aware request scheduler tests.

Two layers, matching the subsystem's split:

* **Policy units** — ``RequestScheduler`` against a fake engine: strict
  priority dispatch, aging, weighted fair-share virtual time, tenant
  quotas, SLO pressure transitions, frame-steps caps. Pure host logic,
  no model, no jit.

* **Serving integration** — a shared tiny engine driving ``serve(...,
  scheduler=)`` on deterministic burst schedules: the overload acceptance
  behaviors ((a) interactive never waits behind best-effort, (b) aging
  eventually admits starved best-effort, (c) preempted rows are
  token-identical to an unpreempted greedy run, (d) the no-scheduler path
  is FIFO-identical), plus shedding/deferral under a scripted SLO breach,
  the zero-in-frame-transfer guard, and the telemetry satellites (HTTP
  /metrics endpoint, frame-steps decision trace, labeled counters).

Engine tests share one module-scope engine and a single slot-table shape
(``frame_slots=2``) so the compiled frame programs are reused across
serves — the same budget discipline as the speculative suite.
"""

import logging
import urllib.request

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.scheduler import (BATCH, BEST_EFFORT,
                                                  INTERACTIVE, Request,
                                                  RequestScheduler,
                                                  SchedulerConfig,
                                                  normalize_priority)
from deepspeed_tpu.inference.v2.telemetry import ServingTelemetry
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils.logging import logger as ds_logger


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


# ---------------------------------------------------------------------------
# policy units (no model)
# ---------------------------------------------------------------------------


class _FakeKV:
    def blocks_for(self, n):
        return -(-n // 16)


class _FakeEngine:
    def __init__(self, enabled=True):
        self.kv = _FakeKV()
        self.telemetry = ServingTelemetry(enabled=enabled,
                                          clock=lambda: 0.0)


def _req(uid, tenant="default", prio=INTERACTIVE, n=8, limit=25, slo=None):
    return Request(uid=uid, tokens=np.zeros(n, np.int32), limit=limit,
                   temp=0.0, eos=None, tenant=tenant, priority=prio,
                   slo_ms=slo)


def _sched(**cfg):
    s = RequestScheduler(SchedulerConfig(**cfg))
    s.begin_serve(_FakeEngine())
    return s


def test_normalize_priority():
    assert normalize_priority(None) == INTERACTIVE
    assert normalize_priority("batch") == BATCH
    assert normalize_priority(2) == BEST_EFFORT
    with pytest.raises(ValueError, match="unknown priority"):
        normalize_priority("bulk")
    with pytest.raises(ValueError, match="out of range"):
        normalize_priority(3)


def test_config_validation():
    with pytest.raises(ValueError, match="aging_frames"):
        SchedulerConfig(aging_frames=0)
    with pytest.raises(ValueError, match="tenant_weights"):
        SchedulerConfig(tenant_weights={"a": 0.0})
    with pytest.raises(ValueError, match="tenant_max_live"):
        SchedulerConfig(tenant_max_live=0)
    with pytest.raises(ValueError, match="defer"):
        SchedulerConfig(slo_defer_threshold=1.5, slo_shed_threshold=1.0)


def test_strict_priority_dispatch():
    """All effective-interactive admissions precede any batch one, which
    precede any best-effort one — regardless of arrival order."""
    s = _sched()
    s.submit(_req(0, prio=BEST_EFFORT))
    s.submit(_req(1, prio=BATCH))
    s.submit(_req(2, prio=INTERACTIVE))
    s.on_boundary({}, live_count=1)
    order = [r.uid for r, _ in s.pick(3, lambda r: object(), live_count=1)]
    assert order == [2, 1, 0]


def test_weighted_fair_share_virtual_time():
    """Under one-admission-per-boundary starvation, tenants split service
    in proportion to their weights (the regime where per-visit-quantum DRR
    would collapse to 1:1)."""
    s = _sched(tenant_weights={"a": 2.0, "b": 1.0})
    uid = 0
    for _ in range(40):
        s.submit(_req(uid, "a")); uid += 1
        s.submit(_req(uid, "b")); uid += 1
    admitted = {"a": 0, "b": 0}
    for _ in range(30):
        s.on_boundary({}, live_count=1)
        for r, _seq in s.pick(1, lambda r: object(), live_count=1):
            admitted[r.tenant] += 1
            s.on_retire(r.uid)
    assert admitted["a"] == 20 and admitted["b"] == 10, admitted


def test_idle_tenant_returns_without_burst():
    """A tenant coming back from idle is synced to the active floor: it
    does not cash in virtual time 'saved' while absent."""
    s = _sched()
    uid = 0
    for _ in range(20):
        s.submit(_req(uid, "busy")); uid += 1
    for _ in range(10):               # busy tenant accumulates vtime
        s.on_boundary({}, live_count=1)
        for r, _seq in s.pick(1, lambda r: object(), live_count=1):
            s.on_retire(r.uid)
    s.submit(_req(100, "idler"))      # activation syncs to busy's clock
    s.submit(_req(101, "idler"))
    s.submit(_req(102, "idler"))
    s.on_boundary({}, live_count=1)
    got = [r.tenant for r, _ in s.pick(4, lambda r: object(), live_count=1)]
    # fair alternation, not an idler monopoly on its stale zero clock
    assert got.count("idler") <= 2, got


def test_tenant_quotas_shed_and_block():
    s = _sched(tenant_max_queued=2, tenant_max_live=1)
    assert s.submit(_req(0, "t")) is None
    assert s.submit(_req(1, "t")) is None
    shed = s.submit(_req(2, "t"))
    assert shed is not None and shed.reason == "tenant_queue_full"
    assert shed.uid == 2 and shed.tenant == "t"
    assert s.shed_log[-1] is shed
    s.on_boundary({}, live_count=1)
    admits = s.pick(4, lambda r: object(), live_count=1)
    assert [r.uid for r, _ in admits] == [0]   # max_live=1 blocks the second
    s.on_retire(0)
    s.on_boundary({}, live_count=1)
    assert [r.uid for r, _ in s.pick(4, lambda r: object(), live_count=1)] \
        == [1]


def test_aging_promotes_one_class_per_window():
    s = _sched(aging_frames=2)
    s.submit(_req(0, prio=BEST_EFFORT))
    r = next(iter(s._queues[(BEST_EFFORT, "default")]))
    assert s._eff(r) == BEST_EFFORT
    for _ in range(2):
        s.on_boundary({}, live_count=1)
    assert s._eff(r) == BATCH
    for _ in range(2):
        s.on_boundary({}, live_count=1)
    assert s._eff(r) == INTERACTIVE
    # a fresh interactive arrival loses the FIFO tie-break to the aged one
    s.submit(_req(1, prio=INTERACTIVE))
    got = [rq.uid for rq, _ in s.pick(1, lambda r: object(), live_count=1)]
    assert got == [0]


def test_slo_pressure_transitions_shed_and_defer():
    s = _sched(slo_ttft_ms=100.0)
    s.submit(_req(0, prio=INTERACTIVE))
    s.submit(_req(1, prio=BATCH))
    s.submit(_req(2, prio=BEST_EFFORT))
    # below defer threshold: everything admits
    sheds = s.on_boundary({"ttft_p90_ms": 50.0}, live_count=1)
    assert not sheds and s.pressure == 0 and s.risk == 0.5
    assert len(s.pick(3, lambda r: object(), live_count=1)) == 3
    for u in (0, 1, 2):
        s.on_retire(u)
    # at-risk: batch/best-effort deferred (stay queued), interactive flows
    s.submit(_req(3, prio=INTERACTIVE))
    s.submit(_req(4, prio=BATCH))
    s.submit(_req(5, prio=BEST_EFFORT))
    sheds = s.on_boundary({"ttft_p90_ms": 90.0}, live_count=1)
    assert not sheds and s.pressure == 1
    assert [r.uid for r, _ in s.pick(3, lambda r: object(), live_count=1)] \
        == [3]
    assert s.queued_count() == 2
    # critical: queued best-effort shed with a structured reason
    sheds = s.on_boundary({"ttft_p90_ms": 150.0}, live_count=1)
    assert s.pressure == 2
    assert [x.uid for x in sheds] == [5]
    assert sheds[0].reason == "slo_pressure" and sheds[0].risk == 1.5
    assert sheds[0].priority == "best_effort"
    assert not s.is_queued(5) and s.queued_count() == 1
    # an idle machine drains its queue instead of deferring it forever
    assert [r.uid for r, _ in s.pick(3, lambda r: object(), live_count=0)] \
        == [4]


def test_preempted_requests_never_shed():
    """A preempted request is mid-flight (accepted, tokens emitted): the
    pressure loop must never shed it, only fresh best-effort arrivals."""
    s = _sched(slo_ttft_ms=100.0)
    s.submit(_req(0, prio=BEST_EFFORT))
    s.on_boundary({}, live_count=1)
    [(rq, _seq)] = s.pick(1, lambda r: object(), live_count=1)
    s.requeue_front(s.on_evict(rq.uid))        # preempt it back to queue
    s.submit(_req(1, prio=BEST_EFFORT))        # fresh, sheddable
    sheds = s.on_boundary({"ttft_p90_ms": 500.0}, live_count=1)
    assert [x.uid for x in sheds] == [1]
    assert s.is_queued(0) and not s.is_queued(1)


def test_preemption_futility_guard():
    """No eviction when even the freed blocks could not fit the waiting
    interactive request — evicting would only buy a re-prefill thrash
    loop (victim recomputed every boundary, interactive still stuck)."""
    s = _sched()
    s.submit(_req(0, prio=BEST_EFFORT, n=8, limit=25))      # cost 3 blocks
    s.on_boundary({}, live_count=0)
    [(victim, _seq)] = s.pick(1, lambda r: object(), live_count=0)
    s.submit(_req(1, prio=INTERACTIVE, n=8, limit=500))     # cost 32 blocks
    s.on_boundary({}, live_count=1)
    assert s.preempt_wanted(free_slots=0)
    committed = {victim.uid: 4}
    # 3 victim blocks + 5 free < 32 needed: futile, no victims
    assert s.pick_victims(committed, free_blocks=5) == []
    # with enough free blocks the eviction goes ahead
    assert s.pick_victims(committed, free_blocks=30) == [victim.uid]
    # and with no capacity information the guard stays out of the way
    assert s.pick_victims(committed) == [victim.uid]


def test_per_request_slo_tightens_target():
    s = _sched(slo_ttft_ms=1000.0)
    s.submit(_req(0, prio=INTERACTIVE, slo=10.0))
    s.on_boundary({"ttft_p90_ms": 20.0}, live_count=1)
    assert s.risk == 2.0 and s.pressure == 2    # 20ms vs the 10ms request


def test_frame_steps_cap_buckets():
    s = _sched(slo_ttft_ms=100.0)
    assert s.frame_steps_cap(8) == 8
    s.submit(_req(0))
    s.on_boundary({"ttft_p90_ms": 90.0}, live_count=1)     # pressure 1
    assert s.frame_steps_cap(8) == 4
    s.on_boundary({"ttft_p90_ms": 200.0}, live_count=1)    # pressure 2
    assert s.frame_steps_cap(8) == 2
    assert s.frame_steps_cap(1) == 1


def test_pick_raises_on_impossible_fit_with_empty_table():
    s = _sched()
    s.submit(_req(0, n=500, limit=500))
    s.on_boundary({}, live_count=0)
    with pytest.raises(RuntimeError, match="can never fit"):
        s.pick(4, lambda r: None, live_count=0)


def test_defer_warning_includes_reserved_blocks():
    tel = ServingTelemetry(clock=lambda: 0.0)
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = Capture()
    ds_logger.addHandler(h)
    try:
        tel.on_defer(queue_depth=3, frame_steps=8, free_slots=2,
                     free_blocks=7, reserved_blocks=5)
    finally:
        ds_logger.removeHandler(h)
    (msg,) = [m for m in records if "admission deferred" in m]
    # free_blocks is net of this round's reservations; the warning carries
    # the reservation so standing pressure and a busy admission round are
    # distinguishable
    assert "free_kv_blocks=7" in msg
    assert "kv_blocks_reserved_this_round=5" in msg


def test_http_metrics_endpoint():
    tel = ServingTelemetry(clock=lambda: 0.0)
    tel.counters["tokens_emitted"] = 42
    srv = tel.serve_metrics_http(0)
    try:
        base = f"http://127.0.0.1:{srv.metrics_port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "ds_serving_tokens_emitted_total 42" in body
        tel.counters["tokens_emitted"] = 43      # scrapes render fresh
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            assert "ds_serving_tokens_emitted_total 43" in resp.read().decode()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/other", timeout=5)
        assert err.value.code == 404
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# serving integration (shared tiny engine, frame_slots=2 throughout)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny")
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4)
    kw.update(over)
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                          max_seq_len=128)
    e.params = jax.device_put(params)
    return e


@pytest.fixture(scope="module")
def served_engine(tiny_model_params):
    """ONE engine for every integration test: serve() leaves the engine
    clean, and a single slot-table shape keeps the jit cache shared."""
    model, params = tiny_model_params
    e = _engine(model, params)
    e.telemetry.record_spans = True
    return e


PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (120,))
           .astype(np.int32)[o:o + n]
           for u, (o, n) in enumerate(
               ((0, 7), (10, 14), (30, 9), (50, 5), (60, 11), (75, 13)))}


def _spans_by_uid(tel, uids):
    """Latest recorded span per uid (the deque persists across serves, so
    tests use disjoint uid ranges or read right after their serve)."""
    out = {}
    for s in tel.spans:
        if s["uid"] in uids:
            out[s["uid"]] = s
    return out


def test_no_scheduler_path_is_fifo_identical(served_engine):
    """(d) scheduler=None keeps the FIFO code path: outputs AND retirement
    order match a default-scheduler run (single tenant, one class, no SLO
    — the policy reduces to FIFO) and the telemetry counters agree."""
    e = served_engine

    def arrivals():
        sched = {0: [0, 1], 2: [2], 3: [3]}
        for k in range(5):
            yield [(u, PROMPTS[u]) for u in sched.get(k, [])]

    base = list(e.serve(arrivals(), max_new_tokens=8))
    base_counters = dict(e.telemetry.counters)
    got = list(e.serve(arrivals(), max_new_tokens=8,
                       scheduler=RequestScheduler()))
    assert [u for u, _ in base] == [u for u, _ in got]   # retirement order
    for (u1, t1), (u2, t2) in zip(base, got):
        np.testing.assert_array_equal(t1, t2, err_msg=f"uid={u1}")
    for k in ("tokens_emitted", "requests_admitted", "requests_retired"):
        assert e.telemetry.counters[k] == base_counters[k], k
    assert e.kv.free_blocks == e.kv.num_blocks - 1


def test_interactive_never_waits_behind_best_effort(served_engine):
    """(a) burst of best-effort fills the table; interactive arrivals that
    show up later are admitted before every still-queued best-effort one
    (preemption off: this is pure queue ordering)."""
    e = served_engine
    be = {u: PROMPTS[u % 6] for u in (20, 21, 22, 23)}
    ia = {u: PROMPTS[u % 6] for u in (30, 31)}

    def arrivals():
        yield [{"uid": u, "tokens": be[u], "priority": "best_effort"}
               for u in be]
        yield []
        yield [{"uid": u, "tokens": ia[u], "priority": "interactive"}
               for u in ia]

    s = RequestScheduler(SchedulerConfig(preemption=False))
    got = dict(e.serve(arrivals(), max_new_tokens=6, frame_slots=2,
                       scheduler=s))
    assert set(got) == set(be) | set(ia)
    spans = _spans_by_uid(e.telemetry, set(be) | set(ia))
    # two best-effort admitted before the interactives even arrived; the
    # OTHER two queued best-effort must admit strictly after both
    # interactives
    be_admits = sorted(spans[u]["admit_t"] for u in be)
    ia_admits = [spans[u]["admit_t"] for u in ia]
    assert max(ia_admits) < be_admits[2], (be_admits, ia_admits)
    assert e.kv.free_blocks == e.kv.num_blocks - 1


def test_aging_admits_starved_best_effort(served_engine):
    """(b) a steady interactive stream would starve best-effort under pure
    strict priority; aging promotes the starved request so it eventually
    wins the FIFO tie-break over fresher interactive arrivals."""
    e = served_engine
    n_ia = 6

    def arrivals():
        yield [{"uid": 40, "tokens": PROMPTS[3], "priority": "interactive"},
               {"uid": 41, "tokens": PROMPTS[4], "priority": "interactive"},
               {"uid": 50, "tokens": PROMPTS[5], "priority": "best_effort"}]
        for k in range(n_ia):
            yield [{"uid": 42 + k, "tokens": PROMPTS[k % 6],
                    "priority": "interactive"}]

    def run(aging_frames):
        s = RequestScheduler(SchedulerConfig(preemption=False,
                                             aging_frames=aging_frames))
        got = dict(e.serve(arrivals(), max_new_tokens=6, frame_slots=2,
                           scheduler=s))
        uids = {40, 41, 50} | {42 + k for k in range(n_ia)}
        assert set(got) == uids
        spans = _spans_by_uid(e.telemetry, uids)
        later_ia = max(spans[u]["admit_t"] for u in uids if u != 50)
        return spans[50]["admit_t"], later_ia

    be_admit, last_ia = run(aging_frames=2)
    assert be_admit < last_ia     # aged best-effort beat a fresh interactive
    be_admit, last_ia = run(aging_frames=1000)
    assert be_admit > last_ia     # without aging it drains dead last


def test_preemption_token_parity(served_engine):
    """(c) an interactive arrival preempts a live best-effort row; the
    preempted row re-prefills from its committed prefix and finishes with
    output token-identical to an unpreempted greedy run."""
    e = served_engine

    def arrivals():
        yield [{"uid": 60, "tokens": PROMPTS[1], "priority": "best_effort"},
               {"uid": 61, "tokens": PROMPTS[2], "priority": "best_effort"}]
        yield []
        yield [{"uid": 62, "tokens": PROMPTS[0], "max_new_tokens": 4,
                "priority": "interactive"}]

    s = RequestScheduler()
    got = dict(e.serve(arrivals(), max_new_tokens=12, frame_slots=2,
                       scheduler=s))
    assert s.summary["preempted"] == 1
    assert e.telemetry.counters["requests_preempted"] == 1
    assert len(got[62]) == 4
    preempt_counters = dict(e.telemetry.counters)
    prom = e.telemetry.render_prometheus()
    assert "ds_serving_requests_preempted_total 1" in prom
    assert 'class="best_effort"' in prom
    # solo (unpreempted) baselines on the same engine
    for uid in (60, 61):
        solo = dict(e.serve(iter([[(uid, dict(
            [(60, PROMPTS[1]), (61, PROMPTS[2])])[uid])]]),
            max_new_tokens=12, frame_slots=2))
        np.testing.assert_array_equal(solo[uid], got[uid],
                                      err_msg=f"uid={uid}")
    assert preempt_counters["requests_retired"] == 3
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    assert not e.state.seqs


def test_shed_and_defer_under_slo_pressure(served_engine):
    """An impossible TTFT target drives the control loop critical after the
    first interactive emission: a later best-effort arrival is shed with a
    structured reason, a batch arrival is deferred until the machine
    drains, and frames shrink to the pressure-capped bucket."""
    e = served_engine

    def arrivals():
        yield [{"uid": 70, "tokens": PROMPTS[0], "max_new_tokens": 16,
                "priority": "interactive"}]
        yield []
        yield [{"uid": 71, "tokens": PROMPTS[3], "priority": "best_effort"}]
        yield [{"uid": 72, "tokens": PROMPTS[4], "max_new_tokens": 4,
                "priority": "batch"}]

    s = RequestScheduler(SchedulerConfig(slo_ttft_ms=1e-4))
    got = dict(e.serve(arrivals(), max_new_tokens=16, frame_slots=2,
                       scheduler=s))
    assert set(got) == {70, 72}            # 71 shed, never yielded
    assert len(got[72]) == 4               # deferred batch still completed
    shed = [x for x in s.shed_log if x.uid == 71]
    assert len(shed) == 1
    assert shed[0].reason == "slo_pressure"
    assert shed[0].priority == "best_effort" and shed[0].risk > 1.0
    assert e.telemetry.counters["requests_shed"] == 1
    assert e.telemetry.gauges["slo_risk"] > 1.0
    prom = e.telemetry.render_prometheus()
    assert "ds_serving_requests_shed_total 1" in prom
    # the batch row waited for the drain: admitted only after the
    # interactive retired
    spans = _spans_by_uid(e.telemetry, {70, 72})
    assert spans[72]["admit_t"] >= spans[70]["retire_t"]
    # pressure capped the frame length below the configured 4
    hist = e.serve_stats["frame_steps_hist"]
    assert any(k < 4 for k in hist), hist
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    # the shed request left no stale descriptor behind (uid stays reusable)
    assert not e.state.seqs


def test_scheduler_adds_no_in_frame_transfers(served_engine,
                                              frame_transfer_guard):
    """Acceptance guard: the whole policy layer (including a preemption)
    runs at frame boundaries — frame dispatch stays free of device→host
    transfers (conftest's shared guard; graft-lint GL001 is the static
    twin)."""
    e = served_engine

    def arrivals():
        yield [{"uid": 80, "tokens": PROMPTS[1], "priority": "best_effort"},
               {"uid": 81, "tokens": PROMPTS[2], "priority": "best_effort"}]
        yield []
        yield [{"uid": 82, "tokens": PROMPTS[0], "max_new_tokens": 4,
                "priority": "interactive"}]

    s = RequestScheduler()
    got = dict(e.serve(arrivals(), max_new_tokens=12, frame_slots=2,
                       scheduler=s))
    assert set(got) == {80, 81, 82}
    assert s.summary["preempted"] == 1     # the eviction ran under the guard
    assert e.kv.free_blocks == e.kv.num_blocks - 1


def test_frame_steps_decision_trace(served_engine):
    """Satellite (d): every frame's sizing decision lands in the bounded
    ring surfaced via serve_stats and the Prometheus gauge."""
    e = served_engine
    got = dict(e.serve(iter([[(90, PROMPTS[0])]]), max_new_tokens=6,
                       frame_slots=2))
    assert len(got[90]) == 6
    trace = list(e.serve_stats["frame_steps_trace"])
    assert len(trace) == e.serve_stats["frames"]
    for rec in trace:
        assert set(rec) == {"frame", "ewma", "saturated", "steps"}
        assert rec["steps"] == 4           # fixed frame_steps, no pressure
    assert [rec["frame"] for rec in trace] == list(range(len(trace)))
    prom = e.telemetry.render_prometheus()
    assert "ds_serving_frame_steps_chosen 4" in prom
    snap = e.telemetry.snapshot()
    assert snap["frame_steps_trace"] == trace


def test_dict_arrivals_without_scheduler(served_engine):
    """Dict arrivals are valid on the FIFO path too — the scheduling fields
    are simply inert — and produce identical output to tuple arrivals."""
    e = served_engine
    base = dict(e.serve(iter([[(95, PROMPTS[2])]]), max_new_tokens=6,
                        frame_slots=2))
    got = dict(e.serve(iter([[{"uid": 96, "tokens": PROMPTS[2],
                               "tenant": "t", "priority": "batch",
                               "slo_ms": 5.0}]]),
                       max_new_tokens=6, frame_slots=2))
    np.testing.assert_array_equal(base[95], got[96])


def test_tenant_labels_exported(served_engine):
    """Scheduler runs label the ds_serving_* counters per class/tenant and
    feed the per-class TTFT histogram."""
    e = served_engine

    def arrivals():
        yield [{"uid": 97, "tokens": PROMPTS[0], "tenant": "acme",
                "priority": "interactive"},
               {"uid": 98, "tokens": PROMPTS[3], "tenant": "umbrella",
                "priority": "batch"}]

    got = dict(e.serve(arrivals(), max_new_tokens=6, frame_slots=2,
                       scheduler=RequestScheduler()))
    assert set(got) == {97, 98}
    prom = e.telemetry.render_prometheus()
    assert 'ds_serving_requests_retired_total{class="interactive",' \
        'tenant="acme"} 1' in prom
    assert 'ds_serving_requests_retired_total{class="batch",' \
        'tenant="umbrella"} 1' in prom
    assert 'ds_serving_tokens_emitted_total{class="interactive",' \
        'tenant="acme"} 6' in prom
    assert 'ds_serving_class_ttft_p90_seconds{class="interactive"}' in prom
    snap = e.telemetry.snapshot()
    assert snap["class_ttft_p90_ms"]["interactive"] > 0
    assert snap["labeled"]["requests_admitted"][
        "class=batch,tenant=umbrella"] == 1


def test_abandonment_releases_scheduler_state(served_engine):
    """Breaking out of a scheduled serve with queued + live + preempted
    requests must strand nothing: descriptors flushed, KV drained, engine
    reusable."""
    e = served_engine

    def arrivals():
        yield [{"uid": 110 + i, "tokens": PROMPTS[i % 6],
                "priority": "best_effort"} for i in range(5)]
        yield []
        yield [{"uid": 120, "tokens": PROMPTS[0],
                "priority": "interactive"}]
        yield []

    s = RequestScheduler()
    for _uid, _toks in e.serve(arrivals(), max_new_tokens=12, frame_slots=2,
                               scheduler=s):
        break                              # abandon mid-flight
    assert not e.state.seqs
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    got = dict(e.serve(iter([[(110, PROMPTS[0])]]), max_new_tokens=4,
                       frame_slots=2))
    assert len(got[110]) == 4


# ---------------------------------------------------------------------------
# admission lookahead (ISSUE 14 satellite): slots reserved for
# EWMA-predicted interactive arrivals
# ---------------------------------------------------------------------------


def test_lookahead_reserves_slots_for_predicted_interactive():
    """Scripted schedule: one fresh interactive submission per boundary
    establishes the EWMA; a batch burst then cannot fill the last
    (reserved) slot, and the interactive arrival that lands one boundary
    later admits immediately — no wait, no preemption."""
    s = _sched(lookahead_reserve=True, lookahead_ewma_alpha=1.0,
               lookahead_max_reserve=2)
    # boundaries 1..3: one interactive arrival each -> ewma == 1.0
    for b in range(3):
        s.submit(_req(100 + b, prio=INTERACTIVE))
        s.on_boundary({}, live_count=1)
        picked = s.pick(4, lambda r: object(), live_count=1)
        assert [r.uid for r, _ in picked] == [100 + b]
    assert s._ia_ewma == 1.0
    assert s.lookahead_reserved(4) == 1
    # batch burst an instant before the predicted chat arrival: with 2
    # free slots it may take only ONE (the other is reserved)
    for u in range(4):
        s.submit(_req(200 + u, prio=BATCH))
    admitted = s.pick(2, lambda r: object(), live_count=2)
    assert [r.uid for r, _ in admitted] == [200]
    # ...and the predicted interactive arrival admits into the held slot
    s.submit(_req(300, prio=INTERACTIVE))
    s.on_boundary({}, live_count=3)
    admitted = s.pick(1, lambda r: object(), live_count=3)
    assert [r.uid for r, _ in admitted] == [300]


def test_lookahead_off_burst_fills_every_slot():
    """Control: without the reserve, the same burst takes both slots and
    the chat arrival must wait for a retirement (or a preemption)."""
    s = _sched()                      # lookahead_reserve defaults False
    for b in range(3):
        s.submit(_req(100 + b, prio=INTERACTIVE))
        s.on_boundary({}, live_count=1)
        s.pick(4, lambda r: object(), live_count=1)
    for u in range(4):
        s.submit(_req(200 + u, prio=BATCH))
    admitted = s.pick(2, lambda r: object(), live_count=2)
    assert [r.uid for r, _ in admitted] == [200, 201]
    s.submit(_req(300, prio=INTERACTIVE))
    s.on_boundary({}, live_count=4)
    assert s.pick(0, lambda r: object(), live_count=4) == []
    assert s.is_queued(300)


def test_lookahead_reserve_decays_and_never_starves_batch():
    """The reserve decays with the EWMA once interactive traffic stops,
    and it never blocks the LAST admissible slot (a pure-batch workload
    still makes progress at free_slots=1)."""
    s = _sched(lookahead_reserve=True, lookahead_ewma_alpha=0.5,
               lookahead_max_reserve=4)
    for b in range(4):
        s.submit(_req(100 + b, prio=INTERACTIVE))
        s.on_boundary({}, live_count=1)
        s.pick(8, lambda r: object(), live_count=1)
    assert s.lookahead_reserved(8) >= 1
    # free_slots=1: the reserve must never eat the last slot
    assert s.lookahead_reserved(1) == 0
    s.submit(_req(500, prio=BATCH))
    assert [r.uid for r, _ in s.pick(1, lambda r: object(),
                                     live_count=1)] == [500]
    # interactive traffic stops: the EWMA (and the reserve) decay to zero
    for _ in range(12):
        s.on_boundary({}, live_count=1)
    assert s.lookahead_reserved(8) == 0
    # aged batch/BE work ignores the reserve (anti-starvation outranks
    # lookahead, like deferral)
    s2 = _sched(lookahead_reserve=True, lookahead_ewma_alpha=1.0,
                aging_frames=1)
    s2.submit(_req(0, prio=INTERACTIVE))
    s2.on_boundary({}, live_count=1)
    s2.pick(4, lambda r: object(), live_count=1)     # ewma == 1
    s2.submit(_req(1, prio=BATCH))
    s2.on_boundary({}, live_count=1)                 # ages 1 -> eff O(1)
    s2.on_boundary({}, live_count=1)
    admitted = s2.pick(1, lambda r: object(), live_count=1)
    assert [r.uid for r, _ in admitted] == [1], \
        "an aged-to-interactive request must ignore the reserve"


def test_lookahead_config_validation():
    with pytest.raises(ValueError, match="lookahead_ewma_alpha"):
        SchedulerConfig(lookahead_ewma_alpha=0.0)
    with pytest.raises(ValueError, match="lookahead_max_reserve"):
        SchedulerConfig(lookahead_max_reserve=-1)
