"""Model-family variant coverage (round-3 verdict item 2): the HF config
flags that previously raised NotImplementedError — Phi/StableLM qk-layernorm,
StableLM parallel residual, Falcon new_decoder_architecture, Gemma-2
(sandwich norms + softcapping + alternating sliding window), MPT qk_ln/rope.

Parity harness mirrors tests/test_inference.py: tiny randomly-initialized HF
models converted via init_inference, logits vs the torch forward. MPT's HF
port ignores qk_ln/rope in its modeling code (config-only flags), so those
are covered at the native level instead.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.config import TransformerConfig

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


def _compare_logits(hf_model, atol=2e-3, batch=2, seq=16):
    engine = ds.init_inference(hf_model, dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (batch, seq))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    got = np.asarray(engine.forward(ids))
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return engine


# ---- Phi qk_layernorm ----------------------------------------------------

def test_phi_qk_layernorm_logits_match():
    cfg = transformers.PhiConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        qk_layernorm=True)
    torch.manual_seed(0)
    _compare_logits(transformers.PhiForCausalLM(cfg).eval())


# ---- StableLM variants ---------------------------------------------------

def _tiny_stablelm(**kw):
    cfg = transformers.StableLmConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, partial_rotary_factor=0.25, **kw)
    torch.manual_seed(0)
    # HF's StableLm _init_weights assumes every LayerNorm has a bias and
    # crashes on the bias-free per-head qk norms; build with torch default
    # init instead and randomize the LN scales so a wrong per-head weight
    # mapping can't silently pass the parity check.
    from transformers.modeling_utils import no_init_weights
    with no_init_weights():
        model = transformers.StableLmForCausalLM(cfg)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, torch.nn.LayerNorm):
                m.weight.normal_(1.0, 0.3)
                if m.bias is not None:
                    m.bias.normal_(0.0, 0.1)
    return model.eval()


def test_stablelm_qk_layernorm_logits_match():
    _compare_logits(_tiny_stablelm(qk_layernorm=True))


def test_stablelm_parallel_residual_logits_match():
    _compare_logits(_tiny_stablelm(use_parallel_residual=True))


def test_stablelm_parallel_qk_ln_qkv_bias_logits_match():
    """All three variant flags at once."""
    _compare_logits(_tiny_stablelm(use_parallel_residual=True,
                                   qk_layernorm=True, use_qkv_bias=True))


# ---- Falcon new_decoder_architecture ------------------------------------

def test_falcon_new_decoder_architecture_logits_match():
    cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        max_position_embeddings=64)
    torch.manual_seed(0)
    _compare_logits(transformers.FalconForCausalLM(cfg).eval())


def test_falcon_new_arch_greedy_matches_hf():
    cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        max_position_embeddings=64)
    torch.manual_seed(1)
    hf = transformers.FalconForCausalLM(cfg).eval()
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(3).integers(0, 100, (1, 8))
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids), max_new_tokens=8, do_sample=False,
                           pad_token_id=0).numpy()
    got = np.asarray(engine.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(got, want)


# ---- Gemma-2 -------------------------------------------------------------

def _tiny_gemma2(n_layers=4, **kw):
    cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=8, max_position_embeddings=64,
        query_pre_attn_scalar=8, sliding_window=8,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0, **kw)
    torch.manual_seed(0)
    return transformers.Gemma2ForCausalLM(cfg).eval()


def test_gemma2_logits_match():
    # seq 16 > window 8 so the even (sliding) layers actually mask
    _compare_logits(_tiny_gemma2(), atol=3e-3)


def test_gemma2_config_mapping():
    from deepspeed_tpu.inference.v2.model_implementations import resolve_container
    hf = _tiny_gemma2()
    container = resolve_container(hf.config)
    cfg = container.config(hf.config)
    assert cfg.sandwich_norm and cfg.attn_softcap == 50.0
    assert cfg.logit_softcap == 30.0
    assert cfg.attn_scale == pytest.approx(8 ** -0.5)
    # HF: even-indexed layers slide
    assert cfg.window_pattern == (8, 0, 8, 0)


def test_gemma2_greedy_matches_hf():
    hf = _tiny_gemma2(n_layers=2)
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(5).integers(0, 100, (1, 12))
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids), max_new_tokens=6, do_sample=False,
                           pad_token_id=0).numpy()
    got = np.asarray(engine.generate(ids, max_new_tokens=6))
    np.testing.assert_array_equal(got, want)


# ---- chunked CE with logit softcap ---------------------------------------

def test_chunked_cross_entropy_softcap_matches_dense():
    """The fused vocab-chunked loss must equal the dense softcapped loss
    (value and gradients) so Gemma-2 training can keep the chunked path."""
    from deepspeed_tpu.ops.cross_entropy import lm_cross_entropy
    rng = np.random.default_rng(0)
    b, s, e, v, cap = 2, 8, 16, 64, 5.0
    h = jnp.asarray(rng.normal(size=(b, s, e)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, e)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)

    def dense(h, w):
        logits = jnp.einsum("bse,ve->bsv", h, w)
        logits = cap * jnp.tanh(logits / cap)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return jnp.mean(lse - ll)

    def chunked(h, w):
        return lm_cross_entropy(h, w, labels, n_chunks=4, softcap=cap)

    want, (dh_w, dw_w) = jax.value_and_grad(dense, argnums=(0, 1))(h, w)
    got, (dh_g, dw_g) = jax.value_and_grad(chunked, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dh_g), np.asarray(dh_w), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dw_g), np.asarray(dw_w), atol=1e-5)


# ---- MPT variants (native-level: HF's port ignores qk_ln/rope) ----------

def _tiny_variant_cfg(**kw):
    base = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                intermediate_size=128, max_seq_len=128, dtype="float32",
                param_dtype="float32")
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.parametrize("mode", ["full", "head_dim", "per_head"])
def test_qk_norm_decode_matches_forward(mode):
    """All three qk-norm layouts: scan decode == full forward."""
    cfg = _tiny_variant_cfg(qk_norm=mode, activation="gelu_exact",
                            norm="layernorm", position="alibi")
    model = build_model(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    ids = jax.random.randint(rng, (2, 8), 0, cfg.vocab_size)
    full = model.apply(params, ids)
    cache = model.init_cache(2, 16)
    cache_len = jnp.zeros((2,), jnp.int32)
    logits, cache = model.apply_decode(params, ids, cache, cache_len)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=2e-4, rtol=1e-4)


def test_qk_norm_mpt_rope_trains():
    """MPT rope + qk_ln variant config: loss decreases, grads finite."""
    cfg = _tiny_variant_cfg(qk_norm="full", position="rope",
                            activation="gelu_exact", norm="layernorm")
    model = build_model(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    ids = jax.random.randint(rng, (2, 16), 0, cfg.vocab_size)
    batch = {"input_ids": ids, "labels": ids}
    loss, grads = jax.value_and_grad(model.loss)(params, batch)
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))


def test_qk_norm_full_matches_manual():
    """qk_norm='full' must equal a LayerNorm over the flattened head dims
    (the MPT q_ln/k_ln semantics)."""
    from deepspeed_tpu.models.layers import apply_qk_norm
    cfg = _tiny_variant_cfg(qk_norm="full", norm="layernorm")
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)), jnp.float32)  # (B,S,H,D)
    scale = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    got = apply_qk_norm({"scale": scale, "bias": bias}, x, cfg)
    flat = np.asarray(x).reshape(2, 4, 64)
    mu = flat.mean(-1, keepdims=True)
    var = flat.var(-1, keepdims=True)
    want = ((flat - mu) / np.sqrt(var + cfg.norm_eps)) * np.asarray(scale) + np.asarray(bias)
    np.testing.assert_allclose(np.asarray(got).reshape(2, 4, 64), want, atol=1e-5)


# ---- heterogeneous layer stacks (Qwen2-MoE sparse/dense interleave) ------

def _tiny_qwen2moe(**kw):
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=80,
        num_experts=4, num_experts_per_tok=2, max_position_embeddings=64,
        **kw)
    torch.manual_seed(0)
    return transformers.Qwen2MoeForCausalLM(cfg).eval()


def test_layer_plan_shapes():
    from deepspeed_tpu.models.transformer import layer_plan, layer_groups
    base = TransformerConfig(num_layers=4, num_experts=2)
    assert layer_plan(base) is None
    alt = base.replace(layer_types=("dense", "moe", "dense", "moe"))
    assert layer_plan(alt) == ("periodic", 2)
    assert layer_groups(alt) == [("dense", (0, 2)), ("moe", (1, 3))]
    pre = base.replace(layer_types=("dense", "moe", "moe", "moe"))
    assert layer_plan(pre) == ("segments", [("dense", 0, 1), ("moe", 1, 3)])
    assert layer_groups(pre) == [("dense", (0,)), ("moe", (1, 2, 3))]


def test_qwen2moe_sparse_step_logits_match():
    """decoder_sparse_step=2: alternating dense/moe — the periodic plan."""
    hf = _tiny_qwen2moe(decoder_sparse_step=2, mlp_only_layers=[])
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(0).integers(0, 100, (1, 8))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(engine.forward(ids))
    np.testing.assert_allclose(got, want, atol=1e-2, rtol=1e-3)


def test_qwen2moe_mlp_only_layers_logits_match():
    """mlp_only_layers=[0]: a dense prefix — the segments plan."""
    hf = _tiny_qwen2moe(decoder_sparse_step=1, mlp_only_layers=[0])
    engine = ds.init_inference(hf, dtype="float32")
    ids = np.random.default_rng(1).integers(0, 100, (1, 8))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(engine.forward(ids))
    np.testing.assert_allclose(got, want, atol=1e-2, rtol=1e-3)


def test_heterogeneous_decode_matches_forward():
    """Grouped decode (periodic plan) == full forward on a native model."""
    cfg = _tiny_variant_cfg(num_experts=2, num_layers=4,
                            layer_types=("dense", "moe", "dense", "moe"),
                            moe_intermediate_size=96)
    model = build_model(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    ids = jax.random.randint(rng, (2, 8), 0, cfg.vocab_size)
    full = model.apply(params, ids)
    cache = model.init_cache(2, 16)
    logits, cache = model.apply_decode(params, ids, cache,
                                       jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=2e-4, rtol=1e-4)


def test_heterogeneous_stack_trains_under_engine():
    """A het stack must train through deepspeed_tpu.initialize (sharding
    rules walk the grouped tree)."""
    cfg = _tiny_variant_cfg(num_experts=2, num_layers=2,
                            layer_types=("dense", "moe"))
    model = build_model(cfg)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2}})
    rng = np.random.default_rng(0)
    batch = engine.stage_batch(
        {"input_ids": rng.integers(0, 200, (8, 16), dtype=np.int32),
         "labels": rng.integers(0, 200, (8, 16), dtype=np.int32)})
    l0 = float(jax.device_get(engine.train_batch(batch)))
    for _ in range(4):
        loss = engine.train_batch(batch)
    assert float(jax.device_get(loss)) < l0
