"""Flash attention kernel vs XLA reference (reference pattern:
tests/unit/ops kernel micro-tests vs torch). Runs in Pallas interpret mode on
CPU; the same kernel compiles via Mosaic on TPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import reference_attention

pytestmark = pytest.mark.usefixtures("mesh_8dp")


def _flash(q, k, v, causal=True):
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)


def _rand_qkv(rng, b=1, s=128, h=2, kvh=None, d=64, dtype=jnp.float32):
    kvh = kvh or h
    kq, kk, kv_ = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv_, (b, s, kvh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(rng, causal):
    q, k, v = _rand_qkv(rng)
    out = _flash(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_gqa(rng):
    q, k, v = _rand_qkv(rng, h=4, kvh=2)
    out = _flash(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_backward_matches_reference(rng):
    q, k, v = _rand_qkv(rng, s=128)

    def loss_flash(q, k, v):
        return jnp.sum(_flash(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_backward_gqa(rng):
    q, k, v = _rand_qkv(rng, h=4, kvh=2)

    def loss_flash(q, k, v):
        return jnp.sum(_flash(q, k, v) * 0.01) + jnp.sum(_flash(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * 0.01) + \
            jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_multiblock_seq(rng):
    """Sequence spanning several kv blocks exercises the online-softmax loop."""
    q, k, v = _rand_qkv(rng, s=256)
    out = _flash(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
