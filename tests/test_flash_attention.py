"""Flash attention kernel vs XLA reference (reference pattern:
tests/unit/ops kernel micro-tests vs torch). Runs in Pallas interpret mode on
CPU; the same kernel compiles via Mosaic on TPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import reference_attention

pytestmark = pytest.mark.usefixtures("mesh_8dp")


def _flash(q, k, v, causal=True):
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)


def _rand_qkv(rng, b=1, s=128, h=2, kvh=None, d=64, dtype=jnp.float32):
    kvh = kvh or h
    kq, kk, kv_ = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv_, (b, s, kvh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(rng, causal):
    q, k, v = _rand_qkv(rng)
    out = _flash(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_gqa(rng):
    q, k, v = _rand_qkv(rng, h=4, kvh=2)
    out = _flash(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_backward_matches_reference(rng):
    q, k, v = _rand_qkv(rng, s=128)

    def loss_flash(q, k, v):
        return jnp.sum(_flash(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_backward_gqa(rng):
    q, k, v = _rand_qkv(rng, h=4, kvh=2)

    def loss_flash(q, k, v):
        return jnp.sum(_flash(q, k, v) * 0.01) + jnp.sum(_flash(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * 0.01) + \
            jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_multiblock_seq(rng):
    """Sequence spanning several kv blocks exercises the online-softmax loop."""
    q, k, v = _rand_qkv(rng, s=256)
    out = _flash(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_evoformer_attention():
    """DS4Sci evoformer attention (mask + pair biases, query-chunked) matches
    the naive materialized form, grads included (reference
    deepspeed4science/evoformer_attn.py DS4Sci_EvoformerAttention)."""
    from deepspeed_tpu.ops.evoformer import DS4Sci_EvoformerAttention
    rng = np.random.default_rng(0)
    B, N, S, H, D = 2, 3, 70, 4, 16
    q = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    b1 = jnp.asarray(rng.normal(size=(B, N, 1, 1, S)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(B, 1, H, S, S)), jnp.float32)

    def naive(q):
        lg = jnp.einsum("bnqhd,bnkhd->bnhqk", q, k) * (D ** -0.5) + b1 + b2
        return jnp.einsum("bnhqk,bnkhd->bnqhd", jax.nn.softmax(lg, -1), v)

    out = DS4Sci_EvoformerAttention(q, k, v, [b1, b2], chunk=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(naive(q)), atol=2e-5)
    gr = jax.grad(lambda q: jnp.sum(naive(q) ** 2))(q)
    gc = jax.grad(lambda q: jnp.sum(
        DS4Sci_EvoformerAttention(q, k, v, [b1, b2], chunk=32).astype(jnp.float32) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gr), atol=2e-4)
    with pytest.raises(ValueError):
        DS4Sci_EvoformerAttention(q, k, v, [jnp.zeros((1, 2, 3))])


def test_evoformer_flash_kernel(monkeypatch):
    """At MXU-friendly shapes the Pallas bias-flash forward engages
    (reference csrc/deepspeed4science/evoformer_attn CUTLASS kernel):
    forward matches the naive materialized form; the chunked-recompute
    backward yields q/k/v AND bias gradients (the kernel's dB outputs)."""
    from deepspeed_tpu.ops import evoformer as evo
    from deepspeed_tpu.ops.pallas import evoformer_flash as ef
    calls = []
    orig = ef.evoformer_flash_fwd

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ef, "evoformer_flash_fwd", spy)
    # the dispatcher gates on backend == tpu (interpret-mode Pallas is slow
    # on CPU); force the path so the suite exercises the kernel
    monkeypatch.setattr(evo, "_use_pallas", lambda: True)
    rng = np.random.default_rng(1)
    B, N, S, H, D = 1, 2, 128, 2, 64
    q = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, N, S, H, D)), jnp.float32)
    b1 = jnp.asarray(rng.normal(size=(B, N, 1, 1, S)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(B, 1, H, S, S)), jnp.float32)

    def naive(q, k, v, b1, b2):
        lg = jnp.einsum("bnqhd,bnkhd->bnhqk", q, k) * (D ** -0.5) + b1 + b2
        return jnp.einsum("bnhqk,bnkhd->bnqhd", jax.nn.softmax(lg, -1), v)

    out = evo.DS4Sci_EvoformerAttention(q, k, v, [b1, b2])
    assert calls, "Pallas evoformer path was not taken at eligible shapes"
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(naive(q, k, v, b1, b2)), atol=2e-5)
    g_naive = jax.grad(lambda *a: jnp.sum(naive(*a) ** 2),
                       argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    g_flash = jax.grad(lambda *a: jnp.sum(
        evo.DS4Sci_EvoformerAttention(a[0], a[1], a[2],
                                      [a[3], a[4]]).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    for a, b, nm in zip(g_flash, g_naive, ("dq", "dk", "dv", "db1", "db2")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4,
                                   err_msg=nm)
    # bias-free + mask-only variants route through the kernel too, BACKWARD
    # included (the custom-VJP None-residual structure for absent biases)
    np.testing.assert_allclose(
        np.asarray(evo.DS4Sci_EvoformerAttention(q, k, v, [])),
        np.asarray(naive(q, k, v, 0.0, 0.0)), atol=2e-5)
    g0 = jax.grad(lambda q_: jnp.sum(
        evo.DS4Sci_EvoformerAttention(q_, k, v, []) ** 2))(q)
    g0r = jax.grad(lambda q_: jnp.sum(naive(q_, k, v, 0.0, 0.0) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g0r), atol=3e-4)
    np.testing.assert_allclose(
        np.asarray(evo.DS4Sci_EvoformerAttention(q, k, v, [b1])),
        np.asarray(naive(q, k, v, b1, 0.0)), atol=2e-5)
    g1 = jax.grad(lambda b: jnp.sum(
        evo.DS4Sci_EvoformerAttention(q, k, v, [b]) ** 2))(b1)
    g1r = jax.grad(lambda b: jnp.sum(naive(q, k, v, b, 0.0) ** 2))(b1)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g1r), atol=3e-4)


def test_flash_alibi_matches_reference():
    """In-kernel ALiBi (slopes → slope*(k-q) built from block coordinates)
    must match the reference path's expanded bias, forward and grads."""
    from deepspeed_tpu.models.layers import alibi_slopes
    from deepspeed_tpu.ops.attention import (_alibi_bias_from_slopes,
                                             reference_attention)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 256, 4, 64
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    slopes = alibi_slopes(h)
    bias = _alibi_bias_from_slopes(slopes, s, s)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       alibi_slopes=slopes, block_q=128,
                                       block_k=128) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True, bias=bias) ** 2)

    o_f = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          block_q=128, block_k=128)
    o_r = reference_attention(q, k, v, causal=True, bias=bias)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r), atol=2e-5)

    g_f = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=1e-3)


def test_flash_sliding_window_matches_reference():
    """In-kernel sliding window (block skipping below the window + mask at
    both boundaries) matches the reference path, fwd and grads, for windows
    smaller than / straddling / larger than the block size."""
    from deepspeed_tpu.ops.attention import reference_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 512, 2, 64
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    for w in (32, 128, 200, 511):
        o_f = flash_attention(q, k, v, causal=True, window=w,
                              block_q=128, block_k=128)
        o_r = reference_attention(q, k, v, causal=True, window=w)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r),
                                   atol=2e-5, err_msg=f"window={w}")

        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, window=w, block_q=128, block_k=128) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(reference_attention(
            q, k, v, causal=True, window=w) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"window={w}")


def test_flash_segment_ids_matches_reference():
    """In-kernel sequence-packing mask: tokens attend only within their own
    segment; fwd + grads must match the reference path."""
    from deepspeed_tpu.ops.attention import reference_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    rng = np.random.default_rng(2)
    b, s, h, d = 2, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    # three packed documents with uneven lengths, different per batch row
    seg = np.zeros((b, s), np.int32)
    seg[0, 100:180] = 1; seg[0, 180:] = 2
    seg[1, 50:]  = 1
    seg = jnp.asarray(seg)

    o_f = flash_attention(q, k, v, causal=True, segment_ids=seg,
                          block_q=128, block_k=128)
    o_r = reference_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r), atol=2e-5)

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, segment_ids=seg, block_q=128, block_k=128) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(reference_attention(
        q, k, v, causal=True, segment_ids=seg) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=1e-3)


def test_flash_segment_ids_noncausal_and_windowed():
    """Segment masking composes with non-causal attention (BERT padding
    masks routed as segment ids) and with sliding windows."""
    from deepspeed_tpu.ops.attention import reference_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    seg = np.zeros((b, s), np.int32); seg[:, 90:] = 1; seg[:, 200:] = 2
    seg = jnp.asarray(seg)

    o_f = flash_attention(q, k, v, causal=False, segment_ids=seg,
                          block_q=128, block_k=128)
    o_r = reference_attention(q, k, v, causal=False, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r), atol=2e-5)

    o_f = flash_attention(q, k, v, causal=True, segment_ids=seg, window=40,
                          block_q=128, block_k=128)
    o_r = reference_attention(q, k, v, causal=True, segment_ids=seg, window=40)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r), atol=2e-5)
