"""graft-lint: the analyzer's own test suite.

Three layers:

1. **Fixture goldens** — each jaxpr rule (GL001 transfer, GL002 donation,
   GL003 collective, GL004 retrace) demonstrably FIRES on its
   deliberately-broken fixture in ``tests/fixtures/graft_lint/`` and stays
   silent on the clean counterparts; the AST rules golden-match the
   ``# expect: GLxxx`` markers in ``bad_ast.py``.
2. **Registry honesty** — ``ast_checks.DISPATCH_DONATIONS`` (the call-site
   donation table) is cross-checked against the LIVE ``Traced.donate_argnums``
   of every serving program, so the table cannot rot when a loop grows a
   carry.
3. **The repo gate** — a full ``deepspeed_tpu/`` run (both families, tp
   programs included on the conftest's 8-device mesh) must be clean modulo
   the committed baseline. This is the regression test every later PR runs
   under.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.analysis import findings as F
from deepspeed_tpu.analysis.ast_checks import (DISPATCH_DONATIONS,
                                               check_donation_sites,
                                               check_module)
from deepspeed_tpu.analysis.jaxpr_checks import (check_collectives,
                                                 check_donation,
                                                 check_program,
                                                 check_retrace,
                                                 check_transfer)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "deepspeed_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "graft_lint")
BASELINE = os.path.join(ROOT, ".graft-lint-baseline.json")


def _fixture(name):
    spec = importlib.util.spec_from_file_location(
        f"graft_lint_fixture_{name}", os.path.join(FIXTURES, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Family A rules fire on their fixtures
# ---------------------------------------------------------------------------


def test_transfer_guard_fires_on_bad_scan_body():
    prog = _fixture("bad_scan_body").make_program()
    got = check_transfer(prog)
    assert [f.rule for f in got] == ["GL001"]
    assert "scan body" in got[0].message
    assert got[0].context == "fixture:bad_scan_body"
    # the donation/retrace checks stay silent: the carry round-trips and
    # the trace is deterministic — rules must not bleed into each other
    assert check_donation(prog) == []
    assert check_retrace(prog) == []


def test_donation_checker_fires_on_unmatched_aval():
    prog = _fixture("bad_donation").make_program()
    got = check_donation(prog)
    assert [f.rule for f in got] == ["GL002"]
    assert "no matching output aval" in got[0].message
    assert check_transfer(prog) == []


def test_donation_checker_fires_on_unrebound_dispatch():
    src = _fixture("bad_donation").BAD_DISPATCH_SRC
    got = check_donation_sites("fixture.py", src,
                               registry={"frame_loop": (1,)})
    assert [f.rule for f in got] == ["GL002"]
    assert "self.kv.k" in got[0].message
    # the real dispatch pattern — donated carry rebound in the same
    # statement — must pass under the same registry
    ok = "toks, emit, self.kv.k = runner.frame_loop(params, self.kv.k)\n"
    assert check_donation_sites("ok.py", ok, registry={"frame_loop": (1,)}) \
        == []
    # ...as must the assign-then-rebind refactor of it (the dead
    # reference is overwritten within the same scope)
    ok2 = ("def dispatch(self, runner, params):\n"
           "    toks, emit, new_k = runner.frame_loop(params, self.kv.k)\n"
           "    self.kv.k = new_k\n"
           "    return toks, emit\n")
    assert check_donation_sites("ok2.py", ok2,
                                registry={"frame_loop": (1,)}) == []


def test_collective_checker_fires_on_wrong_axis():
    got = check_collectives(_fixture("bad_collective").wrong_axis())
    assert [f.rule for f in got] == ["GL003"]
    assert "axis" in got[0].message


def test_collective_checker_fires_on_bad_ring():
    got = check_collectives(_fixture("bad_collective").bad_ring())
    assert [f.rule for f in got] == ["GL003"]
    assert "ppermute" in got[0].message


def test_collective_checker_fires_on_leaky_replicated_output():
    mod = _fixture("bad_collective")
    got = check_collectives(mod.leaky_output())
    assert [f.rule for f in got] == ["GL003"]
    assert "REPLICATED" in got[0].message
    # the clean psum twin must NOT trip the taint pass
    assert check_collectives(mod.clean()) == []


def test_taint_pass_descends_into_while_bodies():
    """Shard-variance INTRODUCED inside a while_loop body (axis_index on
    the carry) must not escape the taint pass just because the loop's
    inputs were replicated."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))

    def body(x):
        def step(c):
            return c + jax.lax.axis_index("tp").astype(jnp.float32)
        return jax.lax.while_loop(lambda c: c < 3.0, step, jnp.sum(x))

    mapped = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_rep=False)

    def trace():
        return jax.make_jaxpr(mapped)(jnp.ones((8,), jnp.float32))

    got = check_collectives(TracedProgram(name="fixture:while_taint",
                                          trace=trace, retrace=trace))
    assert [f.rule for f in got] == ["GL003"]
    assert "REPLICATED" in got[0].message


def test_retrace_budget_fires_on_trace_time_state():
    got = check_retrace(_fixture("bad_retrace").make_program())
    assert [f.rule for f in got] == ["GL004"]
    assert "DIFFERENT jaxprs" in got[0].message


def test_unclassified_trace_failure_is_loud_not_vacuous():
    """A program whose trace dies for a reason no rule classifies
    (signature drift, bad registry shapes) must surface as GL000 — never
    as a silent 'clean' with GL001-GL004 unrun."""
    from deepspeed_tpu.analysis.jaxpr_checks import (TracedProgram,
                                                     check_program)

    def broken():
        raise TypeError("missing a required argument: 'kpool'")

    got = check_program(TracedProgram(name="fixture:drifted", trace=broken,
                                      retrace=broken))
    assert [f.rule for f in got] == ["GL000"]
    assert "TypeError" in got[0].message


def test_gl000_carries_the_innermost_traceback_frame():
    """A GL000 finding names the file:line (and function) the trace abort
    was raised from plus the exception repr — without it, a trace abort is
    near-undebuggable from the JSON output (the program name says WHAT
    failed, never WHERE)."""
    from deepspeed_tpu.analysis.jaxpr_checks import (TracedProgram,
                                                     check_program)

    def _deep_helper():
        raise ValueError("registry shape drifted")

    def broken():
        return _deep_helper()

    got = check_program(TracedProgram(name="fixture:located", trace=broken,
                                      retrace=broken))
    assert [f.rule for f in got] == ["GL000"]
    msg = got[0].message
    assert "test_static_analysis.py:" in msg and "in _deep_helper" in msg
    assert "ValueError('registry shape drifted')" in msg


# ---------------------------------------------------------------------------
# Family B golden: the # expect: markers in bad_ast.py are the spec
# ---------------------------------------------------------------------------


def test_ast_rules_golden_match_fixture_markers():
    path = os.path.join(FIXTURES, "bad_ast.py")
    with open(path) as fh:
        src = fh.read()
    import re
    expected = set()
    for i, line in enumerate(src.splitlines(), start=1):
        m = re.search(r"# expect: (GL\d{3})\s*$", line)
        if m:
            expected.add((m.group(1), i))
    assert expected, "fixture lost its markers"
    found = F.apply_suppressions(check_module("bad_ast.py", src),
                                 {"bad_ast.py": src})
    got = {(f.rule, f.line) for f in found}
    assert got == expected, (f"analyzer drifted from fixture spec:\n"
                             f"  missing: {sorted(expected - got)}\n"
                             f"  extra:   {sorted(got - expected)}")


def test_lambda_scan_bodies_are_walked():
    """A hazard nested inside a lambda scan body must not escape just for
    being an expression — the most common scan-body shape."""
    src = ("import jax.lax as lax\n"
           "lax.scan(lambda c, x: (c + float(x), c), 0.0, xs)\n")
    got = check_module("lam.py", src)
    assert [f.rule for f in got] == ["GL104"], got


def test_unhashable_static_requires_a_jit_callee():
    """GL102 must not flag a host helper that merely shares a kwarg name
    with some jit's static_argnames."""
    src = ("import functools, jax\n"
           "@functools.partial(jax.jit, static_argnames=('width',))\n"
           "def f(x, width):\n"
           "    return x\n"
           "def make_plot(width=None):\n"
           "    return width\n"
           "make_plot(width=[1, 2, 3])\n"     # host call: NOT a finding
           "f(1, width=[1, 2, 3])\n")         # jit call: IS a finding
    got = [f for f in check_module("w.py", src) if f.rule == "GL102"]
    assert len(got) == 1 and got[0].line == 8, got


def test_bare_control_flow_names_require_lax_import():
    """A host-side helper named `switch`/`scan` must not turn its callback
    arguments into 'jitted regions'; a bare name IS a region root when it
    was imported from jax.lax."""
    host = ("def switch(flag, handler):\n"
            "    return handler(flag)\n"
            "def on_change(arr):\n"
            "    return float(arr)\n"
            "switch(1, on_change)\n")
    assert check_module("host.py", host) == []
    real = ("from jax.lax import scan\n"
            "def body(carry, _):\n"
            "    if carry > 0:\n"
            "        return carry, carry\n"
            "    return carry - 1, carry\n"
            "scan(body, 0, None, length=3)\n")
    got = check_module("real.py", real)
    assert [f.rule for f in got] == ["GL101"]


def test_suppression_pragma_parsing():
    src = ("x = 1  # graft-lint: disable=GL104 -- why\n"
           "# graft-lint: disable=GL101,GL103\n"
           "y = 2\n")
    sup = F.suppressed_lines(src)
    assert sup[1] == {"GL104"}
    assert sup[2] == {"GL101", "GL103"}    # the comment line itself
    assert sup[3] == {"GL101", "GL103"}    # ...and the line it annotates
    # a justification spilling onto further comment lines must not void
    # the suppression of the code line below it
    multi = ("# graft-lint: disable=GL104 -- this coercion is fine\n"
             "# because the value is a trace-time constant\n"
             "\n"
             "x = float(y)\n")
    assert "GL104" in F.suppressed_lines(multi).get(4, set())


def test_baseline_roundtrip_and_filter(tmp_path):
    f1 = F.Finding("GL104", "a.py", 3, "msg", context="fn")
    f2 = F.Finding("GL101", "b.py", 9, "other", context="g")
    path = str(tmp_path / "base.json")
    F.write_baseline(path, [f1])
    fps = F.load_baseline(path)
    assert F.filter_baseline([f1, f2], fps) == [f2]
    # fingerprints are line-independent: moving the finding keeps it
    moved = F.Finding("GL104", "a.py", 300, "msg", context="fn")
    assert moved.fingerprint == f1.fingerprint


# ---------------------------------------------------------------------------
# registry honesty + the repo gate
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving_programs():
    from deepspeed_tpu.analysis.programs import build_serving_programs
    return build_serving_programs(include_tp=True)


#: leading wrapper-only params of each runner entry point (the jit sees
#: the args after them), mirroring the call-site shift in DISPATCH_DONATIONS
_WRAPPER_OFFSET = {"frame_loop": 0, "frame_loop_spec": 1, "mixed_loop": 0,
                   "mixed_loop_spec": 1, "decode_loop": 0, "run": 1,
                   "copy_blocks": 0, "scatter_pages": 0}


def test_dispatch_donation_table_matches_live_traces(serving_programs):
    seen = set()
    for prog in serving_programs:
        base = prog.name.split("[")[0]
        if base not in DISPATCH_DONATIONS:
            continue
        seen.add(base)
        expect = tuple(sorted(i + _WRAPPER_OFFSET[base]
                              for i in prog.donate_user_args))
        assert tuple(sorted(DISPATCH_DONATIONS[base])) == expect, (
            f"{base}: DISPATCH_DONATIONS says "
            f"{sorted(DISPATCH_DONATIONS[base])}, live trace donates "
            f"{expect} — a loop grew/lost a carry; update ast_checks")
    assert seen == set(DISPATCH_DONATIONS), (
        f"programs registry no longer traces {set(DISPATCH_DONATIONS) - seen}")


def test_registry_completeness_against_dispatch_sites(serving_programs):
    """Every dispatch site in DISPATCH_DONATIONS is traced in its FULL
    production variant matrix: both tp degrees for the shard_map loops,
    both widths for the frame loops (a draft engine dispatches its WIDE
    prefill frames through frame_loop_spec too), and the
    nonfinite_policy="repair" twins of every frame program. A new serving
    loop that registers its donation contract but not its trace cannot
    slip past Family A (GL001-GL004) — and Family C shares this registry,
    so it cannot skip the cost ledger either."""
    names = {p.name for p in serving_programs}
    expected = set()
    for tp in ("", "[tp=8]"):
        for w in ("w=1", "w=8"):
            expected |= {f"frame_loop[{w}]{tp}",
                         f"frame_loop[{w},repair]{tp}",
                         f"frame_loop_spec[{w}]{tp}",
                         f"frame_loop_spec[{w},repair]{tp}"}
        expected |= {f"mixed_loop{tp}", f"mixed_loop_spec{tp}"}
    # host-step + page-mover programs never compile under shard_map
    expected |= {"decode_loop", "run[chunk=8]", "copy_blocks",
                 "scatter_pages", "gather_pages"}
    missing = expected - names
    assert not missing, f"registry is missing production variants: " \
                        f"{sorted(missing)}"
    # ...and the matrix covers every donation-contract dispatch site
    bases = {n.split("[")[0] for n in expected}
    assert set(DISPATCH_DONATIONS) <= bases


def test_repo_lint_clean(serving_programs):
    """THE regression gate: both families over the real repo, clean modulo
    the committed baseline — the static twin of the serving parity suites.
    Reuses the module-scoped traced programs (the expensive half)."""
    from deepspeed_tpu.analysis.lint import run_ast_family
    findings, sources = run_ast_family([PKG])
    for prog in serving_programs:
        findings.extend(check_program(prog))
    findings = F.apply_suppressions(findings, sources)
    new = F.filter_baseline(findings, F.load_baseline(BASELINE))
    assert not new, "new graft-lint findings:\n" + "\n".join(
        f.render() for f in new)


def test_cli_ast_only_smoke():
    """bin/dstpu_lint surface: --ast-only --format json runs without jax
    and exits 0 on the (clean) repo."""
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", "--ast-only",
         "--format", "json", "--baseline", BASELINE, PKG],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    data = json.loads(out.stdout)
    assert data["findings"] == []


def test_cli_broken_baseline_is_internal_error_not_findings(tmp_path):
    """A corrupt/mismatched baseline must exit 2 (internal error), never 1
    — CI gates on 1 meaning 'new findings'."""
    bad_base = tmp_path / "base.json"
    bad_base.write_text("{not json")
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", "--ast-only",
         "--baseline", str(bad_base), PKG],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "cannot read baseline" in out.stderr
    # a typo'd (nonexistent) baseline path must not silently run
    # baseline-less either
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", "--ast-only",
         "--baseline", str(tmp_path / "no-such.json"), PKG],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    # a typo'd SCAN path must not report "clean" on zero files either
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", "--ast-only",
         str(tmp_path / "no-such-dir")],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "no such file" in out.stderr


def test_wrapper_ast_only_skips_framework_import():
    """bin/dstpu_lint --ast-only loads the analyzer standalone: the
    deepspeed_tpu package (and with it jax, on vanilla environments) is
    never imported — the pre-commit-speed contract."""
    probe = ("import sys, runpy\n"
             "sys.argv = ['dstpu_lint', '--ast-only',\n"
             f"            {os.path.join(PKG, 'analysis')!r}]\n"
             "try:\n"
             f"    runpy.run_path({os.path.join(ROOT, 'bin', 'dstpu_lint')!r},"
             " run_name='__main__')\n"
             "except SystemExit as e:\n"
             "    assert e.code == 0, e.code\n"
             "assert 'deepspeed_tpu' not in sys.modules, 'package imported'\n")
    out = subprocess.run([sys.executable, "-c", probe], cwd=ROOT,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_exits_nonzero_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "@jax.jit\n"
                   "def f(x):\n"
                   "    return float(x)\n")
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", "--ast-only",
         str(bad)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "GL104" in out.stdout


def test_baseline_fingerprints_are_cwd_independent(tmp_path):
    """Finding paths anchor to the scanned target's parent, so a baseline
    written from one directory matches when lint runs from another — the
    third-party --write-baseline adoption flow."""
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "@jax.jit\n"
                   "def f(x):\n"
                   "    return float(x)\n")
    base = tmp_path / "base.json"
    args = [sys.executable, "-m", "deepspeed_tpu.analysis.lint",
            "--ast-only", "--baseline", str(base)]
    wrote = subprocess.run(args + ["--write-baseline", str(bad)],
                           cwd=str(tmp_path), capture_output=True,
                           text=True, timeout=120,
                           env={**os.environ, "PYTHONPATH": ROOT})
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    for cwd in (str(tmp_path), ROOT):
        out = subprocess.run(args + [str(bad)], cwd=cwd,
                             capture_output=True, text=True, timeout=120,
                             env={**os.environ, "PYTHONPATH": ROOT})
        assert out.returncode == 0, (cwd, out.stdout, out.stderr)
    # ...and across scan granularities: inside a repo root marker, the
    # whole-dir scan and the single-file scan fingerprint identically
    (tmp_path / "setup.py").write_text("")
    for target in (str(bad), str(tmp_path)):
        out = subprocess.run(args + [target], cwd=ROOT,
                             capture_output=True, text=True, timeout=120,
                             env={**os.environ, "PYTHONPATH": ROOT})
        assert out.returncode == 0, (target, out.stdout, out.stderr)
