"""Block-sparse attention + compressed-comm tests (reference:
tests/unit/ops/sparse_attention, tests/unit/onebit)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention)
from deepspeed_tpu.ops.attention import reference_attention
from deepspeed_tpu.runtime.comm.compressed import CompressedBackend
from deepspeed_tpu.utils import groups

pytestmark = pytest.mark.usefixtures("mesh_8dp")


def _qkv(rng, b=1, s=64, h=2, d=16):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


def test_dense_layout_matches_full_attention(rng):
    q, k, v = _qkv(rng)
    attn = SparseSelfAttention(DenseSparsityConfig(num_heads=2, block=16))
    out = attn(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fixed_layout_properties():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              attention="unidirectional")
    layout = cfg.make_layout(128)
    assert layout.shape == (8, 8)
    assert layout[0, 0]                        # diagonal always attended
    assert not layout[0, 7]                    # causal
    assert layout.sum() < 64                   # actually sparse


def test_bigbird_and_longformer_layouts():
    bb = BigBirdSparsityConfig(num_heads=2, block=16).make_layout(128)
    lf = BSLongformerSparsityConfig(num_heads=2, block=16).make_layout(128)
    for layout in (bb, lf):
        assert layout.shape == (8, 8)
        assert all(layout[i, i] for i in range(8))     # sliding window hits diag
        assert layout[:, 0].all()                      # global block 0


def test_sparse_output_differs_from_dense(rng):
    q, k, v = _qkv(rng, s=128)
    sparse = SparseSelfAttention(FixedSparsityConfig(num_heads=2, block=16,
                                                     num_local_blocks=2,
                                                     attention="unidirectional"))
    out = sparse(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    assert not np.allclose(np.asarray(out), np.asarray(ref), atol=1e-3)
    assert np.all(np.isfinite(np.asarray(out)))


def test_compressed_allreduce_error_feedback(rng):
    """Error-feedback guarantee: for a repeated signal, the cumulative sum of
    compressed allreduce outputs tracks the cumulative true sum (the residual
    stays bounded instead of growing), so the time-averaged error → 0."""
    n = 8
    rounds = 16
    backend = CompressedBackend("data")
    contrib = jax.random.normal(rng, (n, 512)) + 0.05
    true = np.asarray(jnp.sum(contrib, axis=0))
    approx_acc = np.zeros((n, 512))
    rels = []
    for i in range(rounds):
        out = backend.compressed_allreduce(contrib, key="g")
        approx_acc += np.asarray(out)
        rels.append(np.abs(approx_acc / (i + 1) - true[None]).mean() / np.abs(true).mean())
    assert rels[-1] < rels[0] * 0.5, rels      # time-average converges
    assert rels[-1] < 0.3, rels[-1]
    # and every rank sees the same reduced values
    out = np.asarray(backend.compressed_allreduce(contrib, key="g"))
    assert np.abs(out - out[0]).max() < 1e-4


def test_onebit_adam_compressed_stage_engine():
    """After freeze_step the engine's train step exchanges SIGN-COMPRESSED
    momentum through the error-feedback allreduce (reference onebit/adam.py
    compressed stage) instead of full-precision gradients — and training
    keeps converging through the stage transition."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    model = build_model("tiny")
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "OneBitAdam",
                         "params": {"lr": 1e-3, "freeze_step": 6}},
           "zero_optimization": {"stage": 0},
           "steps_per_print": 10 ** 9}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    losses = [float(engine.train_batch({"input_ids": ids, "labels": ids}))
              for _ in range(12)]
    assert losses[-1] < losses[5], losses
    # the compressed stage actually engaged, with live error feedback
    assert engine._onebit_errors is not None
    w = np.asarray(jax.tree.leaves(engine._onebit_errors)[0])
    assert float(np.abs(w).sum()) > 0


def test_onebit_adam_rejects_zero_sharding():
    import pytest
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2},
           "steps_per_print": 10 ** 9}
    with pytest.raises(NotImplementedError):
        ds.initialize(model=build_model("tiny"), config=cfg)


def test_splash_kernel_matches_dense():
    """Block-skipping splash kernel (fwd Pallas, dense-recompute bwd)
    reproduces the dense masked form for fixed and bigbird layouts,
    including causal masking and grads."""
    from deepspeed_tpu.ops.pallas.sparse_flash import sparse_flash_attention
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 256, 4, 64
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    for causal in (False, True):
        for cfg in (FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=4),
                    BigBirdSparsityConfig(num_heads=H, block=16)):
            layout = cfg.make_layout(S)
            dense = SparseSelfAttention(cfg)
            ref = dense(q, k, v, causal=causal, use_kernel=False)
            got = sparse_flash_attention(q, k, v, layout, layout_block=16,
                                         causal=causal or cfg.attention == "unidirectional")
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       atol=5e-3, rtol=1e-2)
            g1 = jax.grad(lambda q: jnp.sum(sparse_flash_attention(
                q, k, v, layout, layout_block=16, causal=causal).astype(jnp.float32) ** 2))(q)
            g2 = jax.grad(lambda q: jnp.sum(dense(
                q, k, v, causal=causal, use_kernel=False).astype(jnp.float32) ** 2))(q)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       atol=5e-2, rtol=5e-2)


def test_splash_tables_under_jit():
    """precompile_layout keeps mask tensors out of the compile payload:
    the kernel runs under an outer jit with tables as runtime args."""
    from deepspeed_tpu.ops.pallas.sparse_flash import (precompile_layout,
                                                       sparse_flash_attention)
    rng = np.random.default_rng(1)
    B, S, H, D = 1, 256, 4, 64
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=4)
    tables = precompile_layout(cfg.make_layout(S), 16)
    f = jax.jit(lambda q, k, v, t: sparse_flash_attention(
        q, k, v, layout_block=16, tables=t))
    out = f(q, k, v, tables)
    ref = sparse_flash_attention(q, k, v, cfg.make_layout(S), layout_block=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_onebit_adam_compressed_under_tp():
    """r4 review: the pure-data-mesh restriction was this repo's own, not
    the reference's (its 1-bit exchange runs over the DP group regardless of
    MP). data x tensor: the compressed step's manual-data exchange composes
    with auto tensor sharding and matches the pure-data trajectory."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups

    def run(mesh_kw):
        import jax as _jax
        groups.reset_mesh()
        ndev = 1
        for v in mesh_kw.values():
            ndev *= v
        groups.set_mesh(groups.build_mesh(
            **mesh_kw, devices=_jax.devices()[:ndev]))
        cfg = {"train_batch_size": 16,
               "optimizer": {"type": "OneBitAdam",
                             "params": {"lr": 1e-3, "freeze_step": 3}},
               "zero_optimization": {"stage": 0},
               "steps_per_print": 10 ** 9, "seed": 5}
        engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (16, 32))
        return [float(engine.train_batch({"input_ids": ids, "labels": ids}))
                for _ in range(6)]

    ref = run({"data": 4})
    got = run({"data": 4, "tensor": 2})
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
