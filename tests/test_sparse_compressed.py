"""Block-sparse attention + compressed-comm tests (reference:
tests/unit/ops/sparse_attention, tests/unit/onebit)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention)
from deepspeed_tpu.ops.attention import reference_attention
from deepspeed_tpu.runtime.comm.compressed import CompressedBackend
from deepspeed_tpu.utils import groups

pytestmark = pytest.mark.usefixtures("mesh_8dp")


def _qkv(rng, b=1, s=64, h=2, d=16):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


def test_dense_layout_matches_full_attention(rng):
    q, k, v = _qkv(rng)
    attn = SparseSelfAttention(DenseSparsityConfig(num_heads=2, block=16))
    out = attn(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fixed_layout_properties():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              attention="unidirectional")
    layout = cfg.make_layout(128)
    assert layout.shape == (8, 8)
    assert layout[0, 0]                        # diagonal always attended
    assert not layout[0, 7]                    # causal
    assert layout.sum() < 64                   # actually sparse


def test_bigbird_and_longformer_layouts():
    bb = BigBirdSparsityConfig(num_heads=2, block=16).make_layout(128)
    lf = BSLongformerSparsityConfig(num_heads=2, block=16).make_layout(128)
    for layout in (bb, lf):
        assert layout.shape == (8, 8)
        assert all(layout[i, i] for i in range(8))     # sliding window hits diag
        assert layout[:, 0].all()                      # global block 0


def test_sparse_output_differs_from_dense(rng):
    q, k, v = _qkv(rng, s=128)
    sparse = SparseSelfAttention(FixedSparsityConfig(num_heads=2, block=16,
                                                     num_local_blocks=2,
                                                     attention="unidirectional"))
    out = sparse(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    assert not np.allclose(np.asarray(out), np.asarray(ref), atol=1e-3)
    assert np.all(np.isfinite(np.asarray(out)))


def test_compressed_allreduce_error_feedback(rng):
    """Error-feedback guarantee: for a repeated signal, the cumulative sum of
    compressed allreduce outputs tracks the cumulative true sum (the residual
    stays bounded instead of growing), so the time-averaged error → 0."""
    n = 8
    rounds = 16
    backend = CompressedBackend("data")
    contrib = jax.random.normal(rng, (n, 512)) + 0.05
    true = np.asarray(jnp.sum(contrib, axis=0))
    approx_acc = np.zeros((n, 512))
    rels = []
    for i in range(rounds):
        out = backend.compressed_allreduce(contrib, key="g")
        approx_acc += np.asarray(out)
        rels.append(np.abs(approx_acc / (i + 1) - true[None]).mean() / np.abs(true).mean())
    assert rels[-1] < rels[0] * 0.5, rels      # time-average converges
    assert rels[-1] < 0.3, rels[-1]
    # and every rank sees the same reduced values
    out = np.asarray(backend.compressed_allreduce(contrib, key="g"))
    assert np.abs(out - out[0]).max() < 1e-4
