"""Native op + offload tests (reference pattern: tests/unit/ops/aio,
tests/unit/ops/adam/test_cpu_adam.py, ZeRO-Offload configs)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


def _native_available():
    from deepspeed_tpu.ops.op_builder import AsyncIOBuilder
    return AsyncIOBuilder().is_compatible()


pytestmark = pytest.mark.skipif(not _native_available(), reason="g++ unavailable")


def test_aio_roundtrip(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    h = AsyncIOHandle(queue_depth=4)
    data = np.random.default_rng(0).standard_normal(1 << 16).astype(np.float32)
    path = str(tmp_path / "buf.bin")
    assert h.sync_pwrite(data, path) == 0
    out = np.empty_like(data)
    assert h.sync_pread(out, path) == 0
    np.testing.assert_array_equal(data, out)


def test_aio_async_overlap(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    h = AsyncIOHandle(queue_depth=4)
    bufs = [np.full(1 << 14, i, np.float32) for i in range(8)]
    for i, b in enumerate(bufs):
        h.async_pwrite(b, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    outs = [np.empty(1 << 14, np.float32) for _ in range(8)]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, bufs[i])


def test_cpu_adam_native_matches_fused():
    """Native AVX AdamW must match the XLA FusedAdam trajectory."""
    from deepspeed_tpu.ops.cpu_adam_native import cpu_adam_step
    from deepspeed_tpu.ops.optimizers import FusedAdam

    rng = np.random.default_rng(0)
    p0 = rng.standard_normal(1024).astype(np.float32)

    # native
    p_n = p0.copy()
    m = np.zeros_like(p_n)
    v = np.zeros_like(p_n)
    # jax reference
    opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    params = {"x": jnp.asarray(p0)}
    state = opt.init(params)

    for step in range(1, 6):
        g = rng.standard_normal(1024).astype(np.float32)
        cpu_adam_step(p_n, g, m, v, step, 1e-2, weight_decay=0.01)
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)

    np.testing.assert_allclose(p_n, np.asarray(params["x"]), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(m, np.asarray(state["slots"]["x"]["m"]), atol=1e-6)


def test_optimizer_swapper_roundtrip(tmp_path):
    from deepspeed_tpu.runtime.swap_tensor.swapper import OptimizerSwapper
    state = {"step": np.int32(3),
             "slots": {"a": {"m": np.arange(64, dtype=np.float32),
                             "v": np.ones(64, np.float32)}}}
    sw = OptimizerSwapper(str(tmp_path))
    sw.swap_out_optimizer(state)
    back = sw.swap_in_optimizer()
    np.testing.assert_array_equal(back["slots"]["a"]["m"], state["slots"]["a"]["m"])
    assert int(back["step"]) == 3


def test_engine_nvme_offload(tmp_path, mesh_8dp):
    """ZeRO-2 + NVMe optimizer offload trains and matches no-offload run."""
    def run(offload):
        groups.reset_mesh()
        model = build_model("tiny")
        cfg = {
            "train_batch_size": 16,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
            "seed": 7,
        }
        if offload:
            cfg["zero_optimization"]["offload_optimizer"] = {
                "device": "nvme", "nvme_path": str(tmp_path)}
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (16, 32))
        batch = {"input_ids": ids, "labels": ids}
        return [float(engine.train_batch(batch)) for _ in range(3)]

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)
    assert any("optimizer" in d for d in os.listdir(tmp_path))


def test_engine_cpu_offload_config(mesh_8dp):
    """CPU offload config path: runs (host memory kind if supported, else
    transparently stays in device memory)."""
    model = build_model("tiny")
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    assert engine.optimizer.name == "cpu_adam"   # offload selects CPUAdam
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    loss = engine.train_batch({"input_ids": ids, "labels": ids})
    assert np.isfinite(float(loss))
