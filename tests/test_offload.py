"""Native op + offload tests (reference pattern: tests/unit/ops/aio,
tests/unit/ops/adam/test_cpu_adam.py, ZeRO-Offload configs)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


def _native_available():
    from deepspeed_tpu.ops.op_builder import AsyncIOBuilder
    return AsyncIOBuilder().is_compatible()


pytestmark = pytest.mark.skipif(not _native_available(), reason="g++ unavailable")


def test_aio_roundtrip(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    h = AsyncIOHandle(queue_depth=4)
    data = np.random.default_rng(0).standard_normal(1 << 16).astype(np.float32)
    path = str(tmp_path / "buf.bin")
    assert h.sync_pwrite(data, path) == 0
    out = np.empty_like(data)
    assert h.sync_pread(out, path) == 0
    np.testing.assert_array_equal(data, out)


def test_aio_async_overlap(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    h = AsyncIOHandle(queue_depth=4)
    bufs = [np.full(1 << 14, i, np.float32) for i in range(8)]
    for i, b in enumerate(bufs):
        h.async_pwrite(b, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    outs = [np.empty(1 << 14, np.float32) for _ in range(8)]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"f{i}.bin"))
    assert h.wait() == 0
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, bufs[i])


def test_cpu_adam_native_matches_fused():
    """Native AVX AdamW must match the XLA FusedAdam trajectory."""
    from deepspeed_tpu.ops.cpu_adam_native import cpu_adam_step
    from deepspeed_tpu.ops.optimizers import FusedAdam

    rng = np.random.default_rng(0)
    p0 = rng.standard_normal(1024).astype(np.float32)

    # native
    p_n = p0.copy()
    m = np.zeros_like(p_n)
    v = np.zeros_like(p_n)
    # jax reference
    opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    params = {"x": jnp.asarray(p0)}
    state = opt.init(params)

    for step in range(1, 6):
        g = rng.standard_normal(1024).astype(np.float32)
        cpu_adam_step(p_n, g, m, v, step, 1e-2, weight_decay=0.01)
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)

    np.testing.assert_allclose(p_n, np.asarray(params["x"]), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(m, np.asarray(state["slots"]["x"]["m"]), atol=1e-6)


def test_optimizer_swapper_roundtrip(tmp_path):
    from deepspeed_tpu.runtime.swap_tensor.swapper import OptimizerSwapper
    state = {"step": np.int32(3),
             "slots": {"a": {"m": np.arange(64, dtype=np.float32),
                             "v": np.ones(64, np.float32)}}}
    sw = OptimizerSwapper(str(tmp_path))
    sw.swap_out_optimizer(state)
    back = sw.swap_in_optimizer()
    np.testing.assert_array_equal(back["slots"]["a"]["m"], state["slots"]["a"]["m"])
    assert int(back["step"]) == 3


class _FailingAIO:
    """aio stub that lands a truncated write, then reports errors from
    wait() — the scenario that used to leave a partial .swp behind."""

    def __init__(self, errs=1):
        self.errs = errs

    def async_pwrite(self, arr, path):
        with open(path, "wb") as f:
            f.write(b"partial")

    def async_pread(self, arr, path):
        raise AssertionError("no reads expected")

    def wait(self):
        return self.errs


def test_swapper_failed_swap_out_cleans_up(tmp_path):
    """An aio error during swap_out must not leave a partial .swp (or the
    .swp.tmp staging file) behind, must drop the key's metadata, and must
    name the key in the raised error."""
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path), aio_handle=_FailingAIO())
    with pytest.raises(IOError, match="opt_3"):
        sw.swap_out("opt_3", np.arange(8, dtype=np.float32))
    assert list(tmp_path.iterdir()) == []      # nothing stranded on disk
    assert "opt_3" not in sw._meta             # no stale metadata either
    assert not sw._pending


def test_swapper_failed_overwrite_preserves_previous(tmp_path):
    """Atomicity: a failed RE-swap of an existing key leaves the previous
    .swp contents AND metadata intact — swap_in still returns the last
    successfully committed array, not garbage from a truncated write."""
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path))
    first = np.arange(16, dtype=np.float32)
    sw.swap_out("k", first)
    assert (tmp_path / "k.swp").exists()
    assert not (tmp_path / "k.swp.tmp").exists()   # tmp renamed away

    sw.aio = _FailingAIO()
    with pytest.raises(IOError, match="k"):
        sw.swap_out("k", np.ones((4, 4), np.float64))
    assert not (tmp_path / "k.swp.tmp").exists()   # staging file removed

    sw.aio = AsyncIOHandle()
    back = sw.swap_in("k")                         # previous commit intact
    np.testing.assert_array_equal(back, first)


def test_swapper_swap_in_finalizes_pending_writes(tmp_path):
    """swap_in on a swapper with un-waited async writes must finalize them
    through the atomic-commit/rollback path first — draining the shared
    aio queue bare would eat the write errors, and a later wait() would
    then happily rename the truncated tmp over the good .swp."""
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path))
    good = np.arange(8, dtype=np.float32)
    sw.swap_out("k", good)                            # committed
    sw.aio = _FailingAIO()
    sw.swap_out("k", np.ones(16, np.float32), async_op=True)
    with pytest.raises(IOError, match="k"):
        sw.swap_in("k")               # surfaces the in-flight write error
    sw.aio = AsyncIOHandle()
    assert sw.wait() == 0             # nothing left behind to mis-commit
    np.testing.assert_array_equal(sw.swap_in("k"), good)


class _DeferredAIO:
    """aio stub whose writes EXECUTE only at wait() — modeling a queued
    async write still sitting in the aio engine when the host moves on."""

    def __init__(self):
        self._queued = []

    def async_pwrite(self, arr, path):
        self._queued.append((bytes(np.ascontiguousarray(arr).tobytes()), path))

    def async_pread(self, arr, path):
        raise AssertionError("no reads expected")

    def wait(self):
        for payload, path in self._queued:
            with open(path, "wb") as f:
                f.write(payload)
        self._queued.clear()
        return 0


def test_swapper_release_drains_inflight_writes(tmp_path):
    """Known issue (b): release() on a key with an un-waited async
    swap_out used to pop the pending record and delete files EAGERLY —
    the still-queued aio write then recreated the just-deleted
    ``.swp.tmp`` after the fact, stranding a staging file (and a later
    wait() had no pending record to finalize or roll it back). release()
    must drain in-flight writes first."""
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path), aio_handle=_DeferredAIO())
    sw.swap_out("k", np.arange(8, dtype=np.float32), async_op=True)
    sw.release("k")                      # write still queued in the engine
    assert sw.wait() == 0
    assert list(tmp_path.iterdir()) == []    # no resurrected .swp.tmp/.swp
    assert "k" not in sw._meta and not sw._pending


def test_swapper_release_drain_commits_siblings(tmp_path):
    """Draining inside release() must finalize SIBLING pending writes
    through the normal atomic-commit path, not drop them."""
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path), aio_handle=_DeferredAIO())
    keep = np.arange(4, dtype=np.float32)
    sw.swap_out("keep", keep, async_op=True)
    sw.swap_out("gone", np.ones(4, np.float32), async_op=True)
    sw.release("gone")
    assert sorted(p.name for p in tmp_path.iterdir()) == ["keep.swp"]
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    sw.aio = AsyncIOHandle()
    np.testing.assert_array_equal(sw.swap_in("keep"), keep)


def test_swapper_adopt_cross_instance(tmp_path):
    """adopt(): a fresh swapper instance reads a committed .swp written by
    a previous one (crash-recovery path for the KV swap tier)."""
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    first = AsyncTensorSwapper(str(tmp_path))
    data = np.arange(12, dtype=np.float32).reshape(3, 4)
    first.swap_out("x", data)
    fresh = AsyncTensorSwapper(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        fresh.adopt("missing", (1,), np.float32)
    fresh.adopt("x", data.shape, data.dtype)
    np.testing.assert_array_equal(fresh.swap_in("x"), data)


def test_swapper_async_batch_failure_names_keys(tmp_path):
    """The async path (OptimizerSwapper's batched swap_out) finalizes at
    wait(): on error every pending write rolls back and the raise names
    the in-flight keys."""
    from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
    sw = AsyncTensorSwapper(str(tmp_path), aio_handle=_FailingAIO())
    sw.swap_out("a", np.zeros(4, np.float32), async_op=True)
    sw.swap_out("b", np.ones(4, np.float32), async_op=True)
    with pytest.raises(IOError, match="a, b"):
        sw.wait()
    assert list(tmp_path.iterdir()) == []
    assert not sw._meta and not sw._pending


def test_engine_nvme_offload(tmp_path, mesh_8dp):
    """ZeRO-2 + NVMe optimizer offload trains and matches no-offload run."""
    def run(offload):
        groups.reset_mesh()
        model = build_model("tiny")
        cfg = {
            "train_batch_size": 16,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
            "seed": 7,
        }
        if offload:
            cfg["zero_optimization"]["offload_optimizer"] = {
                "device": "nvme", "nvme_path": str(tmp_path)}
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (16, 32))
        batch = {"input_ids": ids, "labels": ids}
        return [float(engine.train_batch(batch)) for _ in range(3)]

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)
    assert any("optimizer" in d for d in os.listdir(tmp_path))


def test_engine_cpu_offload_config(mesh_8dp):
    """CPU offload config path: runs (host memory kind if supported, else
    transparently stays in device memory)."""
    model = build_model("tiny")
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    assert engine.optimizer.name == "cpu_adam"   # offload selects CPUAdam
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    loss = engine.train_batch({"input_ids": ids, "labels": ids})
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# ZeRO-Infinity layer streaming (runtime/zero/infinity.py)
# ---------------------------------------------------------------------------

def _infinity_config(device="cpu", nvme_path=None, group_layers=1):
    zo = {"stage": 3,
          "offload_param": {"device": device,
                            **({"nvme_path": nvme_path} if nvme_path else {}),
                            "buffer_count": 2},
          "stream_group_layers": group_layers}
    return {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "steps_per_print": 10 ** 9,
        "seed": 11,
    }


def _ref_losses(steps=3):
    """Plain single-device fp32 run with the same seed/init for parity."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    model = build_model("tiny")
    cfg = {
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10 ** 9, "seed": 11,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, (8, 32))
    batch = {"input_ids": ids, "labels": ids}
    return [float(engine.train_batch(batch)) for _ in range(steps)]


def test_infinity_streaming_matches_plain():
    """Layer-streaming ZeRO-Infinity must track a plain fp32 run closely
    (same init seed; host CPUAdam vs jnp Adam are same math)."""
    ref = _ref_losses()
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_infinity_config("cpu"))
    assert engine._infinity is not None
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, (8, 32))
    batch = {"input_ids": ids, "labels": ids}
    got = [float(engine.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-4)
    # device residence bounded: at most 2 groups staged at any time
    assert engine._infinity.max_dev_groups <= 2


def test_infinity_nvme_roundtrip(tmp_path):
    """NVMe residence: group files on disk, RAM ring bounded, training sane,
    checkpoint save/load round-trips."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    model = build_model("tiny", num_layers=4)  # 4 groups > buffer ring of 2
    engine, _, _, _ = ds.initialize(
        model=model, config=_infinity_config("nvme", nvme_path=str(tmp_path)))
    run = engine._infinity
    assert run.store.nvme
    import os as _os
    swaps = [f for f in _os.listdir(_os.path.join(str(tmp_path), "params")) if f.endswith(".swp")]
    assert swaps, "no NVMe group files written"
    assert run.store.max_resident <= run.store.buffer_count + 1
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, (8, 32))
    batch = {"input_ids": ids, "labels": ids}
    l0 = float(engine.train_batch(batch))
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert losses[-1] < l0, (l0, losses)
    engine.save_checkpoint(str(tmp_path / "ckpt"), tag="t")
    engine2, _, _, _ = ds.initialize(
        model=build_model("tiny", num_layers=4),
        config=_infinity_config("nvme", nvme_path=str(tmp_path / "n2")))
    engine2.load_checkpoint(str(tmp_path / "ckpt"), tag="t")
    l1 = float(engine.train_batch(batch))
    l2 = float(engine2.train_batch(batch))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_native_host_offload_matches_device(mesh_8dp):
    """offload_optimizer.device=cpu with native=true routes the update
    through the host CPUAdam kernel on fp32 masters; the loss trajectory
    must track the all-device engine."""
    def run(native):
        from deepspeed_tpu.utils import groups
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=8))
        model = build_model("tiny")
        cfg = {
            "train_batch_size": 16,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10 ** 9,
        }
        if native:
            cfg["zero_optimization"]["offload_optimizer"] = {
                "device": "cpu", "native": True}
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(4):
            ids = rng.integers(0, 256, (16, 32))
            losses.append(float(engine.train_batch({"input_ids": ids, "labels": ids})))
        return losses, engine

    ref, _ = run(False)
    got, engine = run(True)
    assert engine._host_optimizer is not None
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


def test_native_host_offload_checkpoint_roundtrip(tmp_path, mesh_8dp):
    """Host-resident optimizer state survives save/load and training
    continues from the restored masters."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    model = build_model("tiny")
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu", "native": True}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (16, 32))
    for _ in range(2):
        engine.train_batch({"input_ids": ids, "labels": ids})
    m_before = np.array(jax.tree.leaves(
        engine._host_optimizer.state_dict()["slots"])[0])
    engine.save_checkpoint(str(tmp_path), tag="t")

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine2, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    m_after = np.array(jax.tree.leaves(
        engine2._host_optimizer.state_dict()["slots"])[0])
    np.testing.assert_allclose(m_before, m_after, rtol=1e-6)
    loss = float(engine2.train_batch({"input_ids": ids, "labels": ids}))
    assert np.isfinite(loss)


def test_zero_init_remote_device_routes_to_infinity(mesh_8dp):
    """zero.Init(remote_device="cpu") is not a no-op: engines constructed
    under it boot the ZeRO-Infinity streaming runner (reference
    partition_parameters.py:808 remote-device semantics)."""
    from deepspeed_tpu.runtime import zero
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    model = build_model("tiny")
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3},
           "steps_per_print": 10 ** 9}
    with zero.Init(remote_device="cpu"):
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
    assert engine._infinity is not None
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (8, 32))
    loss = float(engine.train_batch({"input_ids": ids, "labels": ids}))
    assert np.isfinite(loss)


def test_twinflow_partial_offload_matches_full(mesh_8dp):
    """ZeRO-Offload++ Twin-Flow (offload_optimizer.ratio < 1): half the
    optimizer state on host (CPUAdam), half updated on device — the loss
    trajectory must match the all-device AND all-host engines."""
    def run(offload_cfg):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=8))
        model = build_model("tiny")
        cfg = {
            "train_batch_size": 16,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10 ** 9,
        }
        if offload_cfg:
            cfg["zero_optimization"]["offload_optimizer"] = offload_cfg
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(4):
            ids = rng.integers(0, 256, (16, 32))
            losses.append(float(engine.train_batch({"input_ids": ids, "labels": ids})))
        return losses, engine

    dev, _ = run(None)
    twin, engine = run({"device": "cpu", "native": True, "ratio": 0.5})
    assert engine._twinflow is not None
    mask = engine._twinflow["mask"]
    assert any(mask) and not all(mask)   # genuinely split
    np.testing.assert_allclose(dev, twin, rtol=2e-4, atol=2e-4)


def test_twinflow_checkpoint_roundtrip(tmp_path, mesh_8dp):
    """Both halves of the Twin-Flow optimizer state survive save/load."""
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1, "offload_optimizer": {
            "device": "cpu", "native": True, "ratio": 0.5}},
        "steps_per_print": 10 ** 9,
    }
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (16, 32))
    for _ in range(2):
        engine.train_batch({"input_ids": ids, "labels": ids})
    engine.save_checkpoint(str(tmp_path), tag="t")
    l_ref = float(engine.train_batch({"input_ids": ids, "labels": ids}))

    # restoring the checkpoint must reproduce the post-save step exactly
    # (both optimizer halves restored, merged params correct)
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine2, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    l_replay = float(engine2.train_batch({"input_ids": ids, "labels": ids}))
    np.testing.assert_allclose(l_ref, l_replay, rtol=1e-5)


@pytest.mark.parametrize("ratio", [1.0, 0.5])
def test_universal_checkpoint_restores_host_optimizer(tmp_path, mesh_8dp, ratio):
    """Universal checkpoint ↔ ZeRO-Offload(native): the restored optimizer
    state must land in _host_optimizer (and the Twin-Flow device half), not
    in the unused engine.opt_state — otherwise the first train_batch after a
    restore overwrites the restored weights with init-time masters (advisor
    r4, universal.py:114). Replay-exactness: the post-restore step must
    reproduce the post-save step bit-for-bit trajectory."""
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal_checkpoint)
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1, "offload_optimizer": {
            "device": "cpu", "native": True, "ratio": ratio}},
        "steps_per_print": 10 ** 9,
    }
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (16, 32))
    for _ in range(2):
        engine.train_batch({"input_ids": ids, "labels": ids})
    ds_to_universal(engine, str(tmp_path / "uni"))
    m_before = np.array(jax.tree.leaves(
        engine._host_optimizer.state_dict()["slots"])[0])
    l_ref = float(engine.train_batch({"input_ids": ids, "labels": ids}))

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine2, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    load_universal_checkpoint(engine2, str(tmp_path / "uni"))
    assert engine2.global_steps == 2
    m_after = np.array(jax.tree.leaves(
        engine2._host_optimizer.state_dict()["slots"])[0])
    np.testing.assert_allclose(m_before, m_after, rtol=1e-6)
    l_replay = float(engine2.train_batch({"input_ids": ids, "labels": ids}))
    np.testing.assert_allclose(l_ref, l_replay, rtol=1e-5)


def test_multiprocess_sharded_host_offload(tmp_path):
    """TRUE multi-process ZeRO-Offload (reference stage_1_and_2.py:1189 +
    cpu_adam.cpp: CPU optimizer state sharded per DP rank): two OS processes
    (4 CPU devices each) train with the native host CPUAdam. Each process
    must materialize only its own shard of the fp32 masters/moments
    (disjointness asserted on element counts), and the loss trajectory must
    match the same model trained single-process on an 8-device mesh."""
    import json
    import subprocess
    import sys
    import textwrap

    cfg = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu",
                                                    "native": True}},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, %r)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import deepspeed_tpu as ds
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.models import build_model
        from deepspeed_tpu.utils import groups

        dist.init_distributed(verbose=False,
                              distributed_port=int(os.environ["DS_TEST_PORT"]))
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 8, jax.devices()
        groups.reset_mesh()
        model = build_model("tiny")
        engine, _, _, _ = ds.initialize(model=model, config=json.loads(%r))
        opt = engine._host_optimizer
        assert opt is not None
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree.leaves(engine.module_params))
        rng = np.random.default_rng(0)
        losses = []
        for i in range(3):
            ids = rng.integers(0, 256, (16, 32))
            losses.append(float(engine.train_batch(
                {"input_ids": ids, "labels": ids})))
        print("STATS", json.dumps({
            "rank": jax.process_index(),
            "local": opt.local_element_count(),
            "total": total,
            "losses": losses,
        }))
    """) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            json.dumps(cfg)))

    import socket
    with socket.socket() as s:   # an ephemeral port both workers agree on
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update(MASTER_ADDR="127.0.0.1", WORLD_SIZE="2", JAX_PLATFORMS="cpu",
               DS_TEST_PORT=str(port))
    procs = []
    stats = []
    try:
        for r in range(2):
            e = dict(env, RANK=str(r))
            procs.append(subprocess.Popen([sys.executable, str(worker)], env=e,
                                          stdout=subprocess.PIPE,
                                          stderr=subprocess.STDOUT))
        for p in procs:
            out, _ = p.communicate(timeout=600)
            assert p.returncode == 0, out.decode()[-2000:]
            line = [ln for ln in out.decode().splitlines()
                    if ln.startswith("STATS ")][0]
            stats.append(json.loads(line[len("STATS "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # each rank holds roughly half the optimizer state, and together they
    # cover it all — per-rank FULL replication would put local == total
    total = stats[0]["total"]
    for s in stats:
        assert s["local"] < 0.75 * total, (s["local"], total)
    assert stats[0]["local"] + stats[1]["local"] >= total

    # both ranks observe the same (global) loss
    np.testing.assert_allclose(stats[0]["losses"], stats[1]["losses"],
                               rtol=1e-6)

    # and the trajectory matches the single-process 8-device run
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    rng = np.random.default_rng(0)
    ref = []
    for i in range(3):
        ids = rng.integers(0, 256, (16, 32))
        ref.append(float(engine.train_batch({"input_ids": ids, "labels": ids})))
    np.testing.assert_allclose(ref, stats[0]["losses"], rtol=2e-4, atol=2e-4)


def test_cpu_adagrad_lion_native_match_device():
    """Native host Adagrad and Lion kernels must match the device (XLA)
    optimizer trajectories (reference csrc/adagrad/cpu_adagrad.cpp,
    csrc/lion/cpu_lion.cpp)."""
    from deepspeed_tpu.ops.cpu_adam_native import cpu_adagrad_step, cpu_lion_step
    from deepspeed_tpu.ops.optimizers import FusedAdagrad, FusedLion

    rng = np.random.default_rng(0)
    p0 = rng.standard_normal(1024).astype(np.float32)

    # adagrad
    p_n, acc = p0.copy(), np.zeros_like(p0)
    opt = FusedAdagrad(lr=1e-2, weight_decay=0.01)
    params, state = {"x": jnp.asarray(p0)}, None
    state = opt.init(params)
    for _ in range(5):
        g = rng.standard_normal(1024).astype(np.float32)
        cpu_adagrad_step(p_n, g, acc, 1e-2, weight_decay=0.01)
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)
    np.testing.assert_allclose(p_n, np.asarray(params["x"]), atol=1e-5, rtol=1e-5)

    # lion
    p_n, m = p0.copy(), np.zeros_like(p0)
    opt = FusedLion(lr=1e-3, weight_decay=0.01)
    params, state = {"x": jnp.asarray(p0)}, None
    state = opt.init(params)
    for _ in range(5):
        g = rng.standard_normal(1024).astype(np.float32)
        cpu_lion_step(p_n, g, m, 1e-3, weight_decay=0.01)
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)
    np.testing.assert_allclose(p_n, np.asarray(params["x"]), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(m, np.asarray(state["slots"]["x"]["m"]), atol=1e-6)


@pytest.mark.parametrize("opt_type", ["Adagrad", "Lion"])
def test_native_host_offload_adagrad_lion(opt_type, mesh_8dp):
    """offload_optimizer.device=cpu + native with Adagrad/Lion routes the
    update through the matching native host kernel and tracks the on-device
    engine (the reference's DeepSpeedCPU{Adagrad,Lion})."""
    def run(native):
        from deepspeed_tpu.utils import groups
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=8))
        model = build_model("tiny")
        cfg = {
            "train_batch_size": 16,
            "optimizer": {"type": opt_type, "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
        }
        if native:
            cfg["zero_optimization"]["offload_optimizer"] = {
                "device": "cpu", "native": True}
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(4):
            ids = rng.integers(0, 256, (16, 32))
            losses.append(float(engine.train_batch({"input_ids": ids, "labels": ids})))
        return losses, engine

    ref, _ = run(False)
    got, engine = run(True)
    assert engine._host_optimizer is not None
    assert engine.optimizer.name == f"cpu_{opt_type.lower()}"
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


def test_infinity_gas_matches_plain():
    """Round-4 lift: gradient accumulation under the Infinity streamer —
    gas=2 over micro-4 must track the plain gas=2 engine run."""
    def run(infinity):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
        model = build_model("tiny")
        zo = {"stage": 3 if infinity else 0}
        if infinity:
            zo["offload_param"] = {"device": "cpu", "buffer_count": 2}
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_batch_size": 8, "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": zo, "steps_per_print": 10 ** 9, "seed": 11})
        if infinity:
            assert engine._infinity is not None
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 256, (8, 32))
        batch = {"input_ids": ids, "labels": ids}
        return [float(engine.train_batch(batch)) for _ in range(3)]

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-4)


def test_infinity_moe_het_and_windows():
    """Round-4 lifts: a heterogeneous dense/MoE stack with per-layer window
    patterns streams through Infinity (aux loss included) and trains."""
    from deepspeed_tpu.models.config import TransformerConfig
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
        intermediate_size=128, max_seq_len=128, num_experts=2,
        num_experts_per_tok=1, layer_types=("dense", "moe", "dense", "moe"),
        window_pattern=(16, 0, 16, 0), dtype="float32",
        param_dtype="float32")
    model = build_model(cfg)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": 4, "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "offload_param": {"device": "cpu",
                                                "buffer_count": 2}},
        "steps_per_print": 10 ** 9, "seed": 5})
    assert engine._infinity is not None
    assert engine._infinity._group_tags == [("dense",), ("moe",),
                                            ("dense",), ("moe",)]
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (4, 32))
    batch = {"input_ids": ids, "labels": ids}
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # grouped layer layout survives consolidation
    full = engine._infinity.gathered_params()
    assert set(full["layers"]) == {"g0", "g1"}


def test_infinity_fp16_loss_scaling():
    """Round-4 lift: fp16 under Infinity — the loss scale seeds the
    backward, grads unscale on host, training stays finite and the scaler
    machinery is live."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    from deepspeed_tpu.models import get_config
    model = build_model(get_config("tiny").replace(dtype="float16"))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": 4, "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "offload_param": {"device": "cpu",
                                                "buffer_count": 2}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "steps_per_print": 10 ** 9, "seed": 5})
    assert engine._infinity is not None
    assert float(engine.scaler_state.scale) == 256.0
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (4, 32))
    batch = {"input_ids": ids, "labels": ids}
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_infinity_streaming_bert_encoder():
    """ZeRO-Infinity layer streaming generalizes beyond CausalLM (r4 review:
    the reference's stage3+swap is model-agnostic, stage3.py:109): BERT-tiny
    (post-norm, MLM head, bidirectional + padding mask) streams and tracks
    the plain engine's trajectory."""
    bert_kw = dict(num_layers=2, hidden_size=32, num_heads=4,
                   intermediate_size=64, vocab_size=128, dtype="float32")
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 128, (8, 32))
    labels = np.where(rng.random((8, 32)) < 0.3, ids, -100)
    labels[:, 0] = ids[:, 0]
    mask = np.ones((8, 32), np.int32)
    mask[:, -5:] = 0
    batch = {"input_ids": ids, "labels": labels, "attention_mask": mask}

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    plain, _, _, _ = ds.initialize(
        model=build_model("bert-base", **bert_kw), config={
            "train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 10 ** 9, "seed": 11})
    ref = [float(plain.train_batch(batch)) for _ in range(3)]

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    engine, _, _, _ = ds.initialize(
        model=build_model("bert-base", **bert_kw),
        config=_infinity_config("cpu"))
    assert engine._infinity is not None
    assert "mlm" in engine._infinity.persist["p"]
    assert "final_norm" not in engine._infinity.persist["p"]
    got = [float(engine.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


def test_infinity_mixed_type_stream_groups():
    """group_layers=2 over an interleaved dense/MoE stack: each streaming
    group MIXES layer types (r4 restricted groups to type-homogeneous) —
    the unrolled per-layer dispatch must track the plain het engine."""
    het_kw = dict(vocab_size=256, hidden_size=32, num_layers=4, num_heads=4,
                  intermediate_size=64, moe_intermediate_size=48,
                  num_experts=4, num_experts_per_tok=2, max_seq_len=64,
                  layer_types=("dense", "moe", "dense", "moe"),
                  dtype="float32")
    from deepspeed_tpu.models.config import TransformerConfig
    cfg_m = TransformerConfig(**het_kw)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 256, (8, 32))
    batch = {"input_ids": ids, "labels": ids}

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    plain, _, _, _ = ds.initialize(model=build_model(cfg_m), config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10 ** 9, "seed": 11})
    ref = [float(plain.train_batch(batch)) for _ in range(3)]

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    engine, _, _, _ = ds.initialize(
        model=build_model(TransformerConfig(**het_kw)),
        config=_infinity_config("cpu", group_layers=2))
    run = engine._infinity
    assert run is not None and run.group_layers == 2
    assert all(run._group_mixed), run._group_tags
    got = [float(engine.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
    # zero_to_fp32 path re-assembles the grouped layout from mixed groups
    full = run.gathered_params()
    assert set(full) >= {"embed", "layers"}


def test_infinity_universal_checkpoint_across_group_layouts(tmp_path):
    """Universal checkpoint x ZeRO-Infinity (elastic rejoin calls
    load_universal_checkpoint unconditionally; before r5 this crashed with a
    pytree error): the per-parameter format round-trips ACROSS different
    stream_group_layers — params AND Adam moments — with replay-exactness."""
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal_checkpoint)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, (8, 32))
    batch = {"input_ids": ids, "labels": ids}

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    e1, _, _, _ = ds.initialize(model=build_model("tiny", num_layers=4),
                                config=_infinity_config("cpu", group_layers=1))
    assert e1._infinity is not None
    for _ in range(2):
        e1.train_batch(batch)
    ds_to_universal(e1, str(tmp_path / "uni"))
    l_ref = float(e1.train_batch(batch))

    # restore under a DIFFERENT group layout (2 layers per streaming group)
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=1, devices=jax.devices()[:1]))
    e2, _, _, _ = ds.initialize(model=build_model("tiny", num_layers=4),
                                config=_infinity_config("cpu", group_layers=2))
    load_universal_checkpoint(e2, str(tmp_path / "uni"))
    assert e2._infinity.step_num == e1._infinity.step_num - 1  # pre-replay
    l_replay = float(e2.train_batch(batch))
    np.testing.assert_allclose(l_ref, l_replay, rtol=1e-5)
