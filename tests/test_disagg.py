"""Disaggregated prefill/decode fleet suite (ISSUE 12).

Pins the tentpole contract: role-specialized replicas behind the router —
prefill replicas run wide chunked-prefill frames and publish committed KV
pages into the SHARED ``KVSwapTier`` at the watermark; decode replicas
restore those pages on admission (the PR-8 swap-in path) and stream
tokens — greedy outputs TOKEN-IDENTICAL to the monolithic fleet:

* handoff parity on the FIFO and scheduler paths (single-engine outputs
  are THE reference);
* tp=1 prefill → tp=8 decode cross-degree handoff (``multichip``: pages
  published by an unsharded pool restore into a head-sharded one);
* a prefill replica killed MID-PROMPT fails over with the partial
  watermark restored from the tier (boundary-incremental segment
  publish), not a from-zero re-prefill;
* fleet-wide prefix share: a hot prompt is prefilled once — every later
  identical prompt, on ANY replica, admits from the tier's
  content-addressed prefix record at the watermark with (at most) the
  sub-chunk tail left to prefill;
* async/overlapped swap-out commits (records invisible until drain,
  overlapped-vs-blocking accounting);
* classification and prefill-scoring units;
* none of it adds a device→host transfer inside a frame.

Engines are built per scenario but share shapes (BS/CHUNK match
test_kv_hierarchy), so the frame jit cache stays within the sanitize
retrace budget.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (HandoffEvent,
                                                  InferenceEngineV2,
                                                  RaggedInferenceEngineConfig,
                                                  ServeBoundary)
from deepspeed_tpu.inference.v2.faults import RouterFaultInjector
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.kv_hierarchy import (KVSwapTier,
                                                     token_fingerprint)
from deepspeed_tpu.inference.v2.router import (QUARANTINED, EngineRouter,
                                               RouterConfig)
from deepspeed_tpu.inference.v2.scheduler import RequestScheduler
from deepspeed_tpu.models import build_model

pytestmark = pytest.mark.chaos

BS, CHUNK = 16, 8
MAX_NEW = 8


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


@pytest.fixture(scope="module")
def tiny_model_params():
    # 8 heads: the tp=8 replica's sharded axes divide the virtual mesh
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=BS, prefill_chunk_size=CHUNK,
              max_tokens_per_step=256, dtype="float32",
              max_ragged_batch_size=4, frame_steps=2,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=160)


RNG = np.random.default_rng(11)
LONGS = {u: RNG.integers(0, 200, (48,)).astype(np.int32) for u in (0, 1)}
SHORTS = {u: RNG.integers(0, 200, (6,)).astype(np.int32) for u in (2, 3)}


def _mix_arrivals(session=False, meta=False):
    """Two boundaries of a long-prompt/short-decode + short-prompt mix —
    the workload disaggregation exists for. Long rows carry a small
    budget (classified prefill-heavy at the default ratio), short rows a
    large one (decode-heavy)."""
    def item(u, toks, limit):
        d = {"uid": u, "tokens": toks, "max_new_tokens": limit}
        if session:
            d["session"] = f"s{u % 2}"
        if meta:
            d["tenant"] = f"t{u % 2}"
            d["priority"] = "interactive" if u % 2 else "batch"
        return d
    yield [item(0, LONGS[0], 4), item(2, SHORTS[2], MAX_NEW)]
    yield [item(1, LONGS[1], 4), item(3, SHORTS[3], MAX_NEW)]


def _fleet(model, params, tmp_path, roles=("prefill", "decode"), **over):
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    engines = {}
    for i, role in enumerate(roles):
        eng = _engine(model, params, role=role, **over.get(role, {}))
        eng.attach_kv_tier(tier, tag=f"e{i}")
        engines[f"{role}{i}"] = eng
    return engines, tier


def _assert_clean(eng):
    assert eng.kv.free_blocks == eng.kv.num_blocks - 1
    assert not eng.state.seqs
    assert not eng._ledger


def _assert_parity(outs, base, uids=None):
    uids = set(base) if uids is None else set(uids)
    assert set(outs) >= uids
    for u in uids:
        assert np.array_equal(outs[u], base[u]), \
            f"uid={u}: {outs[u]} != {base[u]}"


@pytest.fixture(scope="module")
def greedy_base(tiny_model_params):
    """Monolithic single-engine outputs — THE parity target."""
    model, params = tiny_model_params
    eng = _engine(model, params)
    return dict(eng.serve(_mix_arrivals(), max_new_tokens=MAX_NEW))


# ---------------------------------------------------------------------------
# units (no fleets served)
# ---------------------------------------------------------------------------


def test_classification_heuristic(tiny_model_params, tmp_path):
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "t"), shared=True)
    pe = _engine(model, params, role="prefill")
    pe.attach_kv_tier(tier, tag="p")
    de = _engine(model, params)
    de.attach_kv_tier(tier, tag="d")
    router = EngineRouter({"p": pe, "d": de},
                         RouterConfig(prefill_route_min_prompt=16,
                                      prefill_route_ratio=4.0))
    router._serve_limit = 8
    long_item = {"uid": 0, "tokens": LONGS[0], "max_new_tokens": 4}
    short_item = {"uid": 1, "tokens": SHORTS[2], "max_new_tokens": 16}
    assert router._classify(long_item) == "prefill"
    assert router._classify(short_item) == "decode"
    # committed tokens ⇒ prefill already happened ⇒ decode, regardless of
    # prompt length (the handoff/failover resume rule)
    resumed = dict(long_item, generated=[5])
    assert router._classify(resumed) == "decode"
    # a queued migration (generated=[]) re-classifies like a fresh arrival
    migrated = dict(long_item, generated=[])
    assert router._classify(migrated) == "prefill"
    # below the absolute floor, the ratio alone never prefill-routes
    tiny_item = {"uid": 2, "tokens": SHORTS[3], "max_new_tokens": 1}
    assert router._classify(tiny_item) == "decode"
    # tuple arrivals classify too
    assert router._classify((3, LONGS[0], 4)) == "prefill"
    # role-blind fleet: classification disabled
    blind = EngineRouter({"a": _engine(model, params)})
    assert blind._classify(long_item) == "any"


def test_prefill_scoring_by_queued_tokens(tiny_model_params, tmp_path):
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "t"), shared=True)
    p0 = _engine(model, params, role="prefill")
    p1 = _engine(model, params, role="prefill")
    de = _engine(model, params)
    for i, e in enumerate((p0, p1, de)):
        e.attach_kv_tier(tier, tag=f"s{i}")
    router = EngineRouter({"p0": p0, "p1": p1, "d": de},
                         RouterConfig(prefill_route_min_prompt=16))
    router._serve_limit = 4
    # seed p0's feed with a long prompt: p1 must win the next placement
    assert router._place({"uid": 7, "tokens": LONGS[0],
                          "max_new_tokens": 4})
    first = router._assignment[7]
    assert router._place({"uid": 8, "tokens": LONGS[1],
                          "max_new_tokens": 4})
    second = router._assignment[8]
    assert {first, second} == {"p0", "p1"}, \
        "queued-prompt-token scoring must spread prefill load"
    # decode-heavy arrivals never land on a prefill replica while a
    # decode/unified one accepts
    assert router._place({"uid": 9, "tokens": SHORTS[2],
                          "max_new_tokens": 16})
    assert router._assignment[9] == "d"


def test_router_validates_shared_tier(tiny_model_params, tmp_path):
    model, params = tiny_model_params
    pe = _engine(model, params, role="prefill")
    de = _engine(model, params)
    with pytest.raises(ValueError, match="no KV swap tier"):
        EngineRouter({"p": pe, "d": de})
    pe.attach_kv_tier(KVSwapTier(str(tmp_path / "a"), shared=True))
    # a tier-less DECODE replica is rejected too: handoffs placed on it
    # would silently re-prefill instead of restoring pages
    with pytest.raises(ValueError, match="no KV swap tier"):
        EngineRouter({"p": pe, "d": de})
    de.attach_kv_tier(KVSwapTier(str(tmp_path / "b"), shared=True))
    with pytest.raises(ValueError, match="share ONE KVSwapTier"):
        EngineRouter({"p": pe, "d": de})
    unshared = KVSwapTier(str(tmp_path / "c"))
    pe.attach_kv_tier(unshared)
    de.attach_kv_tier(unshared)
    with pytest.raises(ValueError, match="shared=True"):
        EngineRouter({"p": pe, "d": de})


def test_async_commit_unit(tmp_path):
    """Async swap-outs are invisible until drain (records enter the index
    only after the single wait), and the commit-mode split is counted."""
    kv = BlockedKVCache(num_layers=2, kv_heads=2, head_dim=4, num_blocks=8,
                        block_size=4, dtype=jnp.float32)
    kv.reserve_trash_block()
    blocks = kv.allocator.allocate(2)
    payload = np.arange(2 * 2 * 2 * 4 * 4, dtype=np.float32).reshape(
        2, 2, 2, 4, 4)
    kv.k = kv.k.at[:, :, blocks].set(payload)
    kv.v = kv.v.at[:, :, blocks].set(payload * 2)
    tier = KVSwapTier(str(tmp_path))
    tier.put_request(1, tokens=8, kv=kv, blocks=blocks,
                     fingerprint="f", async_commit=True)
    assert tier.pending_commits() == 1
    assert "1" not in tier._index["requests"]
    assert tier.drain(blocking=False) == 1          # the boundary drain
    assert tier.pending_commits() == 0
    assert tier.request_record(1)["tokens"] == 8
    assert tier.stats["commits_overlapped"] == 1
    # a read path drains for itself (blocking) when records are queued
    tier.put_request(2, tokens=4, kv=kv, blocks=blocks[:1],
                     fingerprint="g", async_commit=True)
    assert tier.request_record(2)["blocks"] == 1
    assert tier.stats["commits_blocking"] == 1
    # restore across a fresh instance still works (files committed)
    tier2 = KVSwapTier(str(tmp_path))
    dst = kv.allocator.allocate(2)
    tier2.restore_request(1, kv, dst)
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, dst]), payload)


def test_segmented_record_roundtrip(tmp_path):
    """Boundary-incremental segments restore as one contiguous record —
    the partial-watermark schema extension of kv_tier_index.json."""
    kv = BlockedKVCache(num_layers=2, kv_heads=2, head_dim=4, num_blocks=10,
                        block_size=4, dtype=jnp.float32)
    kv.reserve_trash_block()
    blocks = kv.allocator.allocate(3)
    payload = np.random.default_rng(0).normal(
        size=(2, 2, 3, 4, 4)).astype(np.float32)
    kv.k = kv.k.at[:, :, blocks].set(payload)
    kv.v = kv.v.at[:, :, blocks].set(-payload)
    tier = KVSwapTier(str(tmp_path), shared=True)
    tier.publish_request_segment(5, tokens=4, fingerprint="a", kv=kv,
                                 new_blocks=blocks[:1])
    tier.publish_request_segment(5, tokens=8, fingerprint="b", kv=kv,
                                 new_blocks=blocks[1:2])
    tier.publish_request_segment(5, tokens=11, fingerprint="c", kv=kv,
                                 new_blocks=blocks[2:],
                                 handoff={"prompt_tokens": 10})
    tier.drain()
    rec = tier.request_record(5)
    assert rec["tokens"] == 11 and rec["blocks"] == 3
    assert len(rec["segments"]) == 3 and rec["fingerprint"] == "c"
    assert rec["handoff"] == {"prompt_tokens": 10}
    dst = kv.allocator.allocate(3)
    tier.restore_request(5, kv, dst)
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, dst]), payload)
    np.testing.assert_array_equal(np.asarray(kv.v[:, :, dst]), -payload)
    # shared tiers never prune peers' records
    assert tier.prune_requests(set()) == 0
    assert tier.request_record(5) is not None
    tier.drop_request(5)
    assert tier.request_record(5) is None


# ---------------------------------------------------------------------------
# fleet scenarios
# ---------------------------------------------------------------------------


def _router(engines, **over):
    kw = dict(prefill_route_min_prompt=16,
              quarantine_backoff_ticks=1 << 20)
    kw.update(over)
    return EngineRouter(engines, RouterConfig(**kw))


def test_handoff_token_parity_fifo(tiny_model_params, tmp_path, greedy_base):
    model, params = tiny_model_params
    engines, tier = _fleet(model, params, tmp_path)
    router = _router(engines)
    outs = dict(router.serve(_mix_arrivals(), max_new_tokens=MAX_NEW))
    _assert_parity(outs, greedy_base)
    st = router.stats()
    assert st["counters"]["handoffs"] == 2, \
        "both long prompts must hand off to the decode replica"
    assert st["counters"]["handoffs_unpublished"] == 0
    assert st["counters"]["requests_failed"] == 0
    pe = engines["prefill0"]
    de = engines["decode1"]
    assert pe.telemetry.counters["handoffs_out"] == 2
    assert de.telemetry.counters["kv_swap_in_requests"] == 2, \
        "the decode replica must RESTORE pages, not re-prefill"
    # the long prompts' decode tokens stream from the decode replica
    assert de.telemetry.counters["tokens_emitted"] > 0
    # TTFT attribution: exactly one true-first-token sample per request,
    # fleet-wide (the decode side's continuation emits record none)
    assert pe.telemetry.hists["ttft"].total + \
        de.telemetry.hists["ttft"].total == 4
    for eng in engines.values():
        _assert_clean(eng)
    # no leaked tier records
    assert not tier._index["requests"] and not tier.pending_commits()


def test_handoff_token_parity_scheduler(tiny_model_params, tmp_path,
                                        greedy_base):
    model, params = tiny_model_params
    engines, _tier = _fleet(model, params, tmp_path)
    router = _router(engines)
    outs = dict(router.serve(_mix_arrivals(meta=True),
                             max_new_tokens=MAX_NEW,
                             scheduler_factory=RequestScheduler))
    _assert_parity(outs, greedy_base)
    assert router.stats()["counters"]["handoffs"] == 2
    for eng in engines.values():
        _assert_clean(eng)


@pytest.mark.multichip
def test_cross_degree_handoff_tp1_to_tp8(tiny_model_params, tmp_path,
                                         greedy_base):
    """tp=1 prefill replica publishes pages an tp=8 head-sharded decode
    replica restores — the cross-degree handoff the snapshot-split
    machinery already proves for re-prefill, now over real pages."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill")
    de = _engine(model, params, tp=8)
    pe.attach_kv_tier(tier, tag="p")
    de.attach_kv_tier(tier, tag="d")
    router = _router({"p": pe, "d": de})
    outs = dict(router.serve(_mix_arrivals(), max_new_tokens=MAX_NEW))
    _assert_parity(outs, greedy_base)
    assert router.stats()["counters"]["handoffs"] == 2
    assert de.telemetry.counters["kv_swap_in_requests"] == 2
    for eng in (pe, de):
        _assert_clean(eng)


def test_prefill_kill_midprompt_partial_watermark(tiny_model_params,
                                                  tmp_path):
    """Kill the prefill replica MID-PROMPT: the boundary-incremental
    segments already in the tier let the failover peer restore the
    partial watermark and finish the prefill from there — asserted via
    the survivor's swap-in counters AND its prefill-token count (less
    than a from-zero re-prefill)."""
    model, params = tiny_model_params
    # one long prompt, frame_steps=1: prefill spans many boundaries
    long_prompt = np.random.default_rng(21).integers(
        0, 200, (96,)).astype(np.int32)
    ref = _engine(model, params)
    base = dict(ref.serve(iter([[(0, long_prompt)]]), max_new_tokens=4))

    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill", frame_steps=1)
    de = _engine(model, params, frame_steps=1)
    pe.attach_kv_tier(tier, tag="p")
    de.attach_kv_tier(tier, tag="d")
    router = _router({"p": pe, "d": de})
    # tick 4: several prefill boundaries have published segments, the
    # prompt (96 tokens / 8-token chunks / 1-step frames) is far from done
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 4, "engine": "p"}])
    outs = dict(router.serve(iter([[(0, long_prompt, 4)]]),
                             max_new_tokens=4, faults=inj))
    _assert_parity(outs, base)
    st = router.stats()
    assert st["replicas"]["p"] == QUARANTINED
    assert st["counters"]["requests_failed"] == 0
    # the survivor restored the partial watermark from the tier...
    assert de.telemetry.counters["kv_swap_in_requests"] == 1
    restored = de.telemetry.counters["kv_swap_in_blocks"]
    assert restored >= 1
    # ...and prefilled only the tail past it (a from-zero re-prefill
    # would consume the full 96 prompt tokens)
    assert de.telemetry.counters["prefill_tokens"] < len(long_prompt)
    for eng in (pe, de):
        _assert_clean(eng)


def test_fleet_prefix_share_hot_prompt(tiny_model_params, tmp_path):
    """A hot prompt is prefilled once FLEET-WIDE: the handoff publishes a
    content-addressed prefix record, and a later identical prompt on a
    DIFFERENT engine admits at the watermark with only the sub-chunk
    tail (here: one token) left to prefill — zero full prefill chunks."""
    model, params = tiny_model_params
    plen = 6 * CHUNK + 1            # tail of 1: the hit covers 6 chunks
    hot = np.random.default_rng(22).integers(
        0, 200, (plen,)).astype(np.int32)
    ref = _engine(model, params)
    base = dict(ref.serve(iter([[(0, hot)]]), max_new_tokens=MAX_NEW))

    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill")
    pe.attach_kv_tier(tier, tag="p")
    # first pass: the prefill replica pays the full prefill and publishes
    for item in pe.serve(iter([[(0, hot, MAX_NEW)]]), max_new_tokens=MAX_NEW):
        pass
    tier.drain()
    assert tier.stats["prefix_records"] == 1
    assert pe.telemetry.counters["prefill_tokens"] >= plen

    # second pass: a SEPARATE engine (no local prefix cache, different
    # role) admits the same prompt from the tier at the watermark
    de = _engine(model, params)
    de.attach_kv_tier(tier, tag="d")
    outs = dict(de.serve(iter([[(5, hot)]]), max_new_tokens=MAX_NEW))
    np.testing.assert_array_equal(outs[5], base[0])
    assert de.telemetry.counters["tier_prefix_hits"] == 1
    assert de.telemetry.counters["tier_prefix_hit_tokens"] == 6 * CHUNK
    assert de.telemetry.counters["prefill_tokens"] <= 1, \
        "the tier hit must leave only the sub-chunk tail to prefill"
    _assert_clean(de)


def test_transfer_guard_through_handoff(tiny_model_params, tmp_path,
                                        frame_transfer_guard, greedy_base):
    """The whole disaggregated pipeline — incremental publish, handoff,
    tier restore, prefix share — touches the device at frame boundaries
    only (dispatch_frame runs under transfer_guard_device_to_host)."""
    model, params = tiny_model_params
    engines, _tier = _fleet(model, params, tmp_path)
    router = _router(engines)
    outs = dict(router.serve(_mix_arrivals(), max_new_tokens=MAX_NEW))
    _assert_parity(outs, greedy_base)
    assert router.stats()["counters"]["handoffs"] == 2


def test_handoff_yields_events_to_plain_consumers(tiny_model_params,
                                                  tmp_path):
    """A prefill-role engine served WITHOUT a router yields HandoffEvents
    in-stream; driving the arrival back into a second engine by hand is
    the whole disaggregation protocol in miniature."""
    model, params = tiny_model_params
    ref = _engine(model, params)
    base = dict(ref.serve(iter([[(0, LONGS[0])]]), max_new_tokens=MAX_NEW))
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill")
    pe.attach_kv_tier(tier, tag="p")
    events = [item for item in pe.serve(iter([[(0, LONGS[0])]]),
                                        max_new_tokens=MAX_NEW,
                                        yield_boundaries=True)
              if isinstance(item, HandoffEvent)]
    assert len(events) == 1 and events[0].published
    ev = events[0]
    assert ev.arrival["max_new_tokens"] == MAX_NEW    # ORIGINAL budget
    assert len(ev.arrival["generated"]) >= 1
    # the tier record carries the handoff metadata (schema extension),
    # and its fingerprint covers exactly the watermarked stream prefix
    rec = tier.request_record(0)
    assert rec["handoff"]["prompt_tokens"] == len(LONGS[0])
    full = list(LONGS[0]) + ev.arrival["generated"]
    assert rec["fingerprint"] == token_fingerprint(full[:rec["tokens"]])
    de = _engine(model, params)
    de.attach_kv_tier(tier, tag="d")
    outs = dict(de.serve(iter([[ev.arrival]]), max_new_tokens=MAX_NEW))
    np.testing.assert_array_equal(outs[0], base[0])
    _assert_clean(pe)
    _assert_clean(de)


def test_preempt_midprefill_then_handoff_parity(tiny_model_params,
                                                tmp_path):
    """Preemption on a prefill-role engine must reset the tier publish
    cursor: the victim's incremental segments were REPLACED by the
    preemption's own record and consumed by the swap-in re-admission, so
    post-resume publishes restart at block zero. A stale cursor would
    write a record whose segments start at the wrong block offset while
    claiming the full watermark — silently corrupt pages (and divergent
    tokens) on the decode side's restore."""
    model, params = tiny_model_params
    long_a = np.random.default_rng(31).integers(
        0, 200, (96,)).astype(np.int32)
    long_b = np.random.default_rng(32).integers(
        0, 200, (96,)).astype(np.int32)

    def mix():
        yield [{"uid": 0, "tokens": long_a, "max_new_tokens": 4,
                "priority": "best_effort"}]
        yield []
        # arrives while uid 0 is MID-PREFILL in the only slot: preempts it
        yield [{"uid": 1, "tokens": long_b, "max_new_tokens": 4,
                "priority": "interactive"}]

    ref = _engine(model, params, frame_steps=1)
    base = dict(ref.serve(mix(), max_new_tokens=4, frame_slots=1,
                          scheduler=RequestScheduler()))

    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill", frame_steps=1)
    pe.attach_kv_tier(tier, tag="p")
    events = [item for item in pe.serve(mix(), max_new_tokens=4,
                                        frame_slots=1,
                                        scheduler=RequestScheduler(),
                                        yield_boundaries=True)
              if isinstance(item, HandoffEvent)]
    assert len(events) == 2
    assert pe.telemetry.counters["requests_preempted"] >= 1, \
        "the interactive arrival must preempt the mid-prefill victim " \
        "(else this scenario exercised nothing)"
    # the record INVARIANT is the real assertion: segments must cover
    # exactly blocks_for(tokens) pages from block zero. (Output parity
    # alone can mask a shifted restore on this tiny model — ALiBi decay
    # mutes distant corrupt pages below argmax resolution.)
    tier.drain()
    for uid in (0, 1):
        rec = tier.request_record(uid)
        assert rec["blocks"] == pe.kv.blocks_for(rec["tokens"]), \
            (f"uid={uid}: record claims {rec['tokens']} tokens but holds "
             f"{rec['blocks']} pages — a stale post-preemption publish "
             "cursor shifted the segments")
    de = _engine(model, params, frame_steps=1)
    de.attach_kv_tier(tier, tag="d")
    outs, swap_ins = {}, 0
    for ev in events:
        outs.update(de.serve(iter([[ev.arrival]]), max_new_tokens=4))
        # telemetry resets per serve run — accumulate across the two
        swap_ins += de.telemetry.counters["kv_swap_in_requests"]
    _assert_parity(outs, base, uids=[0, 1])
    assert swap_ins == 2, "both handoffs must restore pages, not re-prefill"
    _assert_clean(pe)
    _assert_clean(de)


def test_prefill_role_requires_tier(tiny_model_params):
    model, params = tiny_model_params
    pe = _engine(model, params, role="prefill")
    with pytest.raises(ValueError, match="needs a KV swap tier"):
        pe.serve(iter([]), max_new_tokens=4)
    with pytest.raises(ValueError, match="role="):
        _engine(model, params, role="wide")


def test_boundary_reports_queued_tokens(tiny_model_params):
    """ServeBoundary.queued_tokens is the prefill-placement signal: it
    tracks prompt TOKENS held in the engine-side queue."""
    model, params = tiny_model_params
    eng = _engine(model, params)
    seen = []
    for item in eng.serve(iter([[(0, LONGS[0]), (1, LONGS[1]),
                                 (2, SHORTS[2]), (3, SHORTS[3]),
                                 (4, np.random.default_rng(23).integers(
                                     0, 200, (30,)).astype(np.int32))]]),
                          max_new_tokens=4, frame_slots=2,
                          yield_boundaries=True):
        if isinstance(item, ServeBoundary):
            seen.append(item.queued_tokens)
    assert max(seen) > 0, "a saturated table must report queued tokens"
    assert seen[-1] == 0, "the drained run ends with an empty queue"


# ---------------------------------------------------------------------------
# handoff pipelining (ISSUE 14 satellite): the final record segment is
# published DURING the first-token frame, not after it
# ---------------------------------------------------------------------------


def _traced_publishes(eng):
    """Instrument an engine's segment publishes; returns the log list of
    (watermark, blocks, had_handoff_meta) tuples."""
    log = []
    orig = eng._publish_segments

    def traced(uid, seq, stream, w, nb, handoff=None):
        log.append((w, nb, handoff is not None))
        return orig(uid, seq, stream, w, nb, handoff=handoff)

    eng._publish_segments = traced
    return log


def test_handoff_pipelined_no_page_io_at_handoff(tiny_model_params,
                                                 tmp_path, greedy_base):
    """With ``handoff_pipeline`` on (the default), the final segment —
    handoff metadata included — is published at the boundary BEFORE the
    first-token frame, and the handoff boundary itself does ZERO page
    publishes; outputs stay token-identical to the monolith (the decode
    side replays the sub-frame tail cold). With the flag off, the final
    publish happens at the handoff watermark, as before."""
    model, params = tiny_model_params
    for pipe in (True, False):
        engines, tier = _fleet(model, params, tmp_path / f"p{pipe}",
                               prefill={"handoff_pipeline": pipe},
                               decode={"handoff_pipeline": pipe})
        pe = engines["prefill0"]
        log = _traced_publishes(pe)
        router = _router(engines)
        outs = dict(router.serve(_mix_arrivals(), max_new_tokens=MAX_NEW))
        _assert_parity(outs, greedy_base)
        assert router.stats()["counters"]["handoffs"] == 2
        plen = len(LONGS[0])
        final_pubs = [e for e in log if e[2]]
        assert len(final_pubs) == 2
        if pipe:
            # final (metadata-carrying) publish lands BELOW the prompt
            # watermark — i.e. before the first-token frame completed it
            assert all(w < plen for w, _, _ in final_pubs), final_pubs
            assert pe.telemetry.counters["handoffs_pipelined"] == 2
        else:
            # legacy: the final publish covers the full prompt watermark
            assert all(w >= plen for w, _, _ in final_pubs), final_pubs
            assert pe.telemetry.counters["handoffs_pipelined"] == 0
        for eng in engines.values():
            _assert_clean(eng)


def test_handoff_pipelined_segment_ordering(tiny_model_params, tmp_path):
    """Segment-ordering invariant under pipelining: every record's
    segments cover ``blocks_for(tokens)`` blocks contiguously (sum of
    per-segment block counts == record blocks), including the
    partial-tail case (frame_steps=1: the final publish's tail block is
    mid-fill), and the record restores cleanly into a fresh engine."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill", frame_steps=1)
    pe.attach_kv_tier(tier, tag="p")
    records = {}
    orig = KVSwapTier.stamp_request_handoff

    def arrivals():
        yield [{"uid": 0, "tokens": LONGS[0], "max_new_tokens": 4}]

    ho = None
    for ev in pe.serve(arrivals(), max_new_tokens=4,
                       yield_boundaries=True):
        if isinstance(ev, HandoffEvent):
            ho = ev
            # capture the record AT the handoff boundary, before the
            # router-side lifecycle drops it
            records[0] = tier.request_record(0)
    assert ho is not None and ho.published
    rec = records[0]
    assert rec is not None
    # chunk-aligned watermark at or below the prompt; tail replayed cold
    assert rec["tokens"] % CHUNK == 0
    assert rec["tokens"] <= len(LONGS[0])
    assert rec["handoff"]["pipelined"] is True
    # contiguous coverage: blocks == blocks_for(tokens) == sum(segments)
    assert rec["blocks"] == pe.kv.blocks_for(rec["tokens"])
    assert rec["blocks"] == sum(s["blocks"] for s in rec["segments"])
    # the partial-tail block really is partial (frame_steps=1 with
    # CHUNK < BS makes the final watermark straddle a block)
    assert rec["tokens"] < rec["blocks"] * BS
    # and the record restores into a fresh engine's pool
    de = _engine(model, params)
    de.attach_kv_tier(tier, tag="d")
    blocks = de.kv.allocator.allocate(rec["blocks"])
    tier.restore_request(0, de.kv, blocks)
    de.kv.allocator.free(blocks)
    assert orig is KVSwapTier.stamp_request_handoff


def test_handoff_pipeline_heal_on_missed_prediction(tiny_model_params,
                                                    tmp_path):
    """A pipelined final publish whose handoff never came (the next
    frame ran shorter than planned) must HEAL: the partial-tail record
    is dropped and republished from block zero before any append, so
    the ``blocks == blocks_for(tokens)`` restore invariant survives.
    Forced directly: publish a partial final segment, then advance the
    row as if more prefill happened and let the progress publish run."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill", frame_steps=1)
    pe.attach_kv_tier(tier, tag="p")

    class _Slots:                      # minimal slots view for the publish
        def __init__(self, uid, cached, plen):
            self.slot_of_uid = {uid: 0}
            self.cached_h = [cached]
            self.plen_h = [plen]

    uid, plen = 0, 48
    stream = [int(t) for t in LONGS[0][:plen]]
    seq = pe.state.get_or_create_sequence(uid)
    seq.blocks = pe.kv.allocator.allocate(pe.kv.blocks_for(plen))
    pe._ledger_add(uid, stream, 4, 0.0, None, None)
    pe._handoff_mode = True
    # boundary A: watermark 40, remaining 8 <= chunk*steps -> pipelined
    # partial publish (blocks_for(40)=3, block 2 partial)
    pe._tier_publish_progress(_Slots(uid, 40, plen), 0, next_steps=1)
    assert seq.tier_final and seq.tier_partial and seq.tier_blocks == 3
    rec = tier.request_record(uid)
    assert (rec["tokens"], rec["blocks"]) == (40, 3)
    # prediction misses: the row is STILL mid-prefill at the next
    # boundary with a higher watermark -> heal (drop + republish)
    pe._tier_publish_progress(_Slots(uid, 40, plen + 48), 1, next_steps=1)
    rec = tier.request_record(uid)
    assert rec["blocks"] == pe.kv.blocks_for(rec["tokens"])
    assert rec["blocks"] == sum(s["blocks"] for s in rec["segments"])
    assert not seq.tier_partial
    pe.state.flush_sequence(uid)
    pe._ledger.clear()
