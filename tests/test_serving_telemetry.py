"""Serving telemetry tests.

The telemetry subsystem (``inference/v2/telemetry.py``) has one hard
contract: every number it reports must match a host-side replay of the same
arithmetic EXACTLY (the in-graph counters are not estimates), and measuring
must add zero device→host transfers inside a frame. The scripted-schedule
tests below derive ground truth from the SplitFuse scheduling arithmetic
(prefill steps = ceil(P/chunk), decode steps = N-1 after the
prefill-completing emission) and assert counter equality; the transfer-guard
test pins the no-in-frame-transfer invariant; the histogram/Prometheus tests
pin the fixed-memory bucket math and the exposition format.
"""

import logging

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.ragged_manager import DeviceSlotTable
from deepspeed_tpu.inference.v2.telemetry import (LogBucketHistogram,
                                                  ServingTelemetry)
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils.logging import logger as ds_logger


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny")
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4)
    kw.update(over)
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                          max_seq_len=128)
    e.params = jax.device_put(params)
    return e


PROMPT_LENS = {0: 7, 1: 24, 2: 33}
MAX_NEW = 8
CHUNK = 16


def _prompts():
    rng = np.random.default_rng(5)
    return {u: rng.integers(0, 200, (n,)).astype(np.int32)
            for u, n in PROMPT_LENS.items()}


def _arrivals(prompts, schedule={0: [0, 1], 2: [2]}):
    for k in range(max(schedule) + 2):
        yield [(u, prompts[u]) for u in schedule.get(k, [])]


class StubMonitor:
    """Minimal Monitor-protocol sink: records every event batch."""

    def __init__(self):
        self.events = []

    def write_events(self, events):
        self.events.extend(events)


@pytest.fixture(scope="module")
def served(tiny_model_params, tmp_path_factory):
    """ONE scripted serve() run, with a stub monitor AND a real
    CSV-MonitorMaster attached; telemetry state is snapshotted immediately
    (later tests reuse the engine, which resets the per-serve view)."""
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    from deepspeed_tpu.runtime.config import DeepSpeedMonitorConfig

    model, params = tiny_model_params
    e = _engine(model, params)
    stub = StubMonitor()
    csv_dir = tmp_path_factory.mktemp("csv_monitor")
    master = MonitorMaster(DeepSpeedMonitorConfig(
        csv_monitor={"enabled": True, "output_path": str(csv_dir),
                     "job_name": "serve"}))

    class Tee:
        def write_events(self, events):
            stub.write_events(events)
            master.write_events(events)

    e.attach_monitor(Tee())
    e.telemetry.record_spans = True
    prompts = _prompts()
    outs = dict(e.serve(_arrivals(prompts), max_new_tokens=MAX_NEW))
    snap = {
        "snapshot": e.telemetry.snapshot(),
        "prom": e.telemetry.render_prometheus(),
        "latency_ms": e.telemetry.latency_ms(),
        "spans": list(e.telemetry.spans),
        "events": list(stub.events),
        "csv_dir": csv_dir,
        "serve_view": {k: (dict(v) if isinstance(v, dict) else v)
                       for k, v in e.serve_stats.items()},
    }
    return e, prompts, outs, snap


# ---------------------------------------------------------------------------
# in-graph counters vs host-replay ground truth
# ---------------------------------------------------------------------------


def test_counters_match_host_replay(served):
    """The device counters must equal the SplitFuse arithmetic replayed on
    the host: per row, ceil(P/chunk) prefill steps (the last one emits the
    first token) then N-1 decode steps; no EOS in this schedule."""
    _e, prompts, outs, snap = served
    c = snap["snapshot"]["counters"]
    n_tokens = sum(len(v) for v in outs.values())
    assert n_tokens == len(PROMPT_LENS) * MAX_NEW
    assert c["tokens_emitted"] == n_tokens
    assert c["prefill_tokens"] == sum(PROMPT_LENS.values())
    assert c["eos_events"] == 0
    expect_decode_fwd = sum(MAX_NEW - 1 for _ in PROMPT_LENS)
    assert c["target_forwards"] == expect_decode_fwd
    expect_active = sum(-(-p // CHUNK) + MAX_NEW - 1
                        for p in PROMPT_LENS.values())
    assert c["active_row_steps"] == expect_active
    assert c["drafted_tokens"] == 0 and c["accepted_draft_tokens"] == 0
    assert c["requests_enqueued"] == c["requests_admitted"] \
        == c["requests_retired"] == len(PROMPT_LENS)
    assert c["admission_deferrals"] == 0
    assert c["frames"] == snap["serve_view"]["frames"]


def test_eos_counted_in_graph(tiny_model_params, served):
    """A scripted per-row EOS registers exactly one in-graph EOS event and
    one fewer emitted token than the budget."""
    e, prompts, outs, _snap = served
    eos = int(outs[0][2])
    stop = outs[0].tolist().index(eos)
    got = dict(e.serve(iter([[(0, prompts[0], None, None, eos)]]),
                       max_new_tokens=MAX_NEW))
    c = e.telemetry.counters
    assert len(got[0]) == stop + 1
    assert c["eos_events"] == 1
    assert c["tokens_emitted"] == stop + 1


def test_lifecycle_latency_histograms(served):
    """TTFT/queue-wait/E2E get one sample per request; ITL gets one sample
    per token after each row's first emission (frame-granularity measure)."""
    _e, _prompts, outs, snap = served
    lat = snap["latency_ms"]
    n_req = len(PROMPT_LENS)
    for name in ("ttft", "queue_wait", "e2e"):
        assert lat[name]["count"] == n_req, (name, lat)
        assert lat[name]["p50"] is not None and lat[name]["p50"] >= 0
        assert lat[name]["p99"] is not None
    assert 0 < lat["itl"]["count"] < n_req * MAX_NEW
    spans = snap["spans"]
    assert len(spans) == n_req
    for s in spans:
        assert s["enqueue_t"] <= s["admit_t"] <= s["first_token_t"] \
            <= s["retire_t"]
        assert s["tokens"] == MAX_NEW


def test_occupancy_and_kv_gauges(served):
    e, _prompts, _outs, snap = served
    g = snap["snapshot"]["gauges"]
    assert g["kv_blocks_total"] == e.kv.num_blocks
    assert 1 <= g["kv_blocks_in_use"] <= e.kv.num_blocks
    assert 0.0 < g["occupancy"] <= 1.0
    assert g["slot_count"] == 8
    assert g["recompiled_programs"] >= 1   # the frame programs themselves


# ---------------------------------------------------------------------------
# speculative counter parity (device counters vs host emit-mask replay)
# ---------------------------------------------------------------------------


def test_spec_counter_parity_with_host_replay(tiny_model_params, monkeypatch):
    """serve_stats' speculative counters now come from the device; they must
    equal the old host arithmetic (verify forwards = emit column 0 of
    width-1 frames, accepted = the other columns) replayed on the frames'
    emit masks — and the emitted totals must match the actual outputs."""
    model, params = tiny_model_params
    e = _engine(model, params)
    e.attach_draft(model, params)           # self-draft: high acceptance

    host = {"fwds": 0, "emitted": 0}
    orig = DeviceSlotTable.run_frame

    def spy(self, runner, eng_params, kv, width, steps, greedy, draft=None,
            **kw):
        toks, emit = orig(self, runner, eng_params, kv, width, steps, greedy,
                          draft=draft, **kw)
        if emit.ndim == 3 and width == 1:
            host["fwds"] += int(emit[:, :, 0].sum())
            host["emitted"] += int(emit.sum())
        return toks, emit

    monkeypatch.setattr(DeviceSlotTable, "run_frame", spy)
    prompts = _prompts()
    outs = dict(e.serve(_arrivals(prompts), max_new_tokens=MAX_NEW, gamma=2))
    sp = e.serve_stats["spec"]
    assert sp["target_forwards"] == host["fwds"]
    assert sp["emitted_tokens"] == host["emitted"]
    assert sp["accepted_drafts"] == host["emitted"] - host["fwds"]
    assert sp["acceptance_rate"] == round(
        sp["accepted_drafts"] / (2 * sp["target_forwards"]), 4)
    c = e.telemetry.counters
    assert c["tokens_emitted"] == sum(len(v) for v in outs.values())
    assert c["drafted_tokens"] == 2 * sp["target_forwards"]
    # self-draft under greedy: near-full acceptance => >2 tokens per verify
    assert sp["tokens_per_target_forward"] > 2.0, sp


# ---------------------------------------------------------------------------
# no in-frame host transfers
# ---------------------------------------------------------------------------


def test_telemetry_adds_no_in_frame_transfers(served, frame_transfer_guard):
    """Frame dispatch performs ZERO device→host transfers with telemetry on:
    the counters ride the donated carry and are read only at the frame
    boundary (outside the guarded region, with the token/emit fetch).
    Uses conftest's shared guard — the single definition of "in-frame"
    that graft-lint GL001 checks statically."""
    e, prompts, _outs, _snap = served
    got = dict(e.serve(iter([[(0, prompts[0]), (1, prompts[1])]]),
                       max_new_tokens=MAX_NEW))
    assert len(got) == 2 and all(len(v) == MAX_NEW for v in got.values())
    assert e.telemetry.counters["tokens_emitted"] == 2 * MAX_NEW


# ---------------------------------------------------------------------------
# overload deferral visibility
# ---------------------------------------------------------------------------


def test_admission_deferral_warns_once_and_counts(served):
    """Overloading every slot logs ONE rate-limited structured warning
    (queue depth + frame bucket included) while the deferral counter keeps
    counting every deferred frame boundary."""
    e, _prompts, _outs, _snap = served
    rng = np.random.default_rng(21)
    # 10 arrivals into 8 slots; 24-token prompts reuse the served fixture's
    # compiled shape buckets (prompt width 32, table width 4)
    arr = [(u, rng.integers(0, 200, (24,)).astype(np.int32))
           for u in range(10)]
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = Capture()
    ds_logger.addHandler(h)
    try:
        got = dict(e.serve(iter([arr]), max_new_tokens=MAX_NEW))
    finally:
        ds_logger.removeHandler(h)
    assert len(got) == 10
    warns = [m for m in records if "admission deferred" in m]
    assert len(warns) == 1, warns          # rate-limited to one
    assert "queue_depth=2" in warns[0]
    assert "frame_steps_bucket=" in warns[0]
    assert e.telemetry.counters["admission_deferrals"] >= 2


def test_defer_warning_rate_limit_scripted_clock():
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    tel = ServingTelemetry(clock=clk, defer_warn_interval_s=5.0)
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = Capture()
    ds_logger.addHandler(h)
    try:
        tel.on_defer(queue_depth=3, frame_steps=8, free_slots=0,
                     free_blocks=11)
        clk.t = 1.0
        tel.on_defer(queue_depth=4, frame_steps=8, free_slots=0,
                     free_blocks=11)
        clk.t = 6.1                        # past the interval: warns again
        tel.on_defer(queue_depth=5, frame_steps=4, free_slots=0,
                     free_blocks=11)
    finally:
        ds_logger.removeHandler(h)
    warns = [m for m in records if "admission deferred" in m]
    assert len(warns) == 2
    assert "queue_depth=3" in warns[0] and "no free slots" in warns[0]
    assert "deferral_events_since_last_warning=2" in warns[1]
    assert tel.counters["admission_deferrals"] == 3


# ---------------------------------------------------------------------------
# histogram bucket math (fixed memory, exact placement)
# ---------------------------------------------------------------------------


def test_log_bucket_histogram_math():
    h = LogBucketHistogram(lo=1e-3, growth=10.0, n_buckets=3)
    assert h.bounds == [1e-3, 1e-2, 1e-1]
    for v in (0.0005, 0.001, 0.005, 0.01, 0.05, 5.0):
        h.record(v)
    # placement: <= lo -> bucket 0; bound-exact values stay in their bucket;
    # past the top bound -> overflow
    np.testing.assert_array_equal(h.counts, [2, 2, 1, 1])
    assert h.total == 6
    assert abs(h.sum - 5.0665) < 1e-12
    # p50: rank 3 lands in bucket 1 -> geometric midpoint sqrt(1e-3 * 1e-2)
    assert abs(h.percentile(50) - 10 ** -2.5) < 1e-12
    # p10: rank 0.6 -> bucket 0 -> upper/2
    assert h.percentile(10) == 0.0005
    # p99: rank 5.94 -> overflow bucket -> top bound * growth
    assert h.percentile(99) == 1.0
    assert LogBucketHistogram().percentile(50) is None   # empty
    h.reset()
    assert h.total == 0 and h.sum == 0.0
    # weighted record: one call, n samples
    h.record(0.02, count=5)
    assert h.counts[2] == 5 and h.total == 5


def test_scripted_lifecycle_stamps():
    """Deterministic clock: every histogram sample lands where the
    enqueue→admit→first-token→retire arithmetic says it must."""
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    tel = ServingTelemetry(clock=clk, record_spans=True)
    tel.begin_serve(speculate=False, gamma=0, adaptive=False, n_slots=4,
                    kv_blocks_total=64)
    clk.t = 10.0
    tel.on_enqueue(7)
    clk.t = 10.5
    tel.on_admit(7)                         # queue_wait = 0.5
    clk.t = 11.0
    tel.on_emit(7, 3)                       # first emission: TTFT = 1.0
    clk.t = 12.0
    tel.on_emit(7, 2)                       # 2 ITL samples of 0.5
    clk.t = 13.0
    tel.on_retire(7)                        # e2e = 3.0
    assert tel.hists["queue_wait"].total == 1
    assert tel.hists["ttft"].total == 1
    assert abs(tel.hists["ttft"].sum - 1.0) < 1e-9
    assert tel.hists["itl"].total == 2
    assert abs(tel.hists["itl"].sum - 1.0) < 1e-9    # 2 x 0.5
    assert abs(tel.hists["e2e"].sum - 3.0) < 1e-9
    assert tel.counters["requests_retired"] == 1
    (span,) = tel.spans
    assert span == {"uid": 7, "enqueue_t": 10.0, "admit_t": 10.5,
                    "first_token_t": 11.0, "retire_t": 13.0, "tokens": 5}


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_render_golden():
    """Exact text for one histogram section (cumulative le buckets, sum,
    count, quantiles) — the scrape format is a wire contract."""
    tel = ServingTelemetry(clock=lambda: 0.0)
    h = LogBucketHistogram(lo=1e-3, growth=10.0, n_buckets=3)
    for v in (0.0005, 0.005, 0.05, 5.0):
        h.record(v)
    tel.hists = {"ttft": h}
    text = tel.render_prometheus()
    golden = """# TYPE ds_serving_ttft_seconds histogram
ds_serving_ttft_seconds_bucket{le="0.001"} 1
ds_serving_ttft_seconds_bucket{le="0.01"} 2
ds_serving_ttft_seconds_bucket{le="0.1"} 3
ds_serving_ttft_seconds_bucket{le="+Inf"} 4
ds_serving_ttft_seconds_sum 5.0555
ds_serving_ttft_seconds_count 4
ds_serving_ttft_seconds_quantile{quantile="0.50"} 0.00316228
ds_serving_ttft_seconds_quantile{quantile="0.90"} 1
ds_serving_ttft_seconds_quantile{quantile="0.99"} 1"""
    assert golden in text
    # counters and gauges render with their types
    assert "# TYPE ds_serving_tokens_emitted_total counter" in text
    assert "ds_serving_tokens_emitted_total 0" in text
    assert "# TYPE ds_serving_kv_blocks_in_use gauge" in text
    assert "ds_serving_spec_acceptance_rate NaN" in text
    assert text.endswith("\n")


def test_prometheus_render_from_serve(served):
    """The acceptance-criteria surface: a scripted serve() run exposes
    token counts, occupancy, KV usage, and latency quantiles via
    render_prometheus()."""
    _e, _prompts, outs, snap = served
    text = snap["prom"]
    n_tokens = sum(len(v) for v in outs.values())
    assert f"ds_serving_tokens_emitted_total {n_tokens}" in text
    assert f"ds_serving_requests_retired_total {len(outs)}" in text
    assert 'ds_serving_ttft_seconds_bucket{le="+Inf"} 3' in text
    assert "ds_serving_ttft_seconds_count 3" in text
    assert 'ds_serving_e2e_seconds_quantile{quantile="0.99"}' in text
    assert "ds_serving_occupancy" in text
    assert "ds_serving_kv_blocks_in_use" in text


# ---------------------------------------------------------------------------
# MonitorMaster fan-out
# ---------------------------------------------------------------------------


def test_monitor_fanout(served):
    """Frame-boundary events reach both an arbitrary write_events sink and
    a real CSV MonitorMaster (one file per tag, step = frame index)."""
    _e, _prompts, outs, snap = served
    events = snap["events"]
    tags = {t for t, _v, _s in events}
    assert "serving/tokens_emitted" in tags
    assert "serving/kv_blocks_in_use" in tags
    assert "serving/ttft_p50_ms" in tags
    final = {t: v for t, v, _s in events}    # last write per tag
    assert final["serving/tokens_emitted"] == sum(
        len(v) for v in outs.values())
    csv_files = list((snap["csv_dir"] / "serve").glob("*.csv"))
    assert any(f.name == "serving_tokens_emitted.csv" for f in csv_files)


# ---------------------------------------------------------------------------
# compile-count satellites
# ---------------------------------------------------------------------------


def test_compile_count_total_monotonic_and_reset():
    class FakeJit:
        def __init__(self, n):
            self.n = n

        def _cache_size(self):
            return self.n

    from deepspeed_tpu.inference.v2.model_runner import PagedModelRunner
    r = PagedModelRunner.__new__(PagedModelRunner)   # no model needed
    r._fns = {"frame": FakeJit(3), "chunk16": FakeJit(2)}
    r._evicted_programs = 0
    r._compile_base = 0
    assert r.compile_count() == {"frame": 3, "chunk16": 2}
    assert r.compile_count_total() == 5
    # eviction (draft re-attach) must not lower the monotonic total
    r.evict("frame", "missing")
    assert "frame" not in r._fns
    assert r.compile_count_total() == 5
    r._fns["spec_frame"] = FakeJit(4)
    assert r.compile_count_total() == 9
    r.reset_compile_count()
    assert r.compile_count_total() == 0
    r._fns["spec_frame"].n = 6
    assert r.compile_count_total() == 2


def test_recompile_gauge_exported(served):
    _e, _prompts, _outs, snap = served
    assert "ds_serving_recompiled_programs" in snap["prom"]
    assert snap["snapshot"]["gauges"]["recompiled_programs"] >= 1


# ---------------------------------------------------------------------------
# telemetry-off mode
# ---------------------------------------------------------------------------


def test_telemetry_disabled_keeps_serve_stats_shape(served):
    """telemetry=False skips the host stats path but serve_stats keeps the
    frame bookkeeping shape (and serving output is unchanged)."""
    e, prompts, outs, _snap = served
    e.telemetry.enabled = False
    try:
        got = dict(e.serve(iter([[(0, prompts[0])]]),
                           max_new_tokens=MAX_NEW))
    finally:
        e.telemetry.enabled = True
    np.testing.assert_array_equal(got[0], outs[0])
    view = e.serve_stats
    assert view["frames"] >= 1 and view["frame_steps_last"] == 4
    assert e.telemetry.counters["tokens_emitted"] == 0   # host path idle
    assert e.telemetry.hists["ttft"].total == 0


def test_telemetry_reenabled_mid_serve_discards_backlog(served):
    """Flipping telemetry on mid-serve must not dump the disabled-period
    device-counter backlog into one frame: the transition frame is rebased
    and discarded, so counters reflect only fully-measured frames and the
    occupancy gauge stays a ratio."""
    e, _prompts, _outs, _snap = served
    rng = np.random.default_rng(23)
    p0 = rng.integers(0, 200, (9,)).astype(np.int32)
    p1 = rng.integers(0, 200, (14,)).astype(np.int32)
    e.telemetry.enabled = False
    try:
        gen = e.serve(iter([[(0, p0, 4), (1, p1, 16)]]), max_new_tokens=16)
        uid, toks = next(gen)          # uid 0 retires first (budget 4)
        assert uid == 0 and len(toks) == 4
        e.telemetry.enabled = True     # re-enable while uid 1 is mid-decode
        rest = dict(gen)
    finally:
        e.telemetry.enabled = True
    assert len(rest[1]) == 16
    c = e.telemetry.counters
    # only frames after the (discarded) transition frame are counted
    assert 0 < c["tokens_emitted"] < 4 + 16
    assert 0.0 < e.telemetry.gauges["occupancy"] <= 1.0
    snap = e.telemetry.snapshot()
    assert 0.0 < snap["derived"]["occupancy_avg"] <= 1.0
    assert c["active_row_steps"] <= c["slot_steps_capacity"]


@pytest.mark.slow
def test_wall_clock_latency_values_plausible(tiny_model_params):
    """Wall-clock-sensitive (hence slow-marked): real latencies must be
    positive and ordered TTFT <= E2E for a single-request serve."""
    model, params = tiny_model_params
    e = _engine(model, params)
    prompts = _prompts()
    dict(e.serve(iter([[(0, prompts[0])]]), max_new_tokens=MAX_NEW))
    lat = e.telemetry.latency_ms()
    assert lat["ttft"]["p50"] > 0
    assert lat["e2e"]["p50"] >= lat["ttft"]["p50"]
