"""Optimizer micro-tests vs analytic references (reference pattern:
tests/unit/ops/adam kernel tests compare against torch.optim)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (OPTIMIZER_REGISTRY, FusedAdam, FusedLamb, FusedLion,
                                          OneBitAdam, build_optimizer)


def _quadratic_losses(opt, steps=60, dim=8):
    """Minimize ||x - t||^2; returns trajectory of losses."""
    target = jnp.arange(dim, dtype=jnp.float32)
    params = {"x": jnp.zeros((dim,), jnp.float32)}
    state = opt.init(params)
    losses = []
    for _ in range(steps):
        grads = {"x": 2 * (params["x"] - target)}
        losses.append(float(jnp.sum((params["x"] - target) ** 2)))
        params, state = opt.apply(grads, state, params)
    return losses


@pytest.mark.parametrize("name,lr", [("adam", 0.1), ("adamw", 0.1), ("lamb", 0.1),
                                     ("lion", 0.1), ("adagrad", 2.0), ("sgd", 0.01),
                                     ("onebitadam", 0.1), ("onebitlamb", 0.1)])
def test_optimizers_converge(name, lr):
    opt = build_optimizer(name, {"lr": lr})
    losses = _quadratic_losses(opt)
    assert losses[-1] < losses[0] * 0.2, f"{name}: {losses[0]} -> {losses[-1]}"


def test_adam_matches_torch():
    """Bit-level comparison against torch.optim.AdamW on random grads."""
    import torch
    dim = 16
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=dim).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.AdamW([tp], lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)

    opt = FusedAdam(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01, adam_w_mode=True)
    params = {"x": jnp.asarray(p0)}
    state = opt.init(params)

    for i in range(10):
        g = rng.normal(size=dim).astype(np.float32)
        tp.grad = torch.tensor(g)
        topt.step()
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)

    np.testing.assert_allclose(np.asarray(params["x"]), tp.detach().numpy(), atol=1e-5)


def test_lion_matches_reference_math():
    """One Lion step by hand."""
    opt = FusedLion(lr=0.1, betas=(0.9, 0.99), weight_decay=0.0)
    params = {"x": jnp.asarray([1.0, -1.0])}
    state = opt.init(params)
    g = {"x": jnp.asarray([0.5, -0.5])}
    new_params, new_state = opt.apply(g, state, params)
    # update = sign(0.9*0 + 0.1*g) = sign(g)
    np.testing.assert_allclose(np.asarray(new_params["x"]), [1.0 - 0.1, -1.0 + 0.1], atol=1e-6)
    # m = 0.99*0 + 0.01*g
    np.testing.assert_allclose(np.asarray(new_state["slots"]["x"]["m"]), [0.005, -0.005], atol=1e-7)


def test_onebit_adam_warmup_is_exact_adam():
    adam = FusedAdam(lr=0.01)
    onebit = OneBitAdam(lr=0.01, freeze_step=1000)
    p = {"x": jnp.asarray([1.0, 2.0, 3.0])}
    sa, so = adam.init(p), onebit.init(p)
    pa, po = p, p
    rng = np.random.default_rng(1)
    for _ in range(5):
        g = {"x": jnp.asarray(rng.normal(size=3).astype(np.float32))}
        pa, sa = adam.apply(g, sa, pa)
        po, so = onebit.apply(g, so, po)
    np.testing.assert_allclose(np.asarray(pa["x"]), np.asarray(po["x"]), atol=1e-6)


def test_registry_names():
    for key in ("fusedadam", "cpuadam", "deepspeedcpuadam", "zerooneadam"):
        assert key in OPTIMIZER_REGISTRY


def test_unknown_hyperparam_rejected():
    with pytest.raises(TypeError):
        FusedAdam(lr=0.1, bogus=1)
