"""Optimizer micro-tests vs analytic references (reference pattern:
tests/unit/ops/adam kernel tests compare against torch.optim)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (OPTIMIZER_REGISTRY, FusedAdam, FusedLamb, FusedLion,
                                          OneBitAdam, build_optimizer)


def _quadratic_losses(opt, steps=60, dim=8):
    """Minimize ||x - t||^2; returns trajectory of losses."""
    target = jnp.arange(dim, dtype=jnp.float32)
    params = {"x": jnp.zeros((dim,), jnp.float32)}
    state = opt.init(params)
    losses = []
    for _ in range(steps):
        grads = {"x": 2 * (params["x"] - target)}
        losses.append(float(jnp.sum((params["x"] - target) ** 2)))
        params, state = opt.apply(grads, state, params)
    return losses


@pytest.mark.parametrize("name,lr", [("adam", 0.1), ("adamw", 0.1), ("lamb", 0.1),
                                     ("lion", 0.1), ("adagrad", 2.0), ("sgd", 0.01),
                                     ("onebitadam", 0.1), ("onebitlamb", 0.1)])
def test_optimizers_converge(name, lr):
    opt = build_optimizer(name, {"lr": lr})
    losses = _quadratic_losses(opt)
    assert losses[-1] < losses[0] * 0.2, f"{name}: {losses[0]} -> {losses[-1]}"


def test_adam_matches_torch():
    """Bit-level comparison against torch.optim.AdamW on random grads."""
    import torch
    dim = 16
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=dim).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.AdamW([tp], lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)

    opt = FusedAdam(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01, adam_w_mode=True)
    params = {"x": jnp.asarray(p0)}
    state = opt.init(params)

    for i in range(10):
        g = rng.normal(size=dim).astype(np.float32)
        tp.grad = torch.tensor(g)
        topt.step()
        params, state = opt.apply({"x": jnp.asarray(g)}, state, params)

    np.testing.assert_allclose(np.asarray(params["x"]), tp.detach().numpy(), atol=1e-5)


def test_lion_matches_reference_math():
    """One Lion step by hand."""
    opt = FusedLion(lr=0.1, betas=(0.9, 0.99), weight_decay=0.0)
    params = {"x": jnp.asarray([1.0, -1.0])}
    state = opt.init(params)
    g = {"x": jnp.asarray([0.5, -0.5])}
    new_params, new_state = opt.apply(g, state, params)
    # update = sign(0.9*0 + 0.1*g) = sign(g)
    np.testing.assert_allclose(np.asarray(new_params["x"]), [1.0 - 0.1, -1.0 + 0.1], atol=1e-6)
    # m = 0.99*0 + 0.01*g
    np.testing.assert_allclose(np.asarray(new_state["slots"]["x"]["m"]), [0.005, -0.005], atol=1e-7)


def test_onebit_adam_warmup_is_exact_adam():
    adam = FusedAdam(lr=0.01)
    onebit = OneBitAdam(lr=0.01, freeze_step=1000)
    p = {"x": jnp.asarray([1.0, 2.0, 3.0])}
    sa, so = adam.init(p), onebit.init(p)
    pa, po = p, p
    rng = np.random.default_rng(1)
    for _ in range(5):
        g = {"x": jnp.asarray(rng.normal(size=3).astype(np.float32))}
        pa, sa = adam.apply(g, sa, pa)
        po, so = onebit.apply(g, so, po)
    np.testing.assert_allclose(np.asarray(pa["x"]), np.asarray(po["x"]), atol=1e-6)


def test_registry_names():
    for key in ("fusedadam", "cpuadam", "deepspeedcpuadam", "zerooneadam"):
        assert key in OPTIMIZER_REGISTRY


def test_unknown_hyperparam_rejected():
    with pytest.raises(TypeError):
        FusedAdam(lr=0.1, bogus=1)


def test_master_weights_bf16_matches_fp32():
    """fp32 master weights (reference runtime/bf16_optimizer.py:34): a bf16
    param trained with tiny updates must track the fp32 trajectory; without
    master weights the bf16 round-trip loses the updates entirely."""
    steps = 200
    lr = 1e-4
    g = {"x": jnp.full((64,), 0.5, jnp.float32)}

    def run(dtype, master):
        opt = FusedAdam(lr=lr, weight_decay=0.0)
        opt.master_weights = master
        params = {"x": jnp.ones((64,), dtype)}
        state = opt.init(params)
        if master and dtype != jnp.float32:
            assert "master" in state["slots"]["x"], "master slot missing"
        for _ in range(steps):
            params, state = opt.apply(g, state, params)
        # effective high-precision value: master if kept, else the param
        eff = state["slots"]["x"].get("master", params["x"]) if isinstance(
            state["slots"]["x"], dict) else params["x"]
        return np.asarray(eff, np.float32), np.asarray(params["x"], np.float32)

    ref, _ = run(jnp.float32, False)
    with_master_eff, with_master_p = run(jnp.bfloat16, True)
    without_master, _ = run(jnp.bfloat16, False)

    # master trajectory matches fp32 to fp32 accuracy
    np.testing.assert_allclose(with_master_eff, ref, rtol=1e-5, atol=1e-6)
    # the bf16 copy is the cast of the master
    np.testing.assert_allclose(with_master_p, ref.astype(np.float32), rtol=1e-2)
    # and the no-master scheme visibly drifts from the fp32 trajectory
    drift_master = np.abs(with_master_eff - ref).max()
    drift_plain = np.abs(without_master - ref).max()
    assert drift_plain > 10 * max(drift_master, 1e-12), (
        f"expected visible drift without master: {drift_plain} vs {drift_master}")


def test_engine_enables_master_weights_for_bf16():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, get_config
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    # bf16 *stored* params (param_dtype) is the case that loses updates
    # without fp32 master copies; fp32-stored params are their own master.
    model = build_model(get_config("tiny-gpt2"), param_dtype="bfloat16")
    dp = len(jax.devices())
    config = {
        "train_batch_size": 4 * dp,
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    assert engine.optimizer.master_weights
    slots = engine.opt_state["slots"]
    emb_slot = slots["embed"]["tok"]
    assert "master" in emb_slot and emb_slot["master"].dtype == jnp.float32
    # train a couple of steps and confirm master stays fp32 and finite
    ids = np.random.default_rng(0).integers(0, model.cfg.vocab_size, (4 * dp, 16))
    for _ in range(2):
        loss = engine.train_batch({"input_ids": ids, "labels": ids})
    assert np.isfinite(float(jax.device_get(loss)))
    m = engine.opt_state["slots"]["embed"]["tok"]["master"]
    assert m.dtype == jnp.float32
