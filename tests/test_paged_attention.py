"""Pallas paged decode attention vs the XLA gather reference."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention


def _reference(q, kpool, vpool, tables, lens):
    """Gather pages → masked softmax attention. q: (B,H,D);
    kpool: (KVH,NB,bs,D)."""
    kvh, nb, bs, d = kpool.shape
    b, h, _ = q.shape
    kp = kpool[:, tables]                    # (KVH, B, MB, bs, D)
    kp = kp.reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)   # (B, KVH, S, D)
    vp = vpool[:, tables].reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)
    group = h // kvh
    kp = jnp.repeat(kp, group, axis=1)
    vp = jnp.repeat(vp, group, axis=1)
    s = jnp.einsum("bhd,bhkd->bhk", q, kp, preferred_element_type=jnp.float32)
    s = s * (d ** -0.5)
    slot = jnp.arange(kp.shape[2])[None, None, :]
    s = jnp.where(slot < lens[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", p, vp)


@pytest.mark.parametrize("h,kvh,d", [(4, 4, 64), (8, 2, 64), (4, 1, 128)])
def test_paged_decode_matches_gather(h, kvh, d):
    b, bs, nb, mb = 3, 16, 12, 4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    # distinct physical pages per sequence; lengths not page-aligned
    tables = jnp.asarray(rng.permutation(nb)[: b * mb].reshape(b, mb), jnp.int32)
    lens = jnp.asarray([5, 16 * 2 + 3, 16 * 4], jnp.int32)

    out = paged_decode_attention(q, kpool, vpool, tables, lens)
    ref = _reference(q, kpool, vpool, tables, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_paged_decode_under_jit_and_donation():
    b, h, kvh, d, bs, nb, mb = 2, 4, 2, 64, 8, 6, 3
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    lens = jnp.asarray([20, 9], jnp.int32)
    f = jax.jit(paged_decode_attention)
    out = f(q, kpool, vpool, tables, lens)
    ref = _reference(q, kpool, vpool, tables, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_fused_contiguous_decode_matches_xla():
    """Fused single-token decode over a contiguous cache (the v1
    softmax_context analog) matches the masked XLA form."""
    from deepspeed_tpu.ops.pallas.decode_attention import fused_decode_attention
    import deepspeed_tpu.ops.attention as att
    rng = np.random.default_rng(3)
    B, S, H, KVH, D = 4, 256, 8, 4, 64
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    cl = jnp.asarray(rng.integers(10, S, (B,)), jnp.int32)
    orig = att._use_pallas
    att._use_pallas = lambda: False
    try:
        ref = att.decode_attention(q, k, v, cl)
    finally:
        att._use_pallas = orig
    out = fused_decode_attention(q[:, 0], k, v, cl, block=128)[:, None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


# ---- unified ragged kernel: prefill chunks, windows, ALiBi, softcap ------

def _ragged_reference(q, kpool, vpool, tables, positions, *, window=0,
                      alibi_slopes=None, softcap=0.0, scale=None):
    """Gather-pages reference for the unified kernel: q (B,C,H,D),
    positions (B,C) absolute slots (-1 pad)."""
    kvh, nb, bs, d = kpool.shape
    b, c, h, _ = q.shape
    kp = kpool[:, tables].reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)
    vp = vpool[:, tables].reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)
    group = h // kvh
    kp = jnp.repeat(kp, group, axis=1)
    vp = jnp.repeat(vp, group, axis=1)
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bchd,bhkd->bhck", q, kp,
                   preferred_element_type=jnp.float32) * scale
    slot = jnp.arange(kp.shape[2])[None, None, None, :]        # (1,1,1,S)
    pos = positions[:, None, :, None].astype(jnp.float32)      # (B,1,C,1)
    if alibi_slopes is not None:
        s = s + jnp.asarray(alibi_slopes, jnp.float32)[None, :, None, None] \
            * (slot - pos)
    if softcap:
        s = softcap * jnp.tanh(s / softcap)
    mask = slot <= pos
    if window:
        mask = mask & (slot > pos - window)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhck,bhkd->bchd", p, vp)


def _ragged_case(c=4, h=4, kvh=2, d=64, **kw):
    from deepspeed_tpu.ops.pallas.paged_attention import paged_ragged_attention
    b, bs, nb, mb = 2, 16, 10, 4
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((b, c, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    tables = jnp.asarray(rng.permutation(nb)[: b * mb].reshape(b, mb), jnp.int32)
    # chunk positions: seq 0 prefilling slots 17..17+c-1; seq 1 decode-ish
    # near its end with padding rows
    pos0 = 17 + np.arange(c)
    pos1 = np.concatenate([[40, 41], -np.ones(max(0, c - 2))])[:c]
    positions = jnp.asarray(np.stack([pos0, pos1]), jnp.int32)
    out = paged_ragged_attention(q, kpool, vpool, tables, positions, **kw)
    ref = _ragged_reference(q, kpool, vpool, tables, positions, **kw)
    valid = np.asarray(positions) >= 0
    np.testing.assert_allclose(np.asarray(out)[valid], np.asarray(ref)[valid],
                               rtol=3e-5, atol=3e-5)


def test_paged_ragged_prefill_causal():
    _ragged_case()


def test_paged_ragged_prefill_window():
    _ragged_case(window=8)


def test_paged_ragged_traced_window():
    """Per-layer window patterns reach the kernel as traced scalars."""
    from deepspeed_tpu.ops.pallas.paged_attention import paged_ragged_attention

    def run(win):
        b, c, h, kvh, d, bs, nb, mb = 2, 2, 4, 2, 64, 16, 10, 4
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((b, c, h, d)), jnp.float32) * 0.1
        kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
        vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
        tables = jnp.asarray(rng.permutation(nb)[: b * mb].reshape(b, mb), jnp.int32)
        positions = jnp.asarray([[30, 31], [12, 13]], jnp.int32)
        out = paged_ragged_attention(q, kpool, vpool, tables, positions,
                                     window=win)
        ref = _ragged_reference(q, kpool, vpool, tables, positions,
                                window=int(win))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)

    for w in (jnp.asarray(6, jnp.int32), jnp.asarray(0, jnp.int32)):
        run(w)


def test_paged_ragged_alibi():
    from deepspeed_tpu.models.layers import alibi_slopes
    _ragged_case(h=4, kvh=4, alibi_slopes=alibi_slopes(4))


def test_paged_ragged_softcap_and_scale():
    _ragged_case(softcap=30.0, scale=0.2)


def test_paged_decode_window_alibi_wrapper():
    """Decode wrapper with window+ALiBi vs reference at C=1."""
    from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention
    from deepspeed_tpu.models.layers import alibi_slopes
    b, h, kvh, d, bs, nb, mb = 2, 4, 4, 64, 16, 8, 3
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    tables = jnp.asarray(rng.permutation(nb)[: b * mb].reshape(b, mb), jnp.int32)
    lens = jnp.asarray([30, 14], jnp.int32)
    sl = alibi_slopes(h)
    out = paged_decode_attention(q, kpool, vpool, tables, lens, window=9,
                                 alibi_slopes=sl)
    ref = _ragged_reference(q[:, None], kpool, vpool, tables,
                            (lens - 1)[:, None], window=9, alibi_slopes=sl)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref[:, 0]),
                               rtol=3e-5, atol=3e-5)
