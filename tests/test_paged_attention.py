"""Pallas paged decode attention vs the XLA gather reference."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention


def _reference(q, kpool, vpool, tables, lens):
    """Gather pages → masked softmax attention. q: (B,H,D);
    kpool: (KVH,NB,bs,D)."""
    kvh, nb, bs, d = kpool.shape
    b, h, _ = q.shape
    kp = kpool[:, tables]                    # (KVH, B, MB, bs, D)
    kp = kp.reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)   # (B, KVH, S, D)
    vp = vpool[:, tables].reshape(kvh, b, -1, d).transpose(1, 0, 2, 3)
    group = h // kvh
    kp = jnp.repeat(kp, group, axis=1)
    vp = jnp.repeat(vp, group, axis=1)
    s = jnp.einsum("bhd,bhkd->bhk", q, kp, preferred_element_type=jnp.float32)
    s = s * (d ** -0.5)
    slot = jnp.arange(kp.shape[2])[None, None, :]
    s = jnp.where(slot < lens[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", p, vp)


@pytest.mark.parametrize("h,kvh,d", [(4, 4, 64), (8, 2, 64), (4, 1, 128)])
def test_paged_decode_matches_gather(h, kvh, d):
    b, bs, nb, mb = 3, 16, 12, 4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    # distinct physical pages per sequence; lengths not page-aligned
    tables = jnp.asarray(rng.permutation(nb)[: b * mb].reshape(b, mb), jnp.int32)
    lens = jnp.asarray([5, 16 * 2 + 3, 16 * 4], jnp.int32)

    out = paged_decode_attention(q, kpool, vpool, tables, lens)
    ref = _reference(q, kpool, vpool, tables, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_paged_decode_under_jit_and_donation():
    b, h, kvh, d, bs, nb, mb = 2, 4, 2, 64, 8, 6, 3
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32) * 0.1
    kpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    vpool = jnp.asarray(rng.standard_normal((kvh, nb, bs, d)), jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    lens = jnp.asarray([20, 9], jnp.int32)
    f = jax.jit(paged_decode_attention)
    out = f(q, kpool, vpool, tables, lens)
    ref = _reference(q, kpool, vpool, tables, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_fused_contiguous_decode_matches_xla():
    """Fused single-token decode over a contiguous cache (the v1
    softmax_context analog) matches the masked XLA form."""
    from deepspeed_tpu.ops.pallas.decode_attention import fused_decode_attention
    import deepspeed_tpu.ops.attention as att
    rng = np.random.default_rng(3)
    B, S, H, KVH, D = 4, 256, 8, 4, 64
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    cl = jnp.asarray(rng.integers(10, S, (B,)), jnp.int32)
    orig = att._use_pallas
    att._use_pallas = lambda: False
    try:
        ref = att.decode_attention(q, k, v, cl)
    finally:
        att._use_pallas = orig
    out = fused_decode_attention(q[:, 0], k, v, cl, block=128)[:, None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)
