"""Aux subsystem tests: MoE facade, launcher, elasticity, flops profiler,
curriculum/data pipeline, compression, universal checkpoint, zero_to_fp32,
hybrid engine (reference: tests/unit/{moe,launcher,elasticity,profiling,
data_efficiency,compression,checkpoint})."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


# ---- MoE facade ----

def test_moe_facade(mesh_8dp, rng):
    from deepspeed_tpu.moe.layer import MoE
    moe = MoE(hidden_size=32, num_experts=4, k=2, capacity_factor=2.0, ffn_dim=64)
    params = moe.init(rng)
    x = jax.random.normal(rng, (2, 8, 32))
    out, aux, counts = moe(params, x)
    assert out.shape == x.shape
    assert jnp.isfinite(aux)
    assert int(jnp.sum(counts)) > 0


def test_top1_gate(mesh_8dp, rng):
    from deepspeed_tpu.moe.layer import TopKGate
    gate = TopKGate(model_dim=16, num_experts=4, k=1, capacity_factor=2.0)
    params = gate.init(rng)
    tokens = jax.random.normal(rng, (32, 16))
    combine, dispatch, aux = gate(params, tokens)
    # each token dispatched at most once (top-1)
    per_token = jnp.sum(dispatch, axis=(1, 2))
    assert int(jnp.max(per_token)) <= 1


# ---- launcher ----

def test_hostfile_parse(tmp_path):
    from deepspeed_tpu.launcher.runner import parse_hostfile, parse_inclusion_exclusion
    hf = tmp_path / "hosts"
    hf.write_text("worker-0 slots=4\nworker-1 slots=4\n# comment\n")
    pool = parse_hostfile(str(hf))
    assert pool == {"worker-0": 4, "worker-1": 4}
    active = parse_inclusion_exclusion(pool, include_str="worker-1:0,2")
    assert active == {"worker-1": [0, 2]}
    active = parse_inclusion_exclusion(pool, exclude_str="worker-0")
    assert list(active) == ["worker-1"]
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, include_str="a", exclude_str="b")


def test_launcher_dry_run(tmp_path, capsys):
    from deepspeed_tpu.launcher.runner import main
    hf = tmp_path / "hosts"
    hf.write_text("h1 slots=2\nh2 slots=2\n")
    rc = main(["--hostfile", str(hf), "--dry_run", "train.py", "--lr", "1e-4"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[h1]" in out and "[h2]" in out
    assert "WORLD_SIZE=4" in out and "NODE_RANK=1" in out


# ---- env report ----

def test_env_report():
    from deepspeed_tpu.env_report import env_info, op_report
    r = op_report()
    assert "cpu_adam" in r and "flash_attn" in r
    e = env_info()
    assert "jax version" in e


# ---- elasticity ----

def test_elastic_config_math():
    from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                     get_candidate_batch_sizes,
                                                     get_valid_gpus)
    # reference HCN semantics: each base scaled by the largest highly
    # composite number keeping it under the cap (8*6=48, 12*4=48)
    assert get_candidate_batch_sizes([8, 12], 50) == [48]
    assert get_candidate_batch_sizes([7], 50) == [42]
    assert get_valid_gpus(16, [2, 4], 1, 100) == [1, 2, 4, 8]
    cfg = {"elasticity": {"enabled": True, "micro_batch_sizes": [2, 4],
                          "max_train_batch_size": 64, "min_gpus": 1, "max_gpus": 16}}
    batch, gpus = compute_elastic_config(cfg)
    assert batch % 2 == 0 and len(gpus) > 0
    final, valid, mb = compute_elastic_config(cfg, world_size=8, return_microbatch=True)
    assert 8 in valid and final % (8 * mb) == 0


def test_elastic_incompatible_world_size():
    from deepspeed_tpu.elasticity.elasticity import (ElasticityIncompatibleWorldSize,
                                                     compute_elastic_config)
    cfg = {"elasticity": {"enabled": True, "micro_batch_sizes": [4],
                          "max_train_batch_size": 16, "min_gpus": 1, "max_gpus": 4}}
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=1000)


# ---- flops profiler ----

def test_flops_profiler(mesh_8dp, rng):
    from deepspeed_tpu.profiling.flops_profiler.profiler import (FlopsProfiler,
                                                                 transformer_flops)
    model = build_model("tiny")
    params = model.init(rng)
    ids = jnp.zeros((2, 16), jnp.int32)
    prof = FlopsProfiler()
    cost = prof.profile_fn(model.apply, params, ids, run=True)
    assert prof.get_total_flops() > 0
    assert prof.get_total_duration() > 0
    report = prof.print_model_profile()
    assert "flops" in report

    est = transformer_flops(model.cfg, batch=2, seq=16)
    assert est["total_flops"] > 0 and est["params"] > 0


def test_analytic_param_count_matches_model():
    from deepspeed_tpu.profiling.flops_profiler.profiler import _param_count
    for preset in ("tiny", "gpt2-small", "llama2-7b"):
        model = build_model(preset)
        analytic = _param_count(model.cfg)
        actual = model.param_count()
        assert abs(analytic - actual) / actual < 0.02, (preset, analytic, actual)


# ---- curriculum / data pipeline ----

def test_curriculum_linear():
    from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
    sched = CurriculumScheduler({
        "curriculum_type": "fixed_linear", "min_difficulty": 8, "max_difficulty": 64,
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert sched.update_difficulty(0) == 8
    mid = sched.update_difficulty(50)
    assert 8 < mid < 64 and mid % 8 == 0
    assert sched.update_difficulty(100) == 64
    assert sched.update_difficulty(1000) == 64


def test_curriculum_discrete():
    from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
    sched = CurriculumScheduler({
        "curriculum_type": "fixed_discrete", "min_difficulty": 2, "max_difficulty": 10,
        "schedule_config": {"difficulty": [2, 5, 10], "max_step": [10, 20]}})
    assert sched.update_difficulty(5) == 2
    assert sched.update_difficulty(15) == 5
    assert sched.update_difficulty(25) == 10


def test_data_sampler_partition():
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
    seen = []
    for rank in range(2):
        s = DeepSpeedDataSampler(total_samples=32, micro_batch_size=2,
                                 data_parallel_rank=rank, data_parallel_size=2,
                                 gradient_accumulation_steps=2, shuffle=False)
        batches = list(s)
        assert all(len(b) == 2 for b in batches)
        seen.extend(np.concatenate(batches).tolist())
    assert sorted(seen) == list(range(32))  # full coverage, no overlap


def test_random_ltd(rng):
    from deepspeed_tpu.runtime.data_pipeline.basic_layer import RandomLayerTokenDrop
    layer = RandomLayerTokenDrop(lambda p, x: x * 2.0, keep_ratio=0.5)
    x = jnp.ones((2, 16, 4))
    out = layer(None, x, rng, train=True)
    doubled = int(jnp.sum(out == 2.0))
    kept = int(jnp.sum(out == 1.0))
    assert doubled == 2 * 8 * 4 and kept == 2 * 8 * 4


# ---- compression ----

def test_fake_quant_and_prune(rng):
    from deepspeed_tpu.compression.compress import fake_quantize, magnitude_prune
    w = jax.random.normal(rng, (64, 64))
    q = fake_quantize(w, bits=8)
    assert float(jnp.max(jnp.abs(q - w))) < float(jnp.max(jnp.abs(w))) / 127
    # straight-through gradient
    g = jax.grad(lambda w: jnp.sum(fake_quantize(w) ** 2))(w)
    assert jnp.all(jnp.isfinite(g))
    p = magnitude_prune(w, 0.5)
    assert 0.45 < float(jnp.mean(p == 0)) < 0.55


def test_layer_reduction(mesh_8dp, rng):
    from deepspeed_tpu.compression.compress import redundancy_clean
    model = build_model("tiny", num_layers=4)
    params = model.init(rng)
    cfg = {"compression_training": {"layer_reduction": {
        "enabled": True, "keep_layers": [0, 2]}}}
    reduced = redundancy_clean(params, cfg)
    assert jax.tree.leaves(reduced["layers"])[0].shape[0] == 2


# ---- universal checkpoint + zero_to_fp32 ----

def test_universal_checkpoint_reshard(tmp_path):
    """Save on dp8, resume on dp4+tp2 — the topology-free format reshards."""
    from deepspeed_tpu.checkpoint.universal import ds_to_universal, load_universal_checkpoint
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2}, "steps_per_print": 10 ** 9, "seed": 3}
    groups.reset_mesh()
    model = build_model("tiny")
    e1, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    e1.train_batch({"input_ids": ids, "labels": ids})
    ds_to_universal(e1, str(tmp_path / "uni"))
    ref = np.asarray(e1.module_params["embed"]["tok"])

    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=4, tensor=2))
    model2 = build_model("tiny")
    e2, _, _, _ = ds.initialize(model=model2, config=dict(cfg))
    load_universal_checkpoint(e2, str(tmp_path / "uni"))
    np.testing.assert_allclose(ref, np.asarray(e2.module_params["embed"]["tok"]),
                               atol=1e-6)
    assert e2.global_steps == e1.global_steps
    # training continues on the new topology
    loss = e2.train_batch({"input_ids": ids, "labels": ids})
    assert np.isfinite(float(loss))


def test_zero_to_fp32(tmp_path):
    from deepspeed_tpu.utils.zero_to_fp32 import get_fp32_state_dict_from_zero_checkpoint
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2}, "steps_per_print": 10 ** 9}
    groups.reset_mesh()
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    engine.save_checkpoint(str(tmp_path), tag="t0")
    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path), tag="t0")
    assert "embed.tok" in sd
    assert sd["embed.tok"].dtype == np.float32
    np.testing.assert_allclose(sd["embed.tok"],
                               np.asarray(engine.module_params["embed"]["tok"]))


# ---- hybrid engine ----

def test_hybrid_engine_generate(mesh_8dp):
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10 ** 9}
    engine = DeepSpeedHybridEngine(model=build_model("tiny"), config=cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 200, (2, 8))
    out = engine.generate(prompt, max_new_tokens=4, temperature=0.0)
    assert out.shape == (2, 12)
    # train a step, generate again (params updated in place)
    ids = rng.integers(0, 256, (16, 32))
    engine.train_batch({"input_ids": ids, "labels": ids})
    out2 = engine.generate(prompt, max_new_tokens=4, temperature=0.0)
    assert out2.shape == (2, 12)


def test_engine_emits_monitor_events(tmp_path):
    """The engine writes loss/lr/loss-scale/grad-norm/throughput samples to
    the monitor every steps_per_print (reference engine.py:2001,2222), not
    just lr."""
    import csv as csv_mod
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 2,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "t"},
    }
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    rng = np.random.default_rng(0)
    for _ in range(4):
        ids = rng.integers(0, 256, (16, 32))
        engine.train_batch({"input_ids": ids, "labels": ids})
    files = list((tmp_path).rglob("*.csv"))
    names = {f.stem.split("-")[-1] if "-" in f.stem else f.stem for f in files}
    joined = " ".join(str(f) for f in files)
    for key in ("loss", "lr", "loss_scale"):
        assert any(key in str(f) for f in files), (key, files)


# ---- autotuner strategies ----

def test_tuner_strategies():
    """Grid covers everything in order; random covers everything; model-based
    fits the saturating throughput curve and converges on the best candidate
    without exhausting the grid (reference autotuning/tuner/)."""
    from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                                RandomTuner, build_tuner)
    exps = [{"zero_stage": s, "micro_batch": mb}
            for s in (0, 1) for mb in (1, 2, 4, 8)]

    def true_tput(e):       # saturating in mb, stage 1 slightly slower
        base = e["micro_batch"] / (0.5 + 0.05 * e["micro_batch"])
        return base * (0.9 if e["zero_stage"] == 1 else 1.0)

    g = GridSearchTuner(exps)
    order = []
    while g.has_next():
        e = g.next_trial()
        order.append(e)
        g.update(e, true_tput(e))
    assert order == exps
    assert g.best()[0] == {"zero_stage": 0, "micro_batch": 8}

    r = RandomTuner(exps, seed=3)
    while r.has_next():
        e = r.next_trial()
        r.update(e, true_tput(e))
    assert r.best()[0] == {"zero_stage": 0, "micro_batch": 8}

    m = ModelBasedTuner(exps)
    for _ in range(6):      # under-budget: 6 of 8 trials
        e = m.next_trial()
        m.update(e, true_tput(e))
    assert m.best()[0]["micro_batch"] == 8   # model extrapolates to the top

    import pytest as _pytest
    with _pytest.raises(ValueError):
        build_tuner("nope", exps)


def test_autotuner_strategy_integration(monkeypatch):
    """Autotuner routes trials through the selected strategy."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner

    class FakeModel:
        class cfg:
            vocab_size = 16
        def param_count(self):
            return 1000

    at = Autotuner(FakeModel(), {}, micro_batch_candidates=(1, 2, 4),
                   zero_stage_candidates=(0, 1), strategy="model_based",
                   max_trials=4, remat_candidates=("none",))
    monkeypatch.setattr(
        at, "_trial",
        lambda s, mb, remat="none": mb / (0.5 + 0.1 * mb) * (0.8 if s else 1.0))
    patch = at.tune()
    assert patch["train_micro_batch_size_per_gpu"] == 4
    assert patch["zero_optimization"]["stage"] == 0
    assert len(at.results) <= 4


def test_autotuner_remat_dimension(monkeypatch):
    """remat joins the search space (round-5: "dots" is a measured
    THROUGHPUT win on HBM-bound parts, not only a memory knob): the
    heuristic runs a remat post-pass at the winning (stage, mb) and the
    returned patch carries the activation_checkpointing policy."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner

    class FakeModel:
        class cfg:
            vocab_size = 16
        def param_count(self):
            return 1000

    at = Autotuner(FakeModel(), {}, micro_batch_candidates=(1, 2),
                   zero_stage_candidates=(0,),
                   remat_candidates=("none", "dots"))
    monkeypatch.setattr(
        at, "_trial",
        lambda s, mb, remat="none": mb * (1.1 if remat == "dots" else 1.0))
    patch = at.tune()
    assert patch["train_micro_batch_size_per_gpu"] == 2
    assert patch["activation_checkpointing"]["policy"] == "dots"
    # the strategy path searches the full product including remat
    at2 = Autotuner(FakeModel(), {}, micro_batch_candidates=(1, 2),
                    zero_stage_candidates=(0,), strategy="gridsearch",
                    remat_candidates=("none", "dots"))
    monkeypatch.setattr(
        at2, "_trial",
        lambda s, mb, remat="none": mb * (1.1 if remat == "dots" else 1.0))
    patch2 = at2.tune()
    assert patch2["activation_checkpointing"]["policy"] == "dots"


def test_multinode_runners_build_commands():
    """Runner family (reference multinode_runner.py): each transport builds
    the right fan-out invocation from the per-node commands."""
    from collections import OrderedDict
    from deepspeed_tpu.launcher.multinode_runner import build_runner
    import pytest as _pytest

    world = OrderedDict([("h1", [0, 1]), ("h2", [0, 1])])
    per_node = [("h1", "ENV=1 python -m x"), ("h2", "ENV=1 python -m x")]

    pdsh = build_runner("pdsh", None, world).get_cmd(per_node)
    assert len(pdsh) == 2 and pdsh[0].startswith("pdsh -S -w h1 ")

    mpi = build_runner("openmpi", None, world).get_cmd(per_node)
    assert len(mpi) == 1 and "-H h1:2,h2:2" in mpi[0] and "-np 2" in mpi[0]

    slurm = build_runner("slurm", None, world).get_cmd(per_node)
    assert "--nodes=2" in slurm[0] and "--nodelist=h1,h2" in slurm[0]

    mpich = build_runner("mpich", None, world).get_cmd(per_node)
    assert "-hosts h1,h2" in mpich[0]

    with _pytest.raises(ValueError):
        build_runner("nope", None, world)


def test_compression_scheduler_offsets(rng):
    """Techniques activate at their schedule_offset and apply() transforms
    only the live ones (reference compression/scheduler.py)."""
    from deepspeed_tpu.compression.scheduler import CompressionScheduler
    cfg = {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 2},
            "different_groups": {"g": {"params": {"start_bits": 8},
                                       "modules": ["mlp"]}}},
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"g": {"params": {"dense_ratio": 0.5},
                                       "modules": ["mlp"]}}},
    }}
    sched = CompressionScheduler(cfg)
    params = {"mlp": {"w": jax.random.normal(rng, (32, 32))}}
    assert sched.step() == []                       # step 1: nothing yet
    assert sched.step() == ["weight_quantization"]  # step 2
    p1 = sched.apply(params)
    assert float(jnp.sum(p1["mlp"]["w"] == 0.0)) < 32 * 32 * 0.4  # no pruning yet
    sched.step(3)
    assert sched.active_techniques() == ["weight_quantization", "sparse_pruning"]
    p2 = sched.apply(params)
    zeros = float(jnp.sum(p2["mlp"]["w"] == 0.0))
    assert zeros >= 32 * 32 * 0.5                   # pruned to dense_ratio


def test_comet_monitor_config_and_degradation():
    """Comet joins the monitor fan-out (reference monitor/comet.py); absent
    SDK degrades to disabled without erroring, and events still flow."""
    from deepspeed_tpu.runtime.config import DeepSpeedMonitorConfig
    from deepspeed_tpu.monitor.monitor import CometMonitor, MonitorMaster
    cfg = DeepSpeedMonitorConfig(comet={"enabled": True, "project": "p",
                                        "workspace": "w"})
    assert cfg.enabled
    m = MonitorMaster(cfg)
    assert any(isinstance(x, CometMonitor) for x in m.monitors)
    m.write_events([("loss", 1.0, 1)])   # no-op when SDK missing, no raise


def test_elastic_in_process_rejoin(tmp_path):
    """In-process elastic recovery (reference elastic_agent.py:32, minus the
    process restart): two OS processes train ZeRO-2; a universal snapshot is
    taken; rank 1 is killed; rank 0 — SAME PID — tears down the distributed
    runtime, rebuilds the mesh at world 1, reshards from the universal
    checkpoint, and keeps training."""
    import json
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import json, os, sys, time
        sys.path.insert(0, %r)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import deepspeed_tpu as ds
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.elasticity.rejoin import InProcessElasticWorker
        from deepspeed_tpu.models import build_model
        from deepspeed_tpu.utils import groups

        RUN = os.environ["DS_TEST_RUN_DIR"]
        rank = int(os.environ["RANK"])
        pid0 = os.getpid()

        dist.init_distributed(verbose=False, elastic=True,
                              distributed_port=int(os.environ["DS_TEST_PORT"]))

        def make_engine(world):
            groups.reset_mesh()
            model = build_model("tiny")
            dp = len(jax.devices())
            engine, _, _, _ = ds.initialize(model=model, config={
                "train_batch_size": 2 * dp,
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10 ** 9, "seed": 7})
            return engine

        w = InProcessElasticWorker(make_engine, os.path.join(RUN, "uckpt"),
                                   RUN, heartbeat_timeout=3.0)
        w.start(rank, 2)
        engine = make_engine(2)
        rng = np.random.default_rng(0)

        def step(engine):
            bs = engine.train_batch_size()
            ids = rng.integers(0, 256, (bs, 16))
            return float(engine.train_batch({"input_ids": ids, "labels": ids}))

        losses = [step(engine) for _ in range(3)]
        w.heartbeat()
        w.save_universal(engine)
        snap = np.asarray(jax.tree.leaves(engine.module_params)[0],
                          np.float32).copy()
        if rank == 1:
            os._exit(1)                      # hard death, no cleanup

        # rank 0: wait for the peer's heartbeat to go stale, then rejoin
        deadline = time.time() + 30
        while not w.membership_changed():
            if time.time() > deadline:
                raise RuntimeError("peer death never detected")
            time.sleep(0.5)
        engine = w.rejoin()
        assert os.getpid() == pid0            # same process, no restart
        assert jax.process_count() == 1
        assert engine.global_steps == 3       # resumed from the snapshot
        restore_err = float(np.max(np.abs(np.asarray(
            jax.tree.leaves(engine.module_params)[0], np.float32) - snap)))
        after = [step(engine) for _ in range(2)]
        assert all(np.isfinite(after))
        print("RESULT " + json.dumps({"losses": losses, "after": after,
                                      "restore_err": restore_err,
                                      "world_end": len(jax.devices())}))
    """) % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update(MASTER_ADDR="127.0.0.1", WORLD_SIZE="2", JAX_PLATFORMS="cpu",
               DS_TEST_PORT=str(port), DS_TEST_RUN_DIR=str(tmp_path))
    procs = []
    try:
        for r in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(worker)], env=dict(env, RANK=str(r)),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        out0, _ = procs[0].communicate(timeout=300)
        procs[1].wait(timeout=30)
        assert procs[0].returncode == 0, out0.decode()[-2000:]
        line = [ln for ln in out0.decode().splitlines()
                if ln.startswith("RESULT ")][0]
        res = json.loads(line[len("RESULT "):])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert res["world_end"] == 2              # rank 0's two local devices
    assert len(res["after"]) == 2
    # state restoration is the property under test: the rebuilt engine's
    # params equal the pre-kill snapshot (the universal checkpoint was taken
    # at the same step), and post-rejoin training stays finite — a strict
    # loss-decrease over 2 random-batch steps would be stochastic
    assert res["restore_err"] <= 1e-5
    assert all(np.isfinite(res["after"]))


def test_xtc_binarize_ternarize():
    """XTC 1-/2-bit weight grids (reference Binary/TernaryQuantizer): value
    sets, scales, and straight-through gradients."""
    from deepspeed_tpu.compression.compress import (binarize, fake_quantize,
                                                    ternarize)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)), jnp.float32)
    b = binarize(w)
    # per-output-channel two-point grid
    for col in range(4):
        vals = np.unique(np.round(np.abs(np.asarray(b[:, col])), 6))
        assert len(vals) == 1
    np.testing.assert_allclose(np.asarray(jnp.abs(b).mean(0)),
                               np.asarray(jnp.abs(w).mean(0)), rtol=1e-5)
    t = ternarize(w)
    for col in range(4):
        vals = np.unique(np.round(np.asarray(t[:, col]), 6))
        assert len(vals) <= 3 and 0.0 in vals
    # STE: identity gradients through both
    g = jax.grad(lambda w: jnp.sum(binarize(w) * 3.0))(w)
    np.testing.assert_allclose(np.asarray(g), 3.0)
    # fake_quantize routes the XTC bit-widths
    np.testing.assert_allclose(np.asarray(fake_quantize(w, bits=1)),
                               np.asarray(b))


def test_activation_quant_model_trains():
    """act_quant_bits (QuantAct analog): quantized activations change the
    forward, training still converges, grads flow (STE)."""
    from deepspeed_tpu.models import build_model, get_config
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    cfg = get_config("tiny")
    m_ref = build_model(cfg)
    m_q = build_model(cfg.replace(act_quant_bits=8))
    params = jax.jit(m_ref.init)(jax.random.PRNGKey(0))
    r = np.random.default_rng(0)
    ids = jnp.asarray(r.integers(0, 256, (2, 16)))
    la = float(m_ref.loss(params, {"input_ids": ids, "labels": ids}))
    lq = float(m_q.loss(params, {"input_ids": ids, "labels": ids}))
    assert abs(la - lq) > 1e-7            # quantization actually bites
    assert abs(la - lq) < 0.5             # ...but int8 stays close
    g = jax.grad(m_q.loss)(params, {"input_ids": ids, "labels": ids})
    assert all(np.all(np.isfinite(x)) for x in jax.tree.leaves(g))


def test_knowledge_distillation_loss():
    """DistilledModel: alpha mixes CE and KD; pure-KD training pulls the
    student toward the teacher's distribution on a fixed batch."""
    from deepspeed_tpu.compression.distillation import (DistilledModel,
                                                        kd_loss,
                                                        make_teacher_provider)
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    student = build_model("tiny")
    teacher = build_model("tiny")
    sp = jax.jit(student.init)(jax.random.PRNGKey(1))
    tp = jax.jit(teacher.init)(jax.random.PRNGKey(2))
    r = np.random.default_rng(0)
    ids = jnp.asarray(r.integers(0, 256, (2, 16)))
    batch = {"input_ids": ids, "labels": ids}

    provider = make_teacher_provider(teacher, tp)
    kbatch = provider(batch)
    assert kbatch["teacher_logits"].shape == (2, 16, 256)

    dm = DistilledModel(student, alpha=0.5, temperature=2.0)
    ce = float(student.loss(sp, batch))
    mixed = float(dm.loss(sp, kbatch))
    kd = float(kd_loss(student.apply(sp, ids), kbatch["teacher_logits"], 2.0))
    np.testing.assert_allclose(mixed, 0.5 * ce + 0.5 * kd, rtol=1e-5)
    # a batch without teacher logits degrades to the plain student loss
    np.testing.assert_allclose(float(dm.loss(sp, batch)), ce, rtol=1e-6)

    # pure KD descends toward the teacher on the fixed batch
    dm1 = DistilledModel(student, alpha=1.0, temperature=1.0)
    loss_g = jax.jit(jax.value_and_grad(dm1.loss))
    p = sp
    k0 = float(dm1.loss(p, kbatch))
    for _ in range(10):
        l, g = loss_g(p, kbatch)
        p = jax.tree.map(lambda a, b: a - 0.5 * b, p, g)
    assert float(dm1.loss(p, kbatch)) < k0


def test_distilled_model_trains_under_engine():
    """The XTC recipe config wraps the student via from_config and trains
    through deepspeed_tpu.initialize with teacher logits in the batch."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.compression.compress import xtc_recipe
    from deepspeed_tpu.compression.distillation import (DistilledModel,
                                                        make_teacher_provider)
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    teacher = build_model("tiny")
    tp = jax.jit(teacher.init)(jax.random.PRNGKey(2))
    recipe = xtc_recipe(keep_number_layer=1, schedule_offset=0)
    student = DistilledModel.from_config(build_model("tiny"), recipe)
    assert isinstance(student, DistilledModel)
    engine, _, _, _ = ds.initialize(model=student, config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2}, "steps_per_print": 10 ** 9})
    provider = make_teacher_provider(teacher, tp)
    r = np.random.default_rng(0)
    ids = r.integers(0, 256, (8, 16))
    batch = provider({"input_ids": ids, "labels": ids})
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    assert losses[-1] < losses[0]


def test_distilled_model_gets_engine_dtype_override():
    """Engine precision overrides must reach the WRAPPED student (setting
    cfg on the wrapper would shadow-attribute and silently change nothing)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.compression.distillation import DistilledModel
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    student = DistilledModel(build_model("tiny"), alpha=0.5)
    engine, _, _, _ = ds.initialize(model=student, config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True}, "steps_per_print": 10 ** 9})
    assert student.student.cfg.dtype == "bfloat16"
    assert "cfg" not in vars(student)   # no shadow attribute on the wrapper


def test_op_builder_prebuild_all():
    """AOT prebuild path (reference DS_BUILD_OPS analog): every registered
    op builds or reports a reasoned skip; nothing raises."""
    from deepspeed_tpu.ops.op_builder import ALL_OPS, build_all
    results = build_all(verbose=False)
    assert set(results) == {cls().name for cls in ALL_OPS.values()}
    assert all(s.startswith(("ok", "skipped")) for s in results.values()), results


def test_row_pruning_masks_trains_and_shrinks(mesh_8dp, rng):
    """Structured row/channel pruning (reference basic_layer.py:166/212):
    init_compression MASKS the low-norm intermediate channels (train stage);
    redundancy_clean physically SLICES them (dim_reduction) — the shrunk
    model's forward equals the masked model's, and the pruned model trains."""
    from deepspeed_tpu.compression.compress import (init_compression,
                                                    redundancy_clean)
    from deepspeed_tpu.models import build_model
    cfg_kw = dict(vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
                  intermediate_size=64, max_seq_len=64, dtype="float32",
                  activation="gelu", tie_embeddings=True)
    from deepspeed_tpu.models.config import TransformerConfig
    model = build_model(TransformerConfig(**cfg_kw))
    params = model.init(rng)
    comp = {"compression_training": {"row_pruning": {
        "shared_parameters": {"enabled": True},
        "different_groups": {"rp1": {"params": {"dense_ratio": 0.5}}}}}}

    masked = init_compression(params, comp)
    wi = np.asarray(masked["layers"]["mlp"]["wi"])
    assert wi.shape == (2, 32, 64)                       # shapes unchanged
    zero_channels = (np.abs(wi).sum(axis=1) == 0).sum(axis=1)
    np.testing.assert_array_equal(zero_channels, [32, 32])   # half masked

    # physical dim reduction picks the SAME channels: forwards agree exactly
    shrunk = redundancy_clean(masked, comp)
    assert shrunk["layers"]["mlp"]["wi"].shape == (2, 32, 32)
    assert shrunk["layers"]["mlp"]["wo"].shape == (2, 32, 32)
    small = build_model(TransformerConfig(**{**cfg_kw, "intermediate_size": 32}))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)))
    out_masked = model.apply(masked, ids)
    out_small = small.apply(shrunk, ids)
    np.testing.assert_allclose(np.asarray(out_masked), np.asarray(out_small),
                               rtol=1e-5, atol=1e-5)

    # the pruned model trains
    import deepspeed_tpu as ds
    engine, _, _, _ = ds.initialize(model=small, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10 ** 9})
    engine.module_params = jax.device_put(shrunk, engine.param_shardings)
    engine._resync_masters_from_params()
    rng2 = np.random.default_rng(1)
    bids = rng2.integers(0, 256, (8, 16))
    losses = [float(engine.train_batch({"input_ids": bids, "labels": bids}))
              for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_rejoin_membership_consensus_skewed_detection(tmp_path):
    """The failure mode the consensus exists for: two survivors detect the
    failure at DIFFERENT times. The early one publishes; the late one must
    adopt the PUBLISHED epoch (not wait on a self-computed future epoch and
    fall back to a divergent local view). Pure-filesystem test, no jax."""
    import threading
    import time as _t
    from deepspeed_tpu.elasticity.rejoin import InProcessElasticWorker

    run_dir = str(tmp_path)
    w0 = InProcessElasticWorker(lambda w: None, "/unused", run_dir,
                                heartbeat_timeout=2.0)
    w1 = InProcessElasticWorker(lambda w: None, "/unused", run_dir,
                                heartbeat_timeout=2.0)
    w0.start(0, 3)
    w1.start(1, 3)           # rank 2 never heartbeats → dead

    res = {}
    t0 = threading.Thread(target=lambda: res.setdefault("w0",
                                                        w0._agree_alive()))
    t0.start()               # rank 0 detects first, publishes membership.1
    _t.sleep(1.5)            # rank 1 detects LATE, after the publish
    res["w1"] = w1._agree_alive()
    t0.join(10)
    assert res["w0"] == res["w1"] == [0, 1]
    assert w0._epoch == w1._epoch == 1       # both consumed the same epoch

    # a second failure event later: epochs advance by scan, not blind count
    with open(os.path.join(run_dir, "heartbeat.1"), "w") as f:
        f.write("0")         # rank 1's heartbeat goes stale epoch-wise
    os.utime(os.path.join(run_dir, "heartbeat.1"), (0, 0))
    w0.rank, w0.world = 0, 2
    alive2 = w0._agree_alive()
    assert alive2 == [0]
    assert w0._epoch == 2


def test_launcher_local_end_to_end(tmp_path):
    """REAL execution of the localhost launch path (not a command-string
    test): dstpu main() → launch.py spawner → 2 worker OS processes, each
    seeing its RANK/LOCAL_RANK/WORLD_SIZE/MASTER_* env (reference
    launcher/launch.py:133 semantics). Also: a failing worker propagates a
    non-zero exit through the whole chain."""
    import textwrap
    from deepspeed_tpu.launcher.runner import main

    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        out = os.path.join(os.environ["OUT_DIR"],
                           f"rank{os.environ['RANK']}.json")
        with open(out, "w") as f:
            json.dump({k: os.environ.get(k) for k in
                       ("RANK", "LOCAL_RANK", "WORLD_SIZE", "NODE_RANK",
                        "MASTER_ADDR", "MASTER_PORT")}, f)
        sys.exit(int(sys.argv[1]) if len(sys.argv) > 1 else 0)
    """))
    os.environ["OUT_DIR"] = str(tmp_path)
    try:
        # EXPORT_ENVS must carry OUT_DIR through the shell hop
        from deepspeed_tpu.launcher import runner as rmod
        rmod.EXPORT_ENVS.append("OUT_DIR")
        rc = main(["--num_gpus", "2", str(script)])
        assert rc == 0
        import json
        got = {}
        for r in (0, 1):
            with open(tmp_path / f"rank{r}.json") as f:
                got[r] = json.load(f)
        assert got[0]["RANK"] == "0" and got[1]["RANK"] == "1"
        assert got[0]["LOCAL_RANK"] == "0" and got[1]["LOCAL_RANK"] == "1"
        assert got[0]["WORLD_SIZE"] == got[1]["WORLD_SIZE"] == "2"
        assert got[0]["MASTER_ADDR"] and got[0]["MASTER_PORT"]
        # failure propagation: worker exit 3 → launcher returns non-zero
        rc_bad = main(["--num_gpus", "2", str(script), "3"])
        assert rc_bad != 0
    finally:
        rmod.EXPORT_ENVS.remove("OUT_DIR")
        os.environ.pop("OUT_DIR", None)
