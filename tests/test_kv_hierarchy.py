"""KV memory hierarchy suite: prefix cache + copy-on-write + host-RAM swap.

Pins the ISSUE-8 acceptance contract:

* greedy outputs are TOKEN-IDENTICAL cache-on vs cache-off — on the FIFO
  path, with mid-stream arrivals hitting a still-live donor's published
  blocks, and with a speculative self-draft sharing the target's block
  tables;
* copy-on-write isolates divergent continuations: a request that extends a
  published prefix mid-block writes a private page copy, and a later exact
  replay of the donor's stream still matches clean content;
* reference counts balance: after retirement + eviction + quarantine the
  only blocks in use are the cache's own (and ``clear()`` returns the pool
  to trash-block-only);
* scheduler preemption with the swap tier swaps committed pages out and
  back in, token-identical to the re-prefill path; crash recovery
  (``serve(resume_from=)`` on a FRESH engine sharing the tier directory)
  restores pages instead of recomputing;
* none of it adds a device→host transfer inside a frame (the shared
  ``frame_transfer_guard`` fixture wraps ``dispatch_frame``);
* under KV pressure cold prefix blocks spill to the tier and restore on a
  later hit;
* a tp=8 sharded engine (virtual CPU mesh) keeps cache-on/cache-off parity
  (``multichip`` marker).

Engines are built per scenario but share shapes, so the frame jit cache
stays within the sanitize retrace budget.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.faults import (FaultInjector,
                                               FrameDispatchError)
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier, PrefixCache
from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                  SchedulerConfig)
from deepspeed_tpu.models import build_model

BS, CHUNK = 16, 8          # block > chunk: mid-block COW hits are reachable


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny")
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=BS, prefill_chunk_size=CHUNK,
              max_tokens_per_step=256, dtype="float32",
              max_ragged_batch_size=4, frame_steps=2,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                          max_seq_len=160)
    e.params = jax.device_put(params)
    return e


RNG = np.random.default_rng(7)
SHARED = RNG.integers(0, 200, (40,)).astype(np.int32)     # 2.5 blocks
TAILS = {u: RNG.integers(0, 200, (6,)).astype(np.int32) for u in range(8)}


def _shared_arrivals(n=6, per_boundary=1):
    """One arrival per boundary, all sharing SHARED + a unique tail — later
    arrivals land while earlier donors are still live (publish-at-boundary,
    not publish-at-retire)."""
    u = 0
    while u < n:
        batch = []
        for _ in range(per_boundary):
            if u < n:
                batch.append((u, np.concatenate([SHARED, TAILS[u]])))
                u += 1
        yield batch


def _clean(e):
    """Pool accounting: live blocks == cache-held blocks (+ trash), and a
    cache clear returns the pool to trash-only."""
    resident = e.prefix_cache.resident_blocks() if e.prefix_cache else 0
    assert e.kv.num_blocks - e.kv.free_blocks == resident + 1
    assert not e.state.seqs
    if e.prefix_cache is not None:
        e.prefix_cache.clear()
        assert e.kv.free_blocks == e.kv.num_blocks - 1


# ---------------------------------------------------------------------------
# allocator + tier units (no model)
# ---------------------------------------------------------------------------


def test_refcounted_allocator_units():
    a = BlockedAllocator(4)
    b = a.allocate(2)
    assert a.free_blocks == 2 and all(a.refcount(x) == 1 for x in b)
    a.share([b[0]])
    assert a.refcount(b[0]) == 2
    a.free(b)                      # drops one ref each; b[0] stays alive
    assert a.free_blocks == 3 and a.refcount(b[0]) == 1
    a.free([b[0]])
    assert a.free_blocks == 4
    with pytest.raises(RuntimeError, match="double-free"):
        a.free([b[0]])
    with pytest.raises(RuntimeError, match="share\\(\\) of free"):
        a.share([b[1]])


def _tiny_pool():
    kv = BlockedKVCache(num_layers=2, kv_heads=2, head_dim=4, num_blocks=8,
                        block_size=4, dtype=jnp.float32)
    kv.reserve_trash_block()
    return kv


def test_swap_tier_roundtrip_across_instances(tmp_path):
    """Pages committed by one tier instance restore from a FRESH instance
    on the same directory (the crash-recovery property: the index and the
    atomic .swp files outlive the process; metadata re-enters the swapper
    via ``adopt``)."""
    kv = _tiny_pool()
    blocks = kv.allocator.allocate(2)
    payload = np.arange(2 * 2 * 2 * 4 * 4, dtype=np.float32).reshape(
        2, 2, 2, 4, 4)
    kv.k = kv.k.at[:, :, blocks].set(payload)
    kv.v = kv.v.at[:, :, blocks].set(payload * 2)
    tier = KVSwapTier(str(tmp_path))
    tier.put_request(7, tokens=8, kv=kv, blocks=blocks)
    assert tier.request_record(7)["tokens"] == 8

    tier2 = KVSwapTier(str(tmp_path))          # fresh process analog
    assert tier2.request_record(7)["blocks"] == 2
    dst = kv.allocator.allocate(2)
    tier2.restore_request(7, kv, dst)
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, dst]), payload)
    np.testing.assert_array_equal(np.asarray(kv.v[:, :, dst]), payload * 2)
    tier2.drop_request(7)
    assert tier2.request_record(7) is None
    assert KVSwapTier(str(tmp_path)).request_record(7) is None


def test_prefix_cache_block_spill_and_restore(tmp_path):
    """A cold unreferenced entry spills its page to the tier (block freed,
    entry stays matchable) and restores bit-identically on the next hit."""
    kv = _tiny_pool()
    tier = KVSwapTier(str(tmp_path))
    pc = PrefixCache(kv, swap=tier)
    blocks = kv.allocator.allocate(1)
    content = np.full((2, 2, 1, 4, 4), 3.5, np.float32)
    kv.k = kv.k.at[:, :, blocks].set(content)
    kv.v = kv.v.at[:, :, blocks].set(-content)
    stream = list(range(4))
    pc.publish(uid=1, stream=stream, blocks=blocks, upto_tokens=4)
    kv.allocator.free(blocks)                  # cache ref is now the only one
    assert pc.reclaim(1) == 1
    assert pc.resident_blocks() == 0 and kv.allocator.free_blocks == 7
    full, partial = pc.match(stream + [9])
    assert len(full) == 1 and full[0].block is None
    assert pc.ensure_resident(full[0])
    nb = full[0].block
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, [nb]]), content)
    np.testing.assert_array_equal(np.asarray(kv.v[:, :, [nb]]), -content)
    pc.clear()
    assert kv.allocator.free_blocks == 7


def test_eviction_hot_small_survives_cold_large():
    """Victim scoring beyond LRU (ISSUE-12 satellite): under pressure a
    HOT small prefix (frequent hits, one block) outlives a COLD large one
    (many blocks, zero hits) even when the cold chain was touched more
    RECENTLY — hit frequency outranks recency, and among equally-cold
    entries the larger subtree goes first. LRU stays the tie-break."""
    kv = _tiny_pool()
    pc = PrefixCache(kv)
    bs = kv.block_size
    hot_stream = list(range(bs))
    hot_blocks = kv.allocator.allocate(1)
    pc.publish(uid=1, stream=hot_stream, blocks=hot_blocks,
               upto_tokens=bs)
    kv.allocator.free(hot_blocks)
    cold_stream = [100 + t for t in range(3 * bs)]
    cold_blocks = kv.allocator.allocate(3)
    pc.publish(uid=2, stream=cold_stream, blocks=cold_blocks,
               upto_tokens=3 * bs)
    kv.allocator.free(cold_blocks)
    # the hot prefix is HIT repeatedly (earlier than the cold touch, so
    # pure LRU would evict it first)...
    for _ in range(3):
        full, _ = pc.match(hot_stream + [9])
        pc.touch(full, bs)
    # ...then the cold chain is matched once but never counted as a hit
    # (touch with hit_tokens=0 stamps recency only)
    full_cold, _ = pc.match(cold_stream + [9])
    assert len(full_cold) == 3
    now = pc._tick()
    for e in full_cold:
        e.last_used = now            # most recent — LRU would keep these
    freed = pc.reclaim(3)
    assert freed == 3
    hot_entry, _ = pc.match(hot_stream + [9])
    assert len(hot_entry) == 1 and hot_entry[0].block is not None, \
        "the hot small prefix must survive the cold large one"
    assert pc.match(cold_stream + [9])[0] == [], "the cold chain is gone"
    pc.clear()
    assert kv.allocator.free_blocks == 7


def test_batched_pressure_spill_io_counts(tmp_path, monkeypatch):
    """``reclaim`` spills N cold blocks as ONE batch: one device gather
    per pool (``read_pages`` on the whole block list), all page writes
    committed by a single swapper ``wait``, and one index rewrite — the
    per-block path paid each of those N times (ROADMAP item 3(a))."""
    kv = _tiny_pool()
    tier = KVSwapTier(str(tmp_path))
    pc = PrefixCache(kv, swap=tier)
    n = 3
    blocks = kv.allocator.allocate(n)
    content = np.arange(2 * 2 * n * 4 * 4, dtype=np.float32).reshape(
        2, 2, n, 4, 4)
    kv.k = kv.k.at[:, :, blocks].set(content)
    kv.v = kv.v.at[:, :, blocks].set(-content)
    stream = list(range(4 * n))
    pc.publish(uid=1, stream=stream, blocks=blocks, upto_tokens=4 * n)
    kv.allocator.free(blocks)          # the cache refs are now the only ones
    counts = {"gather": 0, "wait": 0, "index": 0}
    orig_read = type(kv).read_pages
    monkeypatch.setattr(type(kv), "read_pages",
                        lambda self, ids: (counts.__setitem__(
                            "gather", counts["gather"] + 1),
                            orig_read(self, ids))[1])
    orig_wait = tier.swapper.wait
    monkeypatch.setattr(tier.swapper, "wait",
                        lambda: (counts.__setitem__(
                            "wait", counts["wait"] + 1), orig_wait())[1])
    orig_save = tier._save_index
    monkeypatch.setattr(tier, "_save_index",
                        lambda: (counts.__setitem__(
                            "index", counts["index"] + 1), orig_save())[1])
    assert pc.reclaim(n) == n
    assert counts == {"gather": 1, "wait": 1, "index": 1}, counts
    assert pc.resident_blocks() == 0
    assert tier.stats["blocks_out"] == n
    assert pc.stats["swapped_out"] == n
    # the spilled entries stay matchable and restore bit-identically
    full, _ = pc.match(stream + [99])
    assert len(full) == n and all(e.block is None for e in full)
    assert all(pc.ensure_resident(e, protect={x.eid for x in full})
               for e in full)
    order = [e.block for e in full]
    np.testing.assert_array_equal(np.asarray(kv.k[:, :, order]), content)
    np.testing.assert_array_equal(np.asarray(kv.v[:, :, order]), -content)
    pc.clear()
    assert kv.allocator.free_blocks == 7


# ---------------------------------------------------------------------------
# serving parity: prefix cache on vs off
# ---------------------------------------------------------------------------


def test_prefix_hit_token_parity_fifo(tiny_model_params):
    model, params = tiny_model_params
    e_off = _engine(model, params)
    base = dict(e_off.serve(_shared_arrivals(), max_new_tokens=8))
    e_on = _engine(model, params, prefix_cache=True)
    outs = dict(e_on.serve(_shared_arrivals(), max_new_tokens=8))
    assert set(outs) == set(base)
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u],
                                      err_msg=f"uid={u} diverged cache-on")
    c = e_on.telemetry.counters
    # mid-stream arrivals hit blocks published by STILL-LIVE donors
    assert c["prefix_hits"] >= 4
    assert c["prefix_hit_tokens"] >= 4 * 32
    assert c["prefix_blocks_published"] > 0
    # the TTFT lever, measured without a wall clock: cached prefixes are
    # not re-prefilled, so the cache-on run consumes far fewer prompt
    # tokens in-frame
    assert c["prefill_tokens"] < e_off.telemetry.counters["prefill_tokens"]
    assert e_on.telemetry.gauges["prefix_hit_rate"] >= 0.5
    _clean(e_on)


def test_cow_isolation_under_divergent_continuations(tiny_model_params):
    """B extends A's stream mid-block (COW copy), C diverges mid-block with
    different content, then D replays A's exact stream — D must still match
    the ORIGINAL published pages (COW never mutates shared content)."""
    model, params = tiny_model_params
    a_prompt = np.concatenate([SHARED, TAILS[0]])      # 46 tokens

    def mk_arrivals(a_gen):
        # B: A's prompt + A's first generated tokens (mid-block extension)
        b = np.concatenate([a_prompt, a_gen[:4]])
        # C: same length, divergent continuation after SHARED
        c = np.concatenate([a_prompt, (a_gen[:4] + 1) % 200])
        # D: exact replay of A's prompt
        return [[(0, a_prompt)], [], [], [(1, b)], [(2, c)], [], [(3, a_prompt)]]

    e_off = _engine(model, params)
    a_gen = dict(e_off.serve([[ (0, a_prompt) ]], max_new_tokens=8))[0]
    base = dict(e_off.serve(mk_arrivals(a_gen), max_new_tokens=8))
    e_on = _engine(model, params, prefix_cache=True)
    # warm the cache so B/C/D arrive against published blocks
    outs = dict(e_on.serve(mk_arrivals(a_gen), max_new_tokens=8))
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u],
                                      err_msg=f"uid={u} diverged under COW")
    assert e_on.telemetry.counters["prefix_cow_copies"] >= 1
    _clean(e_on)


def test_spec_draft_prefix_parity(tiny_model_params):
    """Self-draft speculative serving: the draft's paged pools index the
    target's block tables, so mapped prefix blocks carry draft KV too —
    greedy outputs stay token-identical cache-on vs cache-off."""
    model, params = tiny_model_params
    e_off = _engine(model, params, speculate_gamma=2)
    e_off.attach_draft(model, params)
    base = dict(e_off.serve(_shared_arrivals(4), max_new_tokens=12))
    e_on = _engine(model, params, speculate_gamma=2, prefix_cache=True)
    e_on.attach_draft(model, params)
    outs = dict(e_on.serve(_shared_arrivals(4), max_new_tokens=12))
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u],
                                      err_msg=f"uid={u} diverged (spec)")
    assert e_on.telemetry.counters["prefix_hits"] >= 2
    _clean(e_on)


def test_refcount_accounting_after_retire_evict_quarantine(tiny_model_params):
    """Retirement + deadline eviction + poison quarantine on a cache-on
    engine: every non-cache reference unwinds, quarantine invalidates the
    poisoned row's published entries, and clear() drains the pool."""
    model, params = tiny_model_params
    e = _engine(model, params, prefix_cache=True)
    inj = FaultInjector([{"kind": "poison_row", "frame": 4, "uid": 1}])

    def arrivals():
        yield [(0, np.concatenate([SHARED, TAILS[0]]))]
        yield [(1, np.concatenate([SHARED, TAILS[1]]))]
        yield [{"uid": 2, "tokens": np.concatenate([SHARED, TAILS[2]]),
                "deadline_ms": 0.0001}]      # expires at the next boundary
        for _ in range(4):
            yield []

    outs = dict(e.serve(arrivals(), max_new_tokens=8, faults=inj))
    assert 0 in outs and 1 not in outs and 2 not in outs
    kinds = {f.kind for f in e.fault_log}
    assert {"poison_row", "deadline_expired"} <= kinds
    # uid 1's published entries were invalidated by the quarantine
    assert all(ent.source_uid != 1
               for ent in e.prefix_cache._by_id.values())
    _clean(e)


# ---------------------------------------------------------------------------
# swap tier: preemption + crash recovery
# ---------------------------------------------------------------------------


PREEMPT_PROMPTS = {u: RNG.integers(0, 200, (24,)).astype(np.int32)
                   for u in range(3)}


def _preempt_arrivals():
    yield [{"uid": 0, "tokens": PREEMPT_PROMPTS[0], "priority": "best_effort"},
           {"uid": 1, "tokens": PREEMPT_PROMPTS[1], "priority": "best_effort"}]
    yield []
    yield []
    yield [{"uid": 2, "tokens": PREEMPT_PROMPTS[2],
            "priority": "interactive"}]


def _preempt_run(e):
    sched = RequestScheduler(SchedulerConfig())
    outs = dict(e.serve(_preempt_arrivals(), max_new_tokens=16,
                        frame_slots=2, scheduler=sched))
    return sched, outs


def test_preemption_swap_in_parity(tiny_model_params, tmp_path):
    """A preempted victim re-admitted via swap-in emits exactly the tokens
    the re-prefill path emits — and the tier actually carried the pages."""
    model, params = tiny_model_params
    e_base = _engine(model, params, max_ragged_batch_size=2)
    s_base, base = _preempt_run(e_base)
    assert s_base.summary["preempted"] >= 1      # scenario sanity
    e_swap = _engine(model, params, max_ragged_batch_size=2,
                     kv_swap_dir=str(tmp_path))
    s_swap, outs = _preempt_run(e_swap)
    assert s_swap.summary["preempted"] >= 1
    c = e_swap.telemetry.counters
    assert c["kv_swap_out_requests"] >= 1 and c["kv_swap_in_requests"] >= 1
    assert c["kv_swap_out_blocks"] == c["kv_swap_in_blocks"] > 0
    for u in base:
        np.testing.assert_array_equal(
            base[u], outs[u], err_msg=f"uid={u} diverged via swap-in")
    assert e_swap.kv.free_blocks == e_swap.kv.num_blocks - 1
    assert not e_swap.kv_swap._index["requests"]     # records all consumed


def test_resume_restores_pages_parity(tiny_model_params, tmp_path):
    """Crash AFTER a preemption swapped a victim's pages out: a FRESH
    engine sharing the tier directory resumes by restoring the pages
    (kv_swap_resume_restores fires) and the combined outputs match the
    crash-free baseline token for token."""
    model, params = tiny_model_params
    e_base = _engine(model, params, max_ragged_batch_size=2)
    _, base = _preempt_run(e_base)

    e1 = _engine(model, params, max_ragged_batch_size=2,
                 kv_swap_dir=str(tmp_path))
    fatal = FaultInjector([{"kind": "dispatch_exception", "frame": 4,
                            "times": 100}])
    got = {}
    with pytest.raises(FrameDispatchError):
        for uid, toks in e1.serve(_preempt_arrivals(), max_new_tokens=16,
                                  frame_slots=2,
                                  scheduler=RequestScheduler(SchedulerConfig()),
                                  faults=fatal):
            got[uid] = toks
    snap = e1.last_crash_snapshot
    assert e1.telemetry.counters["kv_swap_out_requests"] >= 1
    swapped = [r for r in snap["requests"] if r["swapped_tokens"]]
    assert swapped, "snapshot should surface the swapped victim"

    e2 = _engine(model, params, max_ragged_batch_size=2,
                 kv_swap_dir=str(tmp_path))
    got.update(e2.serve(iter([[]]), max_new_tokens=16, frame_slots=2,
                        scheduler=RequestScheduler(SchedulerConfig()),
                        resume_from=snap))
    for u in base:
        np.testing.assert_array_equal(
            base[u], got[u], err_msg=f"uid={u} diverged across restart")
    assert e2.telemetry.counters["kv_swap_resume_restores"] >= 1
    assert e2.kv.free_blocks == e2.kv.num_blocks - 1


def test_stale_swap_record_rejected_on_uid_reuse(tiny_model_params,
                                                 tmp_path):
    """A swap record keyed by a reused uid must NOT restore: the content
    fingerprint mismatches, the record is dropped, and the request cold-
    prefills to the same tokens as a swap-free engine."""
    from deepspeed_tpu.inference.v2.kv_hierarchy import token_fingerprint
    model, params = tiny_model_params
    p = np.concatenate([SHARED, TAILS[0]])
    base = dict(_engine(model, params).serve([[(5, p)]], max_new_tokens=8))
    e = _engine(model, params, kv_swap_dir=str(tmp_path))
    # plant a stale record for uid 5 under DIFFERENT content
    junk = RNG.integers(0, 200, (46,)).astype(np.int32)
    blocks = e.kv.allocator.allocate(2)
    e.kv_swap.put_request(5, tokens=30, kv=e.kv, blocks=blocks,
                          fingerprint=token_fingerprint(junk[:30]))
    e.kv.allocator.free(blocks)
    outs = dict(e.serve([[(5, p)]], max_new_tokens=8))
    np.testing.assert_array_equal(base[5], outs[5])
    assert e.telemetry.counters["kv_swap_in_requests"] == 0
    assert e.kv_swap.request_record(5) is None      # stale record dropped


def test_no_inframe_transfers_with_hierarchy(tiny_model_params, tmp_path,
                                             frame_transfer_guard):
    """COW copies, publishes, swap-outs and swap-ins are all frame-BOUNDARY
    work: the in-frame transfer guard stays green through a schedule that
    exercises hits, preemption swap, and re-admission."""
    model, params = tiny_model_params
    e = _engine(model, params, max_ragged_batch_size=2, prefix_cache=True,
                kv_swap_dir=str(tmp_path))
    sched = RequestScheduler(SchedulerConfig())
    outs = dict(e.serve(_preempt_arrivals(), max_new_tokens=16,
                        frame_slots=2, scheduler=sched))
    assert len(outs) == 3
    e.prefix_cache.clear()


def test_spill_under_pressure_then_restore(tiny_model_params, tmp_path):
    """With a pool too small to hold the cache AND new work, admission
    reclaims cold prefix blocks by SPILLING them to the tier (not
    shedding); a later shared-prefix arrival restores the spilled pages
    and still matches the cache-off outputs."""
    model, params = tiny_model_params
    # pool sized so uid 1's reservation forces a spill of uid 0's cache
    kw = dict(max_ragged_batch_size=1, num_kv_blocks=7,
              prefix_cache=True, kv_swap_dir=str(tmp_path))
    a = np.concatenate([SHARED, TAILS[0]])
    b = RNG.integers(0, 200, (46,)).astype(np.int32)     # no shared prefix

    def arrivals():
        for u, p in ((0, a), (1, b), (2, a)):
            yield [(u, p)]

    e_off = _engine(model, params, max_ragged_batch_size=1, num_kv_blocks=7)
    base = dict(e_off.serve(arrivals(), max_new_tokens=8))
    e = _engine(model, params, **kw)
    outs = dict(e.serve(arrivals(), max_new_tokens=8))
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u])
    c = e.telemetry.counters
    assert c["prefix_blocks_swapped_out"] >= 1
    assert c["prefix_blocks_swapped_in"] >= 1
    assert c["prefix_hits"] >= 1
    _clean(e)


def test_deferred_hit_resumes_at_watermark(tiny_model_params):
    """A prefix-hit admission whose REMAINDER reservation defers must keep
    its mapped shared blocks AND its admission watermark across the retry:
    resuming prefill from 0 would write into the published (read-only)
    pages. Pool sized so the hit request defers behind a live hog, then
    admits after it retires — outputs must match the cache-off run and the
    donor's published content must stay clean (a later replay matches)."""
    model, params = tiny_model_params
    a = np.concatenate([SHARED, TAILS[0]])               # 46 tokens
    hog = RNG.integers(0, 200, (46,)).astype(np.int32)   # no shared prefix
    c = np.concatenate([SHARED, TAILS[1]])

    def arrivals():
        yield [(0, a, 8)]           # donor: publishes SHARED's blocks
        yield [(1, hog, 24)]        # hog: holds most of the pool
        for _ in range(8):
            yield []
        yield [(2, c, 24)]          # hit arrives; remainder can't reserve
        for _ in range(2):
            yield []
        yield [(3, a, 8)]           # donor replay: published pages clean

    kw = dict(max_ragged_batch_size=2, num_kv_blocks=10)
    e_off = _engine(model, params, **kw)
    base = dict(e_off.serve(arrivals(), max_new_tokens=8))
    e = _engine(model, params, prefix_cache=True, **kw)
    outs = dict(e.serve(arrivals(), max_new_tokens=8))
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u],
                                      err_msg=f"uid={u} diverged")
    tel = e.telemetry.counters
    assert tel["prefix_hits"] >= 2                # uid 2 and the replay
    assert tel["admission_deferrals"] >= 1        # uid 2 actually waited
    _clean(e)


def test_prefix_cache_max_blocks_cap(tiny_model_params):
    model, params = tiny_model_params
    e = _engine(model, params, prefix_cache=True, prefix_cache_max_blocks=2)
    outs = dict(e.serve(_shared_arrivals(4), max_new_tokens=8))
    assert len(outs) == 4
    assert e.prefix_cache.resident_blocks() <= 2
    _clean(e)


# ---------------------------------------------------------------------------
# tensor parallel: the hierarchy is topology-blind
# ---------------------------------------------------------------------------


@pytest.mark.multichip
def test_tp8_prefix_parity():
    """Block tables carry block IDS, so the prefix cache works unchanged on
    an 8-way head-sharded engine: tp=8 cache-on output token-identical to
    tp=8 cache-off."""
    model = build_model("tiny", num_heads=8)
    params = model.init(jax.random.PRNGKey(0))

    def mk(prefix):
        cfg = RaggedInferenceEngineConfig(
            kv_block_size=BS, prefill_chunk_size=CHUNK, dtype="float32",
            max_ragged_batch_size=4, frame_steps=2, tp=8,
            prefix_cache=prefix)
        return InferenceEngineV2(model, cfg, params=params, max_seq_len=160)

    base = dict(mk(False).serve(_shared_arrivals(3), max_new_tokens=8))
    e = mk(True)
    outs = dict(e.serve(_shared_arrivals(3), max_new_tokens=8))
    for u in base:
        np.testing.assert_array_equal(base[u], outs[u],
                                      err_msg=f"uid={u} diverged under tp=8")
    assert e.telemetry.counters["prefix_hits"] >= 1
    _clean(e)
