"""Engine integration tests (reference pattern: tests/unit/runtime/test_ds_initialize.py,
tests/unit/runtime/zero/test_zero.py — ZeRO stages must be numerically
equivalent to plain DP)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.utils import groups


def _base_config(stage=0, **over):
    cfg = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }
    cfg.update(over)
    return cfg


def _make_batch(seed=0, bs=16, seq=32, vocab=256):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (bs, seq))
    return {"input_ids": ids, "labels": ids}


def _train(stage, steps=4, preset="tiny"):
    groups.reset_mesh()
    model = build_model(preset)
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(stage))
    losses = [float(engine.train_batch(_make_batch(seed=i))) for i in range(steps)]
    return losses, engine


def test_train_loss_decreases_on_memorization(mesh_8dp):
    """Repeating one batch must drive loss down (training is real)."""
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(0))
    batch = _make_batch(seed=42)
    losses = [float(engine.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stages_match_dp(stage):
    """ZeRO sharding must not change numerics vs stage 0 (pure DP)."""
    ref, _ = _train(0)
    got, engine = _train(stage)
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
    # params actually sharded at stage 3
    if stage == 3:
        tok = engine.module_params["embed"]["tok"]
        assert not tok.sharding.is_fully_replicated


def test_opt_state_sharded_stage1():
    _, engine = _train(1, steps=1)
    slot = engine.opt_state["slots"]["embed"]["tok"]["m"]
    assert not slot.sharding.is_fully_replicated
    # params stay replicated at stage 1
    assert engine.module_params["embed"]["tok"].sharding.is_fully_replicated


def test_forward_backward_step_equals_train_batch():
    """Decomposed API must produce the same update as the fused path."""
    ref_losses, ref_engine = _train(0, steps=2)

    groups.reset_mesh()
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(0))
    for i in range(2):
        full = _make_batch(seed=i)
        gas, mb = 2, 8  # 16 = gas * (1 micro/gpu * 8 devices)
        for g in range(gas):
            sl = {k: v[g * mb:(g + 1) * mb] for k, v in full.items()}
            loss = engine.forward(sl)
            engine.backward(loss)
            engine.step()
    ref_tok = np.asarray(ref_engine.module_params["embed"]["tok"])
    got_tok = np.asarray(engine.module_params["embed"]["tok"])
    np.testing.assert_allclose(ref_tok, got_tok, rtol=1e-4, atol=1e-5)


def test_fp16_overflow_skips_step():
    groups.reset_mesh()
    model = build_model("tiny")
    cfg = _base_config(0, fp16={"enabled": True, "initial_scale_power": 4, "hysteresis": 1})
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    p_before = np.asarray(engine.module_params["embed"]["tok"]).copy()
    # poison gradients through a huge loss-scale overflow: feed inf-producing batch
    # by injecting inf grads directly via the update fn contract
    inf_grads = jax.tree.map(lambda p: jnp.full(p.shape, jnp.inf, jnp.float32),
                             engine.module_params)
    engine._acc_grads = inf_grads
    engine._acc_count = 1
    engine.micro_steps = engine.gradient_accumulation_steps() - 0  # at boundary
    engine.step()
    p_after = np.asarray(engine.module_params["embed"]["tok"])
    np.testing.assert_array_equal(p_before, p_after)
    assert float(engine.scaler_state.scale) < 2 ** 4  # backed off


def test_checkpoint_roundtrip(tmp_path):
    losses, engine = _train(2, steps=2)
    engine.save_checkpoint(str(tmp_path), tag="t1")
    before = np.asarray(engine.module_params["embed"]["tok"]).copy()
    step_before = engine.global_steps

    # train further, then restore
    engine.train_batch(_make_batch(seed=99))
    assert not np.allclose(before, np.asarray(engine.module_params["embed"]["tok"]))
    engine.load_checkpoint(str(tmp_path), tag="t1")
    np.testing.assert_array_equal(before, np.asarray(engine.module_params["embed"]["tok"]))
    assert engine.global_steps == step_before


def test_checkpoint_latest_file(tmp_path):
    _, engine = _train(0, steps=1)
    engine.save_checkpoint(str(tmp_path))
    import os
    assert os.path.isfile(os.path.join(str(tmp_path), "latest"))
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path is not None


def test_lr_schedule_integration():
    groups.reset_mesh()
    model = build_model("tiny")
    cfg = _base_config(0)
    cfg["scheduler"] = {"type": "WarmupLR", "params": {"warmup_num_steps": 10,
                                                       "warmup_max_lr": 1e-3,
                                                       "warmup_type": "linear"}}
    engine, _, _, sched = ds.initialize(model=model, config=cfg)
    engine.train_batch(_make_batch())
    lr1 = engine.get_lr()[0]
    engine.train_batch(_make_batch())
    lr2 = engine.get_lr()[0]
    assert lr2 > lr1  # warming up


def test_tensor_parallel_forward(mesh_2x4):
    """TP=4: params sharded over tensor axis, loss still finite & correct shape."""
    model = build_model("tiny")
    config = _base_config(0)
    config["train_batch_size"] = 4
    config["train_micro_batch_size_per_gpu"] = 1
    config["gradient_accumulation_steps"] = 2
    engine, _, _, _ = ds.initialize(model=model, config=config)
    wq = engine.module_params["layers"]["attn"]["wq"]
    assert not wq.sharding.is_fully_replicated  # heads dim sharded over tensor
    loss = engine.train_batch(_make_batch(bs=4))
    assert np.isfinite(float(loss))


def test_moe_training(mesh_8dp):
    model = build_model("tiny-moe")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(1))
    batch = _make_batch(seed=3)
    losses = [float(engine.train_batch(batch)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_check_sharded_equivalence_guard():
    """Debug correctness guard (SURVEY §5): sharded step == replicated step,
    and the guard actually fails when fed a corrupted comparison."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=4, tensor=2))
    model = build_model("tiny")
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3}, "steps_per_print": 10 ** 9}
    engine, _, _, _ = ds.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    mx, _ = engine.check_sharded_equivalence({"input_ids": ids, "labels": ids})
    assert mx < 1e-4


def test_stage3_param_persistence_threshold():
    """stage3_param_persistence_threshold keeps small leaves replicated
    (persisted) while large ones stay FSDP-sharded, and training still
    matches plain DP."""
    def run(thr):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=8))
        model = build_model("tiny")
        zo = {"stage": 3}
        if thr:
            zo["stage3_param_persistence_threshold"] = thr
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_batch_size": 16,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": zo, "steps_per_print": 10 ** 9})
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(3):
            ids = rng.integers(0, 256, (16, 32))
            losses.append(float(engine.train_batch({"input_ids": ids, "labels": ids})))
        return losses, engine

    ref, _ = run(0)
    # threshold above the norm-scale size (64) but below the attention mats
    got, eng = run(1000)
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)
    norm_scale = eng.module_params["final_norm"]["scale"]
    wq = eng.module_params["layers"]["attn"]["wq"]
    assert norm_scale.sharding.is_fully_replicated          # persisted
    assert not wq.sharding.is_fully_replicated              # still sharded


def test_tiled_linear():
    """TiledLinear (reference runtime/zero/tiling.py:32): tile-sequenced
    matmul equals the dense projection; out splits can stay uncombined."""
    from deepspeed_tpu.runtime.zero.tiling import (TiledLinear,
                                                   tiled_linear_apply,
                                                   tiled_linear_init)
    rng = jax.random.PRNGKey(0)
    p = tiled_linear_init(rng, 16, 24, in_splits=2, out_splits=3)
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 16))
    y = np.asarray(tiled_linear_apply(p, x))
    w = np.asarray(p["w"], np.float32)
    W = np.concatenate([np.concatenate([w[i, o] for o in range(3)], axis=1)
                        for i in range(2)], axis=0)
    ref = np.asarray(x) @ W + np.asarray(p["b"])
    np.testing.assert_allclose(y, ref, rtol=1e-2, atol=2e-3)  # device matmul precision
    outs = tiled_linear_apply(p, x, combine_out_splits=False)
    assert len(outs) == 3 and outs[0].shape == (5, 8)
    tl = TiledLinear(16, 24, in_splits=2, out_splits=3)
    np.testing.assert_allclose(np.asarray(tl(p, x)), y)
    with pytest.raises(ValueError):
        tiled_linear_init(rng, 15, 24, in_splits=2)


def test_bert_mlm_training_zero2(mesh_8dp):
    """Acceptance config 2 analog (BASELINE.md): a BERT-style post-norm
    encoder trains under ZeRO-2 through deepspeed_tpu.initialize — MLM loss
    decreases, params/opt state take the stage-2 shardings."""
    groups.reset_mesh()
    model = build_model("bert-base", num_layers=2, hidden_size=64, num_heads=4,
                        intermediate_size=128, vocab_size=256, max_seq_len=32,
                        dtype="float32", param_dtype="float32")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(2))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (16, 32))
    labels = np.full_like(ids, -100)
    mask_pos = rng.random(ids.shape) < 0.3
    labels[mask_pos] = ids[mask_pos]
    masked = ids.copy()
    masked[mask_pos] = 1   # [MASK]-style corruption
    batch = {"input_ids": masked, "labels": labels}
    losses = [float(engine.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0] - 0.5 and all(np.isfinite(losses)), losses


def test_engine_api_parity_setters(mesh_8dp, tmp_path):
    """Reference engine surface: set_lr, dynamic batch sizing (only GAS
    moves for set_train_batch_size), zero_grad no-op, module state dict
    round-trip, save_16bit_model torch export."""
    import torch
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_base_config(1))
    dp = groups.get_data_parallel_world_size()
    mbs = engine.train_micro_batch_size_per_gpu()

    engine.set_lr(5e-4)
    assert engine.get_lr() == [5e-4]

    engine.set_train_batch_size(mbs * dp * 4)
    assert engine.gradient_accumulation_steps() == 4
    with pytest.raises(ValueError):
        engine.set_train_batch_size(mbs * dp * 4 + 1)
    engine.set_gradient_accumulation_steps(2)
    assert engine.train_batch_size() == mbs * dp * 2

    engine.zero_grad()   # API parity no-op

    sd = engine.module_state_dict()
    engine.load_module_state_dict(sd)
    with pytest.raises(ValueError):
        engine.load_module_state_dict({"nope": sd})

    path = engine.save_16bit_model(str(tmp_path))
    flat = torch.load(path, weights_only=True)
    assert "embed.tok" in flat
    got = float(flat["embed.tok"].float().sum())
    want = float(np.asarray(sd["embed"]["tok"], np.float32).sum())
    np.testing.assert_allclose(got, want, rtol=1e-2)

    # training still works after the dynamic resizes
    ids = np.random.default_rng(0).integers(0, 256, (mbs * dp * 2, 32))
    loss = float(engine.train_batch({"input_ids": ids, "labels": ids}))
    assert np.isfinite(loss)


def test_load_module_state_dict_resyncs_masters(mesh_8dp):
    """Weights loaded via load_module_state_dict must SURVIVE the next
    optimizer step under ZeRO-Offload (host fp32 masters) — without the
    master resync, the next step reverts to stale masters."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    cfg = _base_config(1)
    cfg["zero_optimization"]["offload_optimizer"] = {"device": "cpu", "native": True}
    cfg["train_micro_batch_size_per_gpu"] = 2
    cfg["gradient_accumulation_steps"] = 1
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
    batch = _make_batch(seed=0)
    for _ in range(2):
        engine.train_batch(batch)

    sd = jax.tree.map(lambda x: np.zeros_like(np.asarray(x)),
                      engine.module_state_dict())
    engine.load_module_state_dict(sd)
    engine.train_batch(batch)
    tok = np.asarray(engine.module_params["embed"]["tok"], np.float32)
    # one Adam step away from zeros (|update| <= ~lr), not back at the
    # pre-load weights (normal(0.02) init would give values ~30x lr)
    assert np.abs(tok).max() < 5e-3, np.abs(tok).max()


def test_partitioned_activations_parity_and_memory():
    """activation_checkpointing.partition_activations shards the saved
    checkpoint-boundary residuals' sequence dim over the tensor axis
    (reference checkpointing.py:486): the loss trajectory is unchanged and
    the compiled step's temp allocation shrinks."""
    import jax.numpy as jnp

    def run(partition):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(data=4, tensor=2))
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "activation_checkpointing": {"policy": "dots",
                                         "partition_activations": partition},
            "steps_per_print": 10 ** 9, "seed": 3,
        }
        engine, _, _, _ = ds.initialize(model=build_model("tiny"), config=cfg)
        assert engine.model.cfg.partition_activations == partition
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(3):
            ids = rng.integers(0, 256, (8, 64))
            losses.append(float(engine.train_batch({"input_ids": ids,
                                                    "labels": ids})))
        # compiled-memory probe on the same mesh/model: saved residuals are
        # the dominant temp of a remat'd loss+grad step
        model = engine.model
        params = engine.module_params

        def loss_grad(p, ids):
            return jax.grad(lambda q: model.loss(q, {"input_ids": ids,
                                                     "labels": ids}))(p)

        ids = jnp.asarray(rng.integers(0, 256, (8, 64)))
        mem = jax.jit(loss_grad).lower(params, ids).compile().memory_analysis()
        return losses, int(getattr(mem, "temp_size_in_bytes", -1))

    losses_off, temp_off = run(False)
    losses_on, temp_on = run(True)
    np.testing.assert_allclose(losses_off, losses_on, rtol=2e-4, atol=2e-4)
    assert 0 < temp_on < temp_off, (temp_on, temp_off)


def test_cpu_checkpointing_maps_to_offload_policy():
    """activation_checkpointing.cpu_checkpointing routes the remat policy to
    dots_offload (saved matmul outputs parked in host memory)."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(data=8))
    engine, _, _, _ = ds.initialize(model=build_model("tiny"), config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "activation_checkpointing": {"policy": "dots",
                                     "cpu_checkpointing": True},
        "steps_per_print": 10 ** 9})
    assert engine.model.cfg.remat == "dots_offload"
