"""v2 module system + model implementation tests (reference pattern:
tests/unit/inference/v2/{modules,model_implementations})."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from deepspeed_tpu.inference.v2.modules import (ConfigBundle, DSLinearConfig,
                                                DSMoEConfig, DSNormConfig,
                                                DSUnembedConfig, available,
                                                instantiate, OP_LINEAR, OP_MOE,
                                                OP_PRE_NORM, OP_POST_NORM,
                                                OP_UNEMBED)
from deepspeed_tpu.inference.v2.model_implementations import (build_native,
                                                              resolve_container)


def test_registry_lists_defaults():
    avail = available()
    assert "paged_flash" in avail["attention"]
    assert "fused_norm" in avail["pre_norm"]
    assert "blas_fp" in avail["linear"]
    assert "ragged_moe" in avail["moe"]
    assert "logits_gather" in avail["unembed"]
    with pytest.raises(KeyError):
        instantiate(OP_LINEAR, ConfigBundle("nope", DSLinearConfig()))


def test_norm_and_linear_modules():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8)), jnp.float32)
    pre = instantiate(OP_PRE_NORM, ConfigBundle(
        "fused_norm", DSNormConfig(hidden_size=8, type="rmsnorm", eps=1e-6)))
    y = pre({"scale": jnp.ones((8,))}, x)
    np.testing.assert_allclose(np.mean(np.square(np.asarray(y)), -1), 1.0, rtol=1e-3)

    post = instantiate(OP_POST_NORM, ConfigBundle(
        "fused_norm", DSNormConfig(hidden_size=8, type="layernorm", eps=1e-6)))
    z = post({"scale": jnp.ones((8,)), "bias": jnp.zeros((8,))}, x, x)
    np.testing.assert_allclose(np.asarray(z).mean(-1), 0.0, atol=1e-5)

    lin = instantiate(OP_LINEAR, ConfigBundle(
        "blas_fp", DSLinearConfig(in_features=8, out_features=4, bias=True,
                                  activation="relu", dtype=jnp.float32)))
    w = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
    out = lin({"w": w, "b": jnp.zeros((4,))}, x)
    np.testing.assert_allclose(np.asarray(out), np.maximum(np.asarray(x) @ np.asarray(w), 0),
                               rtol=1e-5)

    gated = instantiate(OP_LINEAR, ConfigBundle(
        "blas_fp", DSLinearConfig(in_features=8, out_features=4,
                                  activation="swiglu", dtype=jnp.float32)))
    out = gated({"w_gate": w, "w_up": w}, x)
    assert out.shape == (2, 4)


def test_unembed_last_token_only():
    cfg = DSUnembedConfig(vocab_size=16, hidden_size=8,
                          norm=DSNormConfig(hidden_size=8, type="rmsnorm"),
                          tie_embeddings=True, dtype=jnp.float32)
    mod = instantiate(OP_UNEMBED, ConfigBundle("logits_gather", cfg))
    rng = np.random.default_rng(1)
    params = {"final_norm": {"scale": jnp.ones((8,))},
              "embed": {"tok": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)}}
    logits = mod(params, jnp.asarray(rng.normal(size=(3, 8)), jnp.float32))
    assert logits.shape == (3, 16) and logits.dtype == jnp.float32


def test_moe_module_matches_model_layer():
    from deepspeed_tpu.models import layers as L
    from deepspeed_tpu.models.config import TransformerConfig
    mcfg = TransformerConfig(vocab_size=1, hidden_size=16, num_layers=1, num_heads=1,
                             intermediate_size=32, max_seq_len=8, num_experts=4,
                             num_experts_per_tok=2, moe_impl="grouped", dtype="float32")
    pr, _ = L.init_moe_mlp(jax.random.PRNGKey(0), mcfg)
    mod = instantiate(OP_MOE, ConfigBundle("ragged_moe", DSMoEConfig(
        num_experts=4, top_k=2, hidden_size=16, intermediate_size=32,
        impl="grouped", dtype=jnp.float32)))
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 16)), jnp.float32)
    y_mod, aux_mod = mod(pr, x)
    y_ref, aux_ref = L.apply_moe_grouped(pr, x, mcfg)
    np.testing.assert_allclose(np.asarray(y_mod), np.asarray(y_ref), rtol=1e-5)


# ---- arch containers: logits parity vs tiny random HF models -------------

def _parity(hf_model, tol=5e-3, vocab=128):
    hf_model.eval()
    ids = np.random.default_rng(0).integers(0, vocab, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    model, params = build_native(hf_model, dtype="float32")
    got = np.asarray(model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, atol=tol, rtol=1e-2)


def test_container_llama():
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(0)
    _parity(LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64)))


def test_container_qwen2_biases():
    from transformers import Qwen2Config, Qwen2ForCausalLM
    torch.manual_seed(0)
    m = Qwen2ForCausalLM(Qwen2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64))
    # qkv biases are real in qwen2 — randomize so a dropped bias would fail
    with torch.no_grad():
        for layer in m.model.layers:
            layer.self_attn.q_proj.bias.normal_()
            layer.self_attn.k_proj.bias.normal_()
            layer.self_attn.v_proj.bias.normal_()
    _parity(m)


def test_container_mixtral_moe():
    from transformers import MixtralConfig, MixtralForCausalLM
    torch.manual_seed(0)
    _parity(MixtralForCausalLM(MixtralConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2)))


def test_container_opt():
    from transformers import OPTConfig, OPTForCausalLM
    torch.manual_seed(0)
    _parity(OPTForCausalLM(OPTConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        ffn_dim=64, max_position_embeddings=64, word_embed_proj_dim=32)))


def test_container_gpt2():
    from transformers import GPT2Config, GPT2LMHeadModel
    torch.manual_seed(0)
    _parity(GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4)))


def test_container_phi3_fused_splits():
    try:
        from transformers import Phi3Config, Phi3ForCausalLM
    except ImportError:
        pytest.skip("transformers has no Phi3")
    torch.manual_seed(0)
    _parity(Phi3ForCausalLM(Phi3Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64,
        pad_token_id=0)))


def test_resolver_unknown_arch():
    class FakeCfg:
        architectures = ["SomethingElseForCausalLM"]

    with pytest.raises(NotImplementedError):
        resolve_container(FakeCfg())


def test_container_gptneox_partial_rotary_parallel_residual():
    """GPT-NeoX/Pythia: head-interleaved fused QKV split, partial rotary
    (rotary_pct), parallel attention+MLP residual, exact-erf gelu."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    torch.manual_seed(0)
    _parity(GPTNeoXForCausalLM(GPTNeoXConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, max_position_embeddings=64,
        rotary_pct=0.25, use_parallel_residual=True)))


def test_container_falcon_multiquery_shared_norm():
    """Falcon-7B style: multi-query attention, parallel block with ONE
    shared layernorm (mapped into both norm slots), fused qkv split."""
    from transformers import FalconConfig, FalconForCausalLM
    torch.manual_seed(0)
    _parity(FalconForCausalLM(FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, multi_query=True, parallel_attn=True,
        new_decoder_architecture=False, bias=False, alibi=False)))


def test_container_gptj_shared_norm_biased_head():
    """GPT-J: interleaved partial rotary, parallel block sharing one
    layernorm, MLP-only biases, biased LM head."""
    from transformers import GPTJConfig, GPTJForCausalLM
    torch.manual_seed(0)
    m = GPTJForCausalLM(GPTJConfig(vocab_size=128, n_embd=32, n_layer=2,
                                   n_head=4, n_positions=64, rotary_dim=4))
    with torch.no_grad():
        m.lm_head.bias.normal_()
    _parity(m)


def test_container_bloom_alibi_embedding_norm():
    """BLOOM: ALiBi positions, embedding layernorm, head-interleaved fused
    QKV, tied head (reference ``module_inject/containers/bloom.py``)."""
    from transformers import BloomConfig, BloomForCausalLM
    torch.manual_seed(0)
    m = BloomForCausalLM(BloomConfig(vocab_size=128, hidden_size=32,
                                     n_layer=2, n_head=4))
    # HF inits all biases to zero; randomize so a dropped/mis-sliced bias
    # mapping would fail the parity check
    with torch.no_grad():
        for name, p in m.named_parameters():
            if name.endswith(".bias"):
                p.normal_(std=0.1)
    _parity(m)


def test_bloom_paged_engine_matches_dense():
    """BLOOM through InferenceEngineV2 (paged runner): the runner must apply
    the embedding layernorm and the ALiBi bias; greedy output == v1 dense."""
    import deepspeed_tpu as ds
    from transformers import BloomConfig, BloomForCausalLM
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    torch.manual_seed(1)
    hf = BloomForCausalLM(BloomConfig(vocab_size=128, hidden_size=32,
                                      n_layer=2, n_head=4))
    hf.eval()
    model, params = build_native(hf, dtype="float32")
    params = jax.tree.map(jnp.asarray, params)

    v1 = ds.init_inference(model, dtype="float32")
    v1.module_params = jax.device_put(params, v1.param_shardings)

    cfg = RaggedInferenceEngineConfig(kv_block_size=16, dtype="float32")
    v2 = InferenceEngineV2(model, cfg, max_seq_len=64, params=jax.device_put(params))

    prompt = np.random.default_rng(0).integers(0, 128, (1, 12))
    dense = np.asarray(v1.generate(prompt, max_new_tokens=6))[0, 12:]
    ragged = v2.generate([prompt[0]], max_new_tokens=6)[0]
    np.testing.assert_array_equal(dense, ragged)


def test_container_phi_parallel_block_biased_head():
    """Phi-1.5/2: parallel attn+mlp sharing one layernorm, partial rotary,
    biases everywhere, untied biased LM head."""
    from transformers import PhiConfig, PhiForCausalLM
    torch.manual_seed(0)
    m = PhiForCausalLM(PhiConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, partial_rotary_factor=0.5))
    with torch.no_grad():
        m.lm_head.bias.normal_()
    _parity(m)


def test_container_gptneo_local_attention():
    """GPT-Neo: alternating global/local attention with a window SMALLER
    than the test sequence (so the sliding-window mask must bind), unscaled
    attention logits, qkv without biases."""
    from transformers import GPTNeoConfig, GPTNeoForCausalLM
    torch.manual_seed(0)
    m = GPTNeoForCausalLM(GPTNeoConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=5,
        max_position_embeddings=64))
    _parity(m)


def test_container_mistral_sliding_window_binds():
    """Mistral with sliding_window < sequence length: the windowed mask must
    match HF's (a model ignoring the window would diverge)."""
    from transformers import MistralConfig, MistralForCausalLM
    torch.manual_seed(0)
    m = MistralForCausalLM(MistralConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=64, sliding_window=6))
    from deepspeed_tpu.inference.v2.model_implementations import resolve_container
    assert resolve_container(m.config).config(m.config).sliding_window == 6
    _parity(m)


def test_gptneo_paged_engine_matches_dense():
    """GPT-Neo through the v2 paged runner: out-proj bias (present without
    use_bias) and the per-layer local window must both be applied."""
    import deepspeed_tpu as ds
    from transformers import GPTNeoConfig, GPTNeoForCausalLM
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    torch.manual_seed(2)
    hf = GPTNeoForCausalLM(GPTNeoConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=5,
        max_position_embeddings=64))
    hf.eval()
    model, params = build_native(hf, dtype="float32")
    params = jax.tree.map(jnp.asarray, params)

    v1 = ds.init_inference(model, dtype="float32")
    v1.module_params = jax.device_put(params, v1.param_shardings)

    cfg = RaggedInferenceEngineConfig(kv_block_size=16, dtype="float32")
    v2 = InferenceEngineV2(model, cfg, max_seq_len=64, params=jax.device_put(params))

    prompt = np.random.default_rng(0).integers(0, 128, (1, 12))
    dense = np.asarray(v1.generate(prompt, max_new_tokens=6))[0, 12:]
    ragged = v2.generate([prompt[0]], max_new_tokens=6)[0]
    np.testing.assert_array_equal(dense, ragged)


def test_container_bert_mlm_parity():
    """BERT: post-norm encoder, token-type embeddings, embedding layernorm,
    MLM head — logits parity vs HF BertForMaskedLM."""
    from transformers import BertConfig, BertForMaskedLM
    torch.manual_seed(0)
    m = BertForMaskedLM(BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2))
    m.eval()
    ids = np.random.default_rng(0).integers(0, 128, (2, 16))
    tt = np.zeros_like(ids); tt[:, 8:] = 1
    with torch.no_grad():
        ref = m(torch.tensor(ids), token_type_ids=torch.tensor(tt)).logits.numpy()
    model, params = build_native(m, dtype="float32")
    from deepspeed_tpu.models.bert import EncoderLM
    assert isinstance(model, EncoderLM)
    got = np.asarray(model.apply(jax.tree.map(jnp.asarray, params), jnp.asarray(ids),
                                 token_type_ids=jnp.asarray(tt)))
    np.testing.assert_allclose(got, ref, atol=5e-3, rtol=1e-2)


def test_container_distilbert_mlm_parity():
    from transformers import DistilBertConfig, DistilBertForMaskedLM
    torch.manual_seed(0)
    m = DistilBertForMaskedLM(DistilBertConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
        max_position_embeddings=64))
    _parity(m)


def test_bert_mlm_loss_ignores_unmasked():
    """MLM loss averages only over labeled (-100-masked-out) positions."""
    from deepspeed_tpu.models import build_model
    model = build_model("bert-base", num_layers=2, hidden_size=64, num_heads=4,
                        intermediate_size=128, vocab_size=256, max_seq_len=32,
                        dtype="float32", param_dtype="float32")
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (2, 16))
    labels = np.full_like(ids, -100)
    labels[:, 3] = ids[:, 3]
    l1 = float(model.loss(params, {"input_ids": jnp.asarray(ids),
                                   "labels": jnp.asarray(labels)}))
    # flipping an ignored label must not change the loss
    labels2 = labels.copy(); labels2[:, 10] = -100
    l2 = float(model.loss(params, {"input_ids": jnp.asarray(ids),
                                   "labels": jnp.asarray(labels2)}))
    assert np.isfinite(l1) and abs(l1 - l2) < 1e-6


def test_bert_chunked_loss_matches_dense():
    """EncoderLM's vocab-chunked fused CE (decoder bias folded into an extra
    input column) must match the dense-logit loss."""
    from deepspeed_tpu.models import build_model
    model = build_model("bert-base", num_layers=2, hidden_size=64, num_heads=4,
                        intermediate_size=128, vocab_size=8192, max_seq_len=32,
                        dtype="float32", param_dtype="float32")
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 8192, (2, 16))
    labels = np.full_like(ids, -100)
    pos = rng.random(ids.shape) < 0.3
    labels[pos] = ids[pos]
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
    dense = float(model.loss(params, batch))        # under threshold: dense
    model_c = build_model(model.cfg.replace(loss_chunk_threshold_bytes=1))
    chunked = float(model_c.loss(params, batch))    # forced chunked path
    np.testing.assert_allclose(dense, chunked, rtol=1e-5)


def test_pipeline_encoder_support_boundaries():
    """Since round 5 the 1F1B engine accepts post-norm/MLM encoders (the
    old check_pipeline_model_support rejection is gone — reference
    pipelines arbitrary LayerSpec lists incl. BERT, pipe/module.py:86);
    the legacy GPipe autodiff path still rejects encoders and per-layer
    window patterns."""
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime.pipe.engine import build_pipeline_loss
    from deepspeed_tpu.utils import groups
    from deepspeed_tpu.models.config import TransformerConfig
    bert = build_model("bert-base", num_layers=2, hidden_size=32, num_heads=4,
                       intermediate_size=64, vocab_size=128)
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    with pytest.raises(NotImplementedError):
        build_pipeline_loss(bert, num_stages=2)       # GPipe = legacy
    neo_like = TransformerConfig(sliding_window=8, local_attention_every=2)
    neo_model = build_model(neo_like.replace(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, dtype="float32"))
    with pytest.raises(NotImplementedError):
        build_pipeline_loss(neo_model, num_stages=2)


def test_container_gemma_geglu_scaled_embed():
    """Gemma: sqrt(E)-scaled embeddings, offset RMSNorm (+1 at load), GeGLU
    MLP, explicit head_dim, tied head."""
    from transformers import GemmaConfig, GemmaForCausalLM
    torch.manual_seed(0)
    m = GemmaForCausalLM(GemmaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=64, max_position_embeddings=64))
    _parity(m)


def test_container_mpt_alibi_stacked_qkv():
    """MPT: stacked (non-interleaved) fused Wqkv, ALiBi, bias-free norms."""
    from transformers import MptConfig, MptForCausalLM
    torch.manual_seed(0)
    m = MptForCausalLM(MptConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4,
        expansion_ratio=2, max_seq_len=64))
    _parity(m)


def test_container_stablelm_partial_rotary_ln():
    from transformers import StableLmConfig, StableLmForCausalLM
    torch.manual_seed(0)
    m = StableLmForCausalLM(StableLmConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=64, partial_rotary_factor=0.5))
    _parity(m)


def test_auto_container_fallback_unmapped_llama_like():
    """An unmapped arch with the Llama module layout converts through the
    AutoContainer fallback (reference AutoTP analog) with exact parity."""
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      intermediate_size=64, max_position_embeddings=64)
    cfg.architectures = ["TotallyUnknownForCausalLM"]
    from deepspeed_tpu.inference.v2.model_implementations.archs import (
        AutoContainer, resolve_container)
    assert resolve_container(cfg) is AutoContainer
    m = LlamaForCausalLM(cfg)
    m.config.architectures = ["TotallyUnknownForCausalLM"]
    _parity(m)


def test_container_qwen2_moe_shared_expert():
    """Qwen2-MoE: un-renormalized top-k routing plus the sigmoid-gated
    always-on shared expert; logits parity vs HF."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
    torch.manual_seed(0)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=80,
        num_experts=4, num_experts_per_tok=2, max_position_embeddings=64,
        decoder_sparse_step=1, mlp_only_layers=[]))
    with torch.no_grad():
        for layer in m.model.layers:
            layer.self_attn.q_proj.bias.normal_()
            layer.self_attn.k_proj.bias.normal_()
            layer.self_attn.v_proj.bias.normal_()
    _parity(m, tol=1e-2)


def test_auto_container_refuses_non_llama_layout():
    """AutoContainer must refuse checkpoints whose layer layout carries
    tensors outside the Llama mapping (silently dropping them would corrupt
    outputs)."""
    from deepspeed_tpu.inference.v2.model_implementations.archs import AutoContainer
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=32))
    sd = m.state_dict()
    sd["model.layers.0.self_attn.q_norm.weight"] = torch.ones(8)
    cfg = AutoContainer.config(m.config)
    with pytest.raises(NotImplementedError, match="q_norm"):
        AutoContainer.build_params(sd, cfg)
