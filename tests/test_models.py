"""Model library tests (reference pattern: tests/unit/simple_model.py fixtures)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import build_model, get_config
from deepspeed_tpu.models.config import PRESETS


def test_tiny_forward_shapes(mesh_8dp, rng):
    model = build_model("tiny")
    params = model.init(rng)
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, model.cfg.vocab_size)


def test_gpt2_style_forward(mesh_8dp, rng):
    model = build_model("tiny-gpt2")
    params = model.init(rng)
    assert "pos" in params["embed"]          # learned positions
    assert "lm_head" not in params["embed"]  # tied
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, model.cfg.vocab_size)


def test_moe_forward(mesh_8dp, rng):
    model = build_model("tiny-moe")
    params = model.init(rng)
    ids = jnp.zeros((2, 16), jnp.int32)
    logits, aux = model.apply(params, ids, return_aux_loss=True)
    assert logits.shape == (2, 16, model.cfg.vocab_size)
    assert jnp.isfinite(aux)


def test_causality(mesh_8dp, rng):
    """Changing a future token must not affect past logits."""
    model = build_model("tiny")
    params = model.init(rng)
    ids1 = jnp.zeros((1, 16), jnp.int32)
    ids2 = ids1.at[0, 10].set(5)
    l1 = model.apply(params, ids1)
    l2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_loss_finite_and_grads(mesh_8dp, rng):
    model = build_model("tiny")
    params = model.init(rng)
    batch = {"input_ids": jnp.zeros((2, 16), jnp.int32),
             "labels": jnp.ones((2, 16), jnp.int32)}
    loss, grads = jax.value_and_grad(model.loss)(params, batch)
    assert jnp.isfinite(loss)
    assert all(jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads))


def test_logical_axes_match_params(mesh_8dp, rng):
    model = build_model("tiny")
    abstract = model.abstract_params()
    axes = model.logical_axes()
    flat_p = jax.tree.leaves(abstract)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for p, a in zip(flat_p, flat_a):
        assert len(a) == p.ndim, f"{a} vs {p.shape}"


def test_decode_matches_full_forward(mesh_8dp, rng):
    """Incremental KV-cache decode must equal full forward on the same prefix."""
    model = build_model("tiny")
    params = model.init(rng)
    ids = jax.random.randint(rng, (2, 8), 0, model.cfg.vocab_size)
    full = model.apply(params, ids)

    cache = model.init_cache(2, 16)
    cache_len = jnp.zeros((2,), jnp.int32)
    outs = []
    for t in range(8):
        logits, cache = model.apply_decode(params, ids[:, t:t + 1], cache, cache_len)
        cache_len = cache_len + 1
        outs.append(logits[:, 0])
    decoded = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(decoded), atol=2e-4)


def test_param_counts_presets():
    # GPT-2 small ~124M, Llama-2-7B ~6.7B (known public numbers)
    gpt2 = build_model("gpt2-small")
    assert 115e6 < gpt2.param_count() < 130e6
    llama = build_model("llama2-7b")
    assert 6.4e9 < llama.param_count() < 7.0e9


def test_all_presets_construct():
    for name in PRESETS:
        cfg = get_config(name)
        assert cfg.ffn_size > 0


def test_moe_grouped_matches_einsum(mesh_8dp=None):
    """Dropless grouped-GEMM MoE (moe_impl="grouped") reproduces the einsum
    dispatch path when capacity is generous enough that nothing drops —
    same loss, same grads within accumulation-order tolerance."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    cfg = get_config("tiny-moe").replace(moe_capacity_factor=8.0)
    me = build_model(cfg)
    mg = build_model(cfg.replace(moe_impl="grouped"))
    params = jax.jit(me.init)(jax.random.PRNGKey(0))
    r = np.random.default_rng(0)
    ids = jnp.asarray(r.integers(0, 256, (4, 32)))
    batch = {"input_ids": ids, "labels": ids}
    le, ge = jax.value_and_grad(me.loss)(params, batch)
    lg, gg = jax.value_and_grad(mg.loss)(params, batch)
    np.testing.assert_allclose(float(le), float(lg), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ge), jax.tree.leaves(gg)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-3, atol=5e-4)


def test_moe_grouped_dropless_beyond_capacity():
    """Where the einsum path drops tokens past capacity, the grouped path
    keeps them: outputs differ under a tight capacity factor and the grouped
    loss stays finite (every token routed)."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    cfg = get_config("tiny-moe").replace(moe_capacity_factor=0.25)
    me = build_model(cfg)
    mg = build_model(cfg.replace(moe_impl="grouped"))
    params = jax.jit(me.init)(jax.random.PRNGKey(1))
    r = np.random.default_rng(1)
    ids = jnp.asarray(r.integers(0, 256, (4, 32)))
    batch = {"input_ids": ids, "labels": ids}
    le = float(me.loss(params, batch))
    lg = float(mg.loss(params, batch))
    assert np.isfinite(lg)
    assert abs(le - lg) > 1e-6  # einsum dropped tokens, grouped did not


def test_moe_grouped_ep_matches_einsum():
    """Dropless grouped MoE under a SHARDED expert axis (explicit all-to-all
    ring + local ragged_dot, ``apply_moe_grouped_ep``) reproduces the
    capacity-einsum dispatch on a data x expert mesh when capacity is
    generous enough that nothing drops — same loss, same grads."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(expert=2, data=4))
    cfg = get_config("tiny-moe").replace(moe_capacity_factor=8.0)
    me = build_model(cfg)
    mg = build_model(cfg.replace(moe_impl="grouped"))
    params = jax.jit(me.init)(jax.random.PRNGKey(0))
    r = np.random.default_rng(0)
    ids = jnp.asarray(r.integers(0, 256, (8, 32)))
    batch = {"input_ids": ids, "labels": ids}
    le, ge = jax.jit(jax.value_and_grad(me.loss))(params, batch)
    lg, gg = jax.jit(jax.value_and_grad(mg.loss))(params, batch)
    np.testing.assert_allclose(float(le), float(lg), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ge), jax.tree.leaves(gg)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-3, atol=5e-4)


def test_moe_grouped_ep_dropless_beyond_capacity():
    """Under EP with a tight capacity factor the einsum path drops tokens;
    the grouped-EP ring keeps every token (static worst-case slot buffers)
    and trains a finite, different loss."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(expert=2, data=4))
    cfg = get_config("tiny-moe").replace(moe_capacity_factor=0.25)
    me = build_model(cfg)
    mg = build_model(cfg.replace(moe_impl="grouped"))
    params = jax.jit(me.init)(jax.random.PRNGKey(1))
    r = np.random.default_rng(1)
    ids = jnp.asarray(r.integers(0, 256, (8, 32)))
    batch = {"input_ids": ids, "labels": ids}
    le = float(me.loss(params, batch))
    lg = float(mg.loss(params, batch))
    assert np.isfinite(lg)
    assert abs(le - lg) > 1e-6  # einsum dropped tokens, grouped-EP did not


def test_alibi_slopes_standard_values():
    """ALiBi slopes match the published closed form (Press et al.): for 8
    heads the geometric sequence 2^-1 .. 2^-8; non-power-of-two counts
    extend with odd-indexed slopes of the doubled sequence."""
    from deepspeed_tpu.models.layers import alibi_slopes
    s8 = np.asarray(alibi_slopes(8))
    np.testing.assert_allclose(s8, [2.0 ** -(i + 1) for i in range(8)], rtol=1e-6)
    s12 = np.asarray(alibi_slopes(12))
    assert s12.shape == (12,)
    np.testing.assert_allclose(s12[:8], s8, rtol=1e-6)
    assert np.all(s12 > 0)


def test_alibi_attention_biases_distance(mesh_8dp, rng):
    """ALiBi end-to-end: forward is finite and incremental decode (bias
    built from absolute cache slots) matches the full forward. The bias
    sign/magnitude itself is pinned by the HF BLOOM parity test in
    test_v2_modules.py."""
    from deepspeed_tpu.models.config import TransformerConfig
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, intermediate_size=128, max_seq_len=32,
                            activation="gelu", norm="layernorm",
                            position="alibi", embedding_norm=True,
                            use_bias=True, tie_embeddings=True,
                            dtype="float32", param_dtype="float32")
    model = build_model(cfg)
    params = model.init(rng)
    assert "emb_norm" in params["embed"]
    ids = jax.random.randint(rng, (2, 12), 0, cfg.vocab_size)
    full = model.apply(params, ids)
    assert np.all(np.isfinite(np.asarray(full)))

    cache = model.init_cache(2, 16)
    cache_len = jnp.zeros((2,), jnp.int32)
    outs = []
    for t in range(12):
        logits, cache = model.apply_decode(params, ids[:, t:t + 1], cache, cache_len)
        cache_len = cache_len + 1
        outs.append(logits[:, 0])
    decoded = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(decoded), atol=3e-4)


def test_sliding_window_decode_matches_full(mesh_8dp, rng):
    """Sliding-window attention: KV-cache decode must apply the same window
    mask as the full forward (uniform window and alternating local/global)."""
    from deepspeed_tpu.models.config import TransformerConfig
    for every in (None, 2):
        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                                num_heads=4, intermediate_size=128, max_seq_len=32,
                                sliding_window=4, local_attention_every=every,
                                dtype="float32", param_dtype="float32")
        model = build_model(cfg)
        params = model.init(rng)
        ids = jax.random.randint(rng, (2, 12), 0, cfg.vocab_size)
        full = model.apply(params, ids)
        # windowed must differ from global attention (the mask binds)
        glob = build_model(cfg.replace(sliding_window=None)).apply(params, ids)
        assert np.abs(np.asarray(full) - np.asarray(glob)).max() > 1e-4

        cache = model.init_cache(2, 16)
        cache_len = jnp.zeros((2,), jnp.int32)
        outs = []
        for t in range(12):
            logits, cache = model.apply_decode(params, ids[:, t:t + 1], cache, cache_len)
            cache_len = cache_len + 1
            outs.append(logits[:, 0])
        decoded = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(full), np.asarray(decoded), atol=3e-4,
                                   err_msg=f"local_attention_every={every}")


def test_remat_offload_policy_resolves():
    """remat="dots_offload" (the reference cpu_checkpointing analog) maps to
    the host-offload checkpoint policy; numerics must match remat="none".
    (The actual host parking only happens on TPU — this exercises policy
    resolution and gradient equivalence.)"""
    from deepspeed_tpu.models.transformer import _remat_policy
    assert _remat_policy("dots_offload") is not None
    if jax.default_backend() != "tpu":
        return  # pinned_host memory space exists only on accelerators
    cfg = get_config("tiny").replace(remat="dots_offload")
    m_off = build_model(cfg)
    m_ref = build_model(cfg.replace(remat="none"))
    params = jax.jit(m_ref.init)(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)))
    batch = {"input_ids": ids, "labels": ids}
    np.testing.assert_allclose(float(m_off.loss(params, batch)),
                               float(m_ref.loss(params, batch)), rtol=1e-6)
