"""Fused vocab-chunked cross-entropy vs the unfused fp32 reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent, lm_cross_entropy


def _ref_nll(h, w, labels):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32).T)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return lse - ll


@pytest.mark.parametrize("v,n_chunks", [(1000, 8), (1024, 4), (50257, 8)])
def test_forward_matches_reference(v, n_chunks):
    rng = np.random.default_rng(0)
    n, e = 64, 32
    h = jnp.asarray(rng.standard_normal((n, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)
    nll = chunked_softmax_xent(h, w, labels, n_chunks)
    ref = _ref_nll(h, w, labels)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_grads_match_reference():
    rng = np.random.default_rng(1)
    n, e, v = 48, 24, 997  # prime vocab: exercises padding
    h = jnp.asarray(rng.standard_normal((n, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)

    def fused(h, w):
        return jnp.mean(chunked_softmax_xent(h, w, labels, 8))

    def ref(h, w):
        return jnp.mean(_ref_nll(h, w, labels))

    gf_h, gf_w = jax.grad(fused, argnums=(0, 1))(h, w)
    gr_h, gr_w = jax.grad(ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf_h), np.asarray(gr_h), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gf_w), np.asarray(gr_w), rtol=1e-4, atol=1e-5)


def test_lm_cross_entropy_masked_and_transposed():
    rng = np.random.default_rng(2)
    b, s, e, v = 2, 16, 24, 512
    h = jnp.asarray(rng.standard_normal((b, s, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (b, s)), jnp.float32)

    loss = lm_cross_entropy(h, w, labels, loss_mask=mask, n_chunks=4)
    loss_t = lm_cross_entropy(h, w.T, labels, loss_mask=mask, n_chunks=4, transpose_w=True)
    ref = _ref_nll(h.reshape(-1, e), w, labels.reshape(-1)).reshape(b, s)
    ref = jnp.sum(ref * mask) / jnp.sum(mask)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    np.testing.assert_allclose(float(loss_t), float(ref), rtol=1e-5)


def test_model_loss_fused_vs_unfused():
    """CausalLM.loss with loss_chunks vs the unfused path: same value."""
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(vocab_size=4096, hidden_size=64, num_layers=2, num_heads=4,
                            intermediate_size=128, max_seq_len=32, dtype="float32")
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
    batch = {"input_ids": ids, "labels": ids}

    m_fused = build_model(cfg.replace(loss_chunks=4, loss_chunk_threshold_bytes=0))
    params = m_fused.init(jax.random.PRNGKey(0))
    l_fused = m_fused.loss(params, batch)
    m_plain = build_model(cfg.replace(loss_chunks=0))
    l_plain = m_plain.loss(params, batch)
    np.testing.assert_allclose(float(l_fused), float(l_plain), rtol=2e-5)

    gf = jax.grad(m_fused.loss)(params, batch)
    gp = jax.grad(m_plain.loss)(params, batch)
    for a, b_ in zip(jax.tree.leaves(gf), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-4, atol=1e-5)
