"""Pipeline parallelism tests (reference pattern: tests/unit/runtime/pipe).

Correctness bar: a pipe-parallel run must match the single-stage run
numerically — same model, same data, same updates.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule, InferenceSchedule, bubble_fraction
from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec
from deepspeed_tpu.utils import groups


def _config(stage=0, gas=4):
    return {
        "train_batch_size": 32,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }


def _batch(seed, n=32, seq=32):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, (n, seq))
    return {"input_ids": ids, "labels": ids}


def _train(mesh_kw, steps=3, model_name="tiny", preset_over=None, zero=0):
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(**mesh_kw))
    model = build_model(model_name, **(preset_over or {}))
    engine, _, _, _ = ds.initialize(model=model, config=_config(zero))
    losses = [float(engine.train_batch(_batch(i))) for i in range(steps)]
    return losses, engine


def test_pipeline_matches_single_stage():
    """pipe=2 run must reproduce the dp-only run's loss trajectory."""
    ref, ref_eng = _train({"data": 8})
    got, eng = _train({"pipe": 2, "data": 4})
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)
    # layer stack actually sharded over pipe
    wq = eng.module_params["layers"]["attn"]["wq"]
    assert not wq.sharding.is_fully_replicated


def test_pipeline_with_zero1():
    ref, _ = _train({"data": 8}, zero=1)
    got, _ = _train({"pipe": 2, "data": 4}, zero=1)
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)


def test_pipeline_4stage():
    """4 stages x 4-layer model (1 layer per stage)."""
    over = {"num_layers": 4}
    ref, _ = _train({"data": 8}, preset_over=over)
    got, _ = _train({"pipe": 4, "data": 2}, preset_over=over)
    np.testing.assert_allclose(ref, got, rtol=5e-4, atol=5e-4)


def test_pipeline_forbids_decomposed_api():
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_config())
    with pytest.raises(RuntimeError):
        engine.forward(_batch(0, n=4))


def test_train_schedule_1f1b_structure():
    """1F1B instruction stream properties (reference TrainSchedule:189)."""
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = sched.steps()
    kinds = [[type(c).__name__ for c in s] for s in steps]
    flat = [k for s in kinds for k in s]
    assert flat.count("ForwardPass") == 4
    assert flat.count("BackwardPass") == 4
    assert flat[-1] == "OptimizerStep"
    # first stage loads microbatches
    assert "LoadMicroBatch" in flat
    # last stage never sends activations
    last = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    flat_last = [type(c).__name__ for s in last.steps() for c in s]
    assert "SendActivation" not in flat_last
    assert "RecvActivation" in flat_last


def test_compile_tick_tables_invariants():
    """Table compiler self-checks (completeness, deps, slot safety) pass for
    a spread of (microbatches, stages); strict mode respects the 1F1B
    in-flight cap while eager mode reaches the ideal tick count."""
    from deepspeed_tpu.runtime.pipe.schedule import compile_tick_tables
    for m, p in [(4, 2), (8, 4), (2, 4), (1, 2), (16, 8)]:
        f, b, n_buf = compile_tick_tables(m, p)           # asserts internally
        assert n_buf <= min(m, p)
        fe, be, n_buf_e = compile_tick_tables(m, p, eager=True)
        assert fe.shape[0] <= f.shape[0]
    # eager hits the ideal fill-drain tick count
    fe, _, _ = compile_tick_tables(32, 4, eager=True)
    assert fe.shape[0] == 32 + 2 * 3


def _pipe_1f1b_vs_ref(model, params, batch, num_stages, eager=False,
                      scale=1.0, rtol=1e-4, atol=1e-5):
    from deepspeed_tpu.runtime.pipe.engine import build_pipeline_1f1b
    m = jax.tree.leaves(batch)[0].shape[0]
    step = build_pipeline_1f1b(model, num_stages=num_stages, eager=eager)
    loss, grads = jax.jit(step)(params, batch, scale)

    def ref(p):
        return sum(model.loss(p, jax.tree.map(lambda v: v[i], batch))
                   for i in range(m)) / m

    rl, rg = jax.value_and_grad(ref)(params)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(rg)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   scale * np.asarray(b, np.float32),
                                   rtol=rtol, atol=atol)


def test_1f1b_matches_autodiff_causallm():
    """Compiled 1F1B (explicit vjp backward in reference TrainSchedule
    order) reproduces plain autodiff loss AND grads for a CausalLM."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("tiny")
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 256, (4, 2, 16)))
    _pipe_1f1b_vs_ref(model, params, {"input_ids": ids, "labels": ids}, 2,
                      rtol=2e-2, atol=2e-4)


def test_1f1b_matches_autodiff_encoder():
    """BERT-style post-norm/MLM/bidirectional encoder pipelines through the
    compiled 1F1B engine with loss AND grad parity vs plain autodiff —
    padding masks ride the microbatch stream into every stage's attention
    (reference pipelines BERT via arbitrary LayerSpec lists,
    pipe/module.py:86)."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("bert-base", num_layers=2, hidden_size=32,
                        num_heads=4, intermediate_size=64, vocab_size=128,
                        dtype="float32")
    assert model.cfg.post_norm and model.cfg.mlm_head and not model.cfg.causal
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(5)
    m, mb, s = 4, 2, 16
    ids = rng.integers(0, 128, (m, mb, s))
    labels = np.where(rng.random((m, mb, s)) < 0.15, ids, -100)
    labels[..., 0] = ids[..., 0]              # >=1 masked position per row
    mask = np.ones((m, mb, s), np.int32)
    mask[..., -3:] = 0                        # padded tail
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels),
             "attention_mask": jnp.asarray(mask)}
    _pipe_1f1b_vs_ref(model, params, batch, 2, rtol=2e-3, atol=2e-4)


def test_engine_bert_pipeline_trains():
    """End-to-end: BERT-tiny under pp=2 through deepspeed_tpu.initialize —
    the engine routes encoders into the 1F1B step and the MLM loss falls."""
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    import deepspeed_tpu as ds
    model = build_model("bert-base", num_layers=2, hidden_size=32,
                        num_heads=4, intermediate_size=64, vocab_size=128,
                        dtype="float32")
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": 16, "gradient_accumulation_steps": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "steps_per_print": 10 ** 9, "seed": 11})
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(4):
        ids = rng.integers(0, 128, (16, 16))
        labels = np.where(rng.random((16, 16)) < 0.2, ids, -100)
        labels[:, 0] = ids[:, 0]
        losses.append(float(engine.train_batch(
            {"input_ids": ids, "labels": labels})))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_1f1b_second_model_family():
    """1F1B is model-generic: the ResidualMLP family (pipe_embed/pipe_layer/
    pipe_loss protocol) pipelines with exact grad parity."""
    from deepspeed_tpu.models.mlp import ResidualMLP, MLPConfig
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = ResidualMLP(MLPConfig(num_layers=4))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(2)
    batch = {"x": jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32),
             "y": jnp.asarray(rng.integers(0, 8, (4, 8)))}
    _pipe_1f1b_vs_ref(model, params, batch, 2)


def test_1f1b_loss_scale_seeding():
    """fp16-style loss scale enters through the backward cotangent seed:
    grads come out multiplied by the scale, loss does not."""
    from deepspeed_tpu.models.mlp import ResidualMLP, MLPConfig
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = ResidualMLP(MLPConfig(num_layers=2))
    params = model.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(3)
    batch = {"x": jnp.asarray(rng.normal(size=(3, 4, 32)), jnp.float32),
             "y": jnp.asarray(rng.integers(0, 8, (3, 4)))}
    _pipe_1f1b_vs_ref(model, params, batch, 2, scale=64.0)


def test_inference_schedule():
    sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    flat = [type(c).__name__ for s in sched.steps() for c in s]
    assert flat.count("ForwardPass") == 3
    assert "BackwardPass" not in flat


def test_bubble_fraction():
    assert bubble_fraction(1, 4) == pytest.approx(3 / 4)
    assert bubble_fraction(16, 4) == pytest.approx(3 / 19)


def test_pipeline_module_planner():
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("tiny")  # 2 layers
    pm = PipelineModule.from_model(model)
    assert pm.num_stages == 2
    assert pm.layers_per_stage == 1
    assert pm.stage_owner(0) == 0 and pm.stage_owner(1) == 1
    assert pm.stage_layers(1) == [1]
    with pytest.raises(ValueError):
        PipelineModule.from_model(build_model("tiny", num_layers=3), num_stages=2)


def test_partition_method_validation():
    """'uniform'/'parameters' accepted (identical under stacked homogeneous
    layers); unknown methods rejected; type-regex loudly unimplemented."""
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    from deepspeed_tpu.models import build_model
    m = build_model("tiny")
    u = PipelineModule(model=m, num_stages=2, partition_method="uniform")
    p = PipelineModule(model=m, num_stages=2, partition_method="parameters")
    assert u.layers_per_stage == p.layers_per_stage
    with pytest.raises(ValueError):
        PipelineModule(model=m, num_stages=2, partition_method="bogus")
    with pytest.raises(NotImplementedError):
        PipelineModule(model=m, num_stages=2, partition_method="type:attn")


@pytest.mark.parametrize("layer_types", [
    ("dense", "moe", "dense", "moe"),   # periodic (Qwen2-MoE sparse step)
    ("dense", "dense", "moe", "moe"),   # contiguous segments (mlp_only prefix)
])
def test_1f1b_heterogeneous_stack(layer_types):
    """Heterogeneous stacks pipeline through 1F1B (reference PipeModule
    partitions arbitrary LayerSpec lists, ``runtime/pipe/module.py:86``):
    per-stage slot tables lax.switch each slot to its group's layer, and
    grads must match plain autodiff on the grouped tree — including the MoE
    router/expert grads."""
    from deepspeed_tpu.models.config import TransformerConfig
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=len(layer_types),
        num_heads=4, intermediate_size=128, max_seq_len=128, num_experts=2,
        num_experts_per_tok=1, layer_types=tuple(layer_types),
        dtype="float32", param_dtype="float32")
    model = build_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 256, (4, 2, 16)))
    _pipe_1f1b_vs_ref(model, params, {"input_ids": ids, "labels": ids}, 2,
                      rtol=2e-2, atol=2e-4)


def test_1f1b_per_layer_window_pattern():
    """Per-layer local/global window patterns (Gemma-2 style) pipeline
    through 1F1B via the (stage, slot) window table: grads match plain
    autodiff."""
    from deepspeed_tpu.models.config import TransformerConfig
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
        intermediate_size=128, max_seq_len=128,
        window_pattern=(8, 0, 8, 0), dtype="float32", param_dtype="float32")
    model = build_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 256, (4, 2, 16)))
    _pipe_1f1b_vs_ref(model, params, {"input_ids": ids, "labels": ids}, 2,
                      rtol=2e-2, atol=2e-4)
