"""Pipeline parallelism tests (reference pattern: tests/unit/runtime/pipe).

Correctness bar: a pipe-parallel run must match the single-stage run
numerically — same model, same data, same updates.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule, InferenceSchedule, bubble_fraction
from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec
from deepspeed_tpu.utils import groups


def _config(stage=0, gas=4):
    return {
        "train_batch_size": 32,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
        "seed": 7,
    }


def _batch(seed, n=32, seq=32):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, (n, seq))
    return {"input_ids": ids, "labels": ids}


def _train(mesh_kw, steps=3, model_name="tiny", preset_over=None, zero=0):
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(**mesh_kw))
    model = build_model(model_name, **(preset_over or {}))
    engine, _, _, _ = ds.initialize(model=model, config=_config(zero))
    losses = [float(engine.train_batch(_batch(i))) for i in range(steps)]
    return losses, engine


def test_pipeline_matches_single_stage():
    """pipe=2 run must reproduce the dp-only run's loss trajectory."""
    ref, ref_eng = _train({"data": 8})
    got, eng = _train({"pipe": 2, "data": 4})
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)
    # layer stack actually sharded over pipe
    wq = eng.module_params["layers"]["attn"]["wq"]
    assert not wq.sharding.is_fully_replicated


def test_pipeline_with_zero1():
    ref, _ = _train({"data": 8}, zero=1)
    got, _ = _train({"pipe": 2, "data": 4}, zero=1)
    np.testing.assert_allclose(ref, got, rtol=3e-4, atol=3e-4)


def test_pipeline_4stage():
    """4 stages x 4-layer model (1 layer per stage)."""
    over = {"num_layers": 4}
    ref, _ = _train({"data": 8}, preset_over=over)
    got, _ = _train({"pipe": 4, "data": 2}, preset_over=over)
    np.testing.assert_allclose(ref, got, rtol=5e-4, atol=5e-4)


def test_pipeline_forbids_decomposed_api():
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("tiny")
    engine, _, _, _ = ds.initialize(model=model, config=_config())
    with pytest.raises(RuntimeError):
        engine.forward(_batch(0, n=4))


def test_train_schedule_1f1b_structure():
    """1F1B instruction stream properties (reference TrainSchedule:189)."""
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = sched.steps()
    kinds = [[type(c).__name__ for c in s] for s in steps]
    flat = [k for s in kinds for k in s]
    assert flat.count("ForwardPass") == 4
    assert flat.count("BackwardPass") == 4
    assert flat[-1] == "OptimizerStep"
    # first stage loads microbatches
    assert "LoadMicroBatch" in flat
    # last stage never sends activations
    last = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    flat_last = [type(c).__name__ for s in last.steps() for c in s]
    assert "SendActivation" not in flat_last
    assert "RecvActivation" in flat_last


def test_inference_schedule():
    sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    flat = [type(c).__name__ for s in sched.steps() for c in s]
    assert flat.count("ForwardPass") == 3
    assert "BackwardPass" not in flat


def test_bubble_fraction():
    assert bubble_fraction(1, 4) == pytest.approx(3 / 4)
    assert bubble_fraction(16, 4) == pytest.approx(3 / 19)


def test_pipeline_module_planner():
    groups.reset_mesh()
    groups.set_mesh(groups.build_mesh(pipe=2, data=4))
    model = build_model("tiny")  # 2 layers
    pm = PipelineModule.from_model(model)
    assert pm.num_stages == 2
    assert pm.layers_per_stage == 1
    assert pm.stage_owner(0) == 0 and pm.stage_owner(1) == 1
    assert pm.stage_layers(1) == [1]
    with pytest.raises(ValueError):
        PipelineModule.from_model(build_model("tiny", num_layers=3), num_stages=2)
