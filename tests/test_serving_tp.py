"""Tensor-parallel frame serving (shard_map on the 8-device mesh).

`serve()` with ``tp=8`` compiles the frame loops under ``jax.shard_map``
over a 1-D tp mesh: weights column/row-sharded, paged KV pools (target AND
draft) sharded head-wise, and the whole slot-table carry replicated so every
frame-boundary policy (admission, quarantine, deadlines, snapshots) stays
single-host. The contract these tests pin, on the same virtual 8-device CPU
mesh the MULTICHIP dryruns use:

- greedy outputs token-identical to ``tp=1`` — plain, speculative, and
  mid-stream-arrival serving alike;
- the zero-in-frame-device-to-host transfer guard still holds;
- the opt-in collective lowerings (T3-style overlap ring, EQuARX-style int8
  quantized exchanges) meet their parity contracts;
- fault tolerance is topology-blind: poison-row quarantine keeps survivor
  parity on a sharded engine, and a crash snapshot taken at one TP degree
  resumes token-identically at another (the carry/snapshot plumbing is
  engine-shape-agnostic — the prerequisite for the multi-engine router).

Engines are f32 and module-scoped where possible: shard_map programs over 8
virtual devices compile slowly enough that every fresh engine costs seconds.
"""

import numpy as np
import jax
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.faults import (FaultInjector, FaultSpec,
                                               FrameDispatchError)
from deepspeed_tpu.models import build_model

pytestmark = pytest.mark.multichip

MAX_NEW = 8


@pytest.fixture(scope="module")
def tp_model_params():
    """tiny with 8 heads: every TP-sharded axis (heads=kv_heads=8, ffn=128,
    vocab=256) divides the 8-way mesh."""
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=128)


PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (200,))
           .astype(np.int32)[o:o + n]
           for u, (o, n) in enumerate(((0, 7), (10, 24), (40, 33), (80, 5)))}
SCHEDULE = {0: [0, 1], 2: [2], 3: [3]}


def _mid_stream_arrivals():
    for k in range(max(SCHEDULE) + 2):
        yield [(u, PROMPTS[u]) for u in SCHEDULE.get(k, [])]


@pytest.fixture(scope="module")
def greedy_base(tp_model_params):
    """tp=1 greedy serve() outputs — THE reference every sharded variant
    must reproduce token-for-token."""
    model, params = tp_model_params
    return dict(_engine(model, params).serve(_mid_stream_arrivals(),
                                             max_new_tokens=MAX_NEW))


@pytest.fixture(scope="module")
def tp8_engine(tp_model_params):
    model, params = tp_model_params
    return _engine(model, params, tp=8)


def test_tp8_greedy_token_parity(tp8_engine, greedy_base):
    """tp=8 serve() is token-identical to tp=1 under greedy decoding,
    including sequences admitted mid-decode, and drains clean."""
    e = tp8_engine
    got = dict(e.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW))
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    assert not e.state.seqs
    assert e.telemetry.gauges["tp_degree"] == 8


def test_tp8_device_counters_match_tp1(tp8_engine, tp_model_params,
                                       greedy_base):
    """The in-graph frame counters (read from shard 0 only) replay the same
    totals as the single-chip engine — the telemetry surface is
    topology-blind."""
    model, params = tp_model_params
    e1 = _engine(model, params)
    dict(e1.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW))
    dict(tp8_engine.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW))
    for name in ("tokens_emitted", "prefill_tokens", "eos_events",
                 "target_forwards"):
        assert (e1.telemetry.counters[name]
                == tp8_engine.telemetry.counters[name]), name


def test_tp8_spec_greedy_parity(tp_model_params, greedy_base):
    """Speculative serving on the sharded engine (self-draft, its own
    head-sharded KV pools riding the same mesh) stays token-identical to
    the tp=1 non-speculative baseline."""
    model, params = tp_model_params
    e = _engine(model, params, tp=8)
    e.attach_draft(model, params)
    got = dict(e.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW,
                       gamma=2))
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    sp = e.serve_stats["spec"]
    assert sp["tokens_per_target_forward"] > 2.0, sp
    assert e.kv.free_blocks == e.kv.num_blocks - 1


def test_tp8_zero_in_frame_transfers(tp_model_params, greedy_base,
                                     frame_transfer_guard):
    """Sharding must not smuggle device reads into the frame: dispatch
    under a device-to-host transfer guard (conftest's shared definition of
    "in-frame"), with the per-shard stats rows and replicated carry all
    surfacing at boundaries only."""
    model, params = tp_model_params
    e = _engine(model, params, tp=8)
    got = dict(e.serve(iter([[(0, PROMPTS[0]), (1, PROMPTS[1])]]),
                       max_new_tokens=MAX_NEW))
    for u in (0, 1):
        np.testing.assert_array_equal(greedy_base[u], got[u])


def test_tp8_replica_consistency_debug_mode(tp_model_params, greedy_base):
    """tp_debug_replica_check reads ALL shards' frame-counter rows at every
    boundary and asserts they agree — the replica-consistency proof of the
    shard-0-only steady-state read. A full serve under the check passing is
    the assertion (any shard-varying leak into the counters raises)."""
    model, params = tp_model_params
    e = _engine(model, params, tp=8, tp_debug_replica_check=True)
    got = dict(e.serve(iter([[(0, PROMPTS[0]), (1, PROMPTS[1])]]),
                       max_new_tokens=MAX_NEW))
    for u in (0, 1):
        np.testing.assert_array_equal(greedy_base[u], got[u])
    assert e.telemetry.counters["tokens_emitted"] == 2 * MAX_NEW


def test_tp8_quantized_collectives_parity_at_tolerance(tp8_engine,
                                                       tp_model_params,
                                                       greedy_base):
    """The opt-in int8 all-reduce/all-gather path (EQuARX-style): per-row
    symmetric quantization bounds the logit error, so single-step logits
    must track the exact path within tolerance and generation must still
    complete every budget. Token-for-token equality is NOT the contract —
    quantization may legitimately flip near-ties."""
    model, params = tp_model_params
    eq = _engine(model, params, tp=8, tp_quantized_collectives=True)
    got = dict(eq.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW))
    assert set(got) == set(PROMPTS)
    assert all(len(v) == MAX_NEW for v in got.values())
    assert eq.kv.free_blocks == eq.kv.num_blocks - 1

    # logit-level tolerance on one exact forward vs one quantized forward:
    # run the SAME single-token decode through both engines' runners
    ids = np.asarray([[5]], np.int32)
    pos = np.asarray([[0]], np.int32)
    tbl = np.asarray([[1]], np.int32)
    ones = np.asarray([1], np.int32)

    def one_logits(e):
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        tp = e.tp_ctx
        import functools
        fwd = functools.partial(e.runner._forward, tp=tp)

        def core(params, kpool, vpool):
            logits, _, _ = fwd(params, jnp.asarray(ids), jnp.asarray(pos),
                               jnp.asarray(tbl), jnp.asarray(ones),
                               kpool, vpool)
            return logits

        f = shard_map(core, mesh=tp.mesh,
                      in_specs=(tp.param_specs, tp.kv_spec, tp.kv_spec),
                      out_specs=P(), check_rep=False)
        return np.asarray(jax.jit(f)(e.params, e.kv.k, e.kv.v))

    exact = one_logits(tp8_engine)
    quant = one_logits(eq)
    scale = np.abs(exact).max()
    assert np.abs(exact - quant).max() <= 0.05 * scale, \
        (np.abs(exact - quant).max(), scale)


def test_tp8_overlap_ring_collectives_parity(tp_model_params, greedy_base):
    """The T3-style overlap path (MLP all-reduce as ppermute ring chunks)
    reorders the reduction but changes no operand values: greedy tokens on
    this model match the exact path."""
    model, params = tp_model_params
    eo = _engine(model, params, tp=8, tp_overlap_collectives=True)
    got = dict(eo.serve(_mid_stream_arrivals(), max_new_tokens=MAX_NEW))
    for u in PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")


@pytest.mark.chaos
def test_tp8_poison_quarantine_survivor_parity(tp_model_params, greedy_base):
    """Chaos on the sharded engine: a poisoned row is quarantined via the
    mesh-aware evict (one replicated boundary write) while its batch
    siblings stay token-identical to the fault-free tp=1 baseline — the
    quarantine/evict machinery is topology-blind."""
    model, params = tp_model_params
    e = _engine(model, params, tp=8)
    fi = FaultInjector([FaultSpec(kind="poison_row", frame=1, uid=1)])
    got = dict(e.serve(iter([[(u, PROMPTS[u]) for u in (0, 1, 2)]]),
                       max_new_tokens=MAX_NEW, faults=fi))
    assert 1 not in got
    for u in (0, 2):
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"survivor uid={u}")
    fl = [f for f in e.fault_log if f.kind == "poison_row"]
    assert len(fl) == 1 and fl[0].uid == 1
    assert e.kv.free_blocks == e.kv.num_blocks - 1   # evicted blocks freed
    assert not e.state.seqs


@pytest.mark.chaos
def test_snapshot_resumes_across_tp_degrees(tp_model_params, greedy_base):
    """Kill-and-resume with a DIFFERENT tensor-parallel degree on each side:
    the ledger snapshot is host-only and engine-shape-agnostic, so a tp=8
    crash resumes on tp=1 (and tp=1 on tp=8) token-identically — the
    contract ROADMAP item 2's multi-engine failover router builds on."""
    model, params = tp_model_params

    def crash(e):
        fi = FaultInjector(
            [FaultSpec(kind="dispatch_exception", frame=2, times=99)])
        out = {}
        with pytest.raises(FrameDispatchError):
            for u, t in e.serve(iter([[(u, PROMPTS[u]) for u in (0, 1, 2)]]),
                                max_new_tokens=MAX_NEW, faults=fi):
                out[u] = t
        assert e.last_crash_snapshot is not None
        return out, e.last_crash_snapshot

    # tp=8 crash -> tp=1 resume
    done, snap = crash(_engine(model, params, tp=8))
    merged = dict(done)
    merged.update(dict(_engine(model, params).serve(iter([[]]),
                                                    resume_from=snap)))
    for u in (0, 1, 2):
        np.testing.assert_array_equal(greedy_base[u], merged[u],
                                      err_msg=f"tp8->tp1 uid={u}")

    # tp=1 crash -> tp=8 resume
    done, snap = crash(_engine(model, params))
    e8 = _engine(model, params, tp=8)
    merged = dict(done)
    merged.update(dict(e8.serve(iter([[]]), resume_from=snap)))
    for u in (0, 1, 2):
        np.testing.assert_array_equal(greedy_base[u], merged[u],
                                      err_msg=f"tp1->tp8 uid={u}")
    assert e8.telemetry.counters["recoveries"] == len(snap["requests"])


def test_tp_validation_rejects_indivisible_arch():
    """Loud construction-time failure when a sharded axis doesn't divide:
    a silently replicated head tensor would corrupt the psum arithmetic."""
    model = build_model("tiny")          # 4 heads: 4 % 8 != 0
    with pytest.raises(NotImplementedError, match="num_heads=4"):
        InferenceEngineV2(model,
                          RaggedInferenceEngineConfig(tp=8, dtype="float32"),
                          max_seq_len=128)


def test_tp_vocab_fallback_replicates(tp_model_params):
    """A vocab the tp degree doesn't divide falls back to a replicated
    embedding/LM head (memory cost, not a correctness cliff) while heads
    and MLP stay sharded."""
    model = build_model("tiny", num_heads=8, vocab_size=252)  # 252 % 8 != 0
    params = model.init(jax.random.PRNGKey(0))
    e1 = _engine(model, params)
    e8 = _engine(model, params, tp=8)
    assert not e8.tp_ctx.vocab_sharded
    p = np.random.default_rng(7).integers(0, 250, (9,)).astype(np.int32)
    base = dict(e1.serve(iter([[(0, p)]]), max_new_tokens=MAX_NEW))
    got = dict(e8.serve(iter([[(0, p)]]), max_new_tokens=MAX_NEW))
    np.testing.assert_array_equal(base[0], got[0])
