"""Frame-based persistent serving loop tests.

The frame loop (``engine_v2.serve``) must match host-driven ``step()``
serving token-for-token under greedy decoding — including sequences admitted
while others are mid-decode — and must keep the compiled-program count
O(log) in batch size (the recompile budget that makes continuous batching
run at compiled-loop speed)."""

import numpy as np
import jax
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.models import build_model


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny")
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4)
    kw.update(over)
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                          max_seq_len=128)
    e.params = jax.device_put(params)
    return e


def _step_serve(eng, admissions, max_new_tokens):
    """Host-driven baseline: put() batches at arbitrary points mid-decode,
    step() until every uid has its budget. Per-uid greedy outputs are
    schedule-independent (rows are independent in the forward and chunk
    boundaries depend only on the chunk size), so this is THE reference for
    any admission timing."""
    admissions = list(admissions)
    counts = {}
    outs = {}
    while admissions or counts:
        if admissions:
            uids, prompts = admissions.pop(0)
            eng.put(uids, prompts)
            counts.update({u: 0 for u in uids})
        for _ in range(3):   # a few steps between admissions
            produced = eng.step()
            for u, _t in produced.items():
                counts[u] += 1
            for u in list(counts):
                if counts[u] >= max_new_tokens:
                    seq = eng.state.seqs[u]
                    seq.done = True
                    outs[u] = np.asarray(seq.generated[:max_new_tokens])
                    eng.flush([u])
                    del counts[u]
            if not counts:
                break
    return outs


def test_frame_serving_parity_mid_stream_arrivals(tiny_model_params):
    """serve() greedy outputs == step() greedy outputs per uid, with
    sequences admitted while others are mid-decode on both sides."""
    model, params = tiny_model_params
    rng = np.random.default_rng(5)
    prompts = {u: rng.integers(0, 200, (n,)).astype(np.int32)
               for u, n in zip(range(4), (7, 24, 33, 5))}

    # frame loop: uids 0/1 arrive up front; 2 and 3 arrive at later frame
    # boundaries, while 0/1 are already decoding
    schedule = {0: [0, 1], 2: [2], 3: [3]}

    def arrivals():
        for k in range(5):
            yield [(u, prompts[u]) for u in schedule.get(k, [])]

    e1 = _engine(model, params)
    got = dict(e1.serve(arrivals(), max_new_tokens=8))
    assert set(got) == set(prompts)
    assert e1.kv.free_blocks == e1.kv.num_blocks - 1   # all retired+flushed

    # host-driven baseline with its own (different) mid-stream admissions
    e2 = _engine(model, params)
    ref = _step_serve(e2, [([0, 1], [prompts[0], prompts[1]]),
                           ([2], [prompts[2]]), ([3], [prompts[3]])], 8)

    for u in prompts:
        np.testing.assert_array_equal(ref[u], got[u],
                                      err_msg=f"uid={u} diverged")


def test_frame_serving_in_graph_eos(tiny_model_params):
    """A row whose sampled token hits its per-row EOS freezes IN-GRAPH and
    retires with the EOS included; other rows are unaffected."""
    model, params = tiny_model_params
    rng = np.random.default_rng(6)
    prompts = {0: rng.integers(0, 200, (9,)).astype(np.int32),
               1: rng.integers(0, 200, (21,)).astype(np.int32)}

    base = dict(_engine(model, params).serve(
        iter([[(u, prompts[u]) for u in prompts]]), max_new_tokens=8))
    eos = int(base[0][2])          # uid 0's third token becomes its EOS
    stop = base[0].tolist().index(eos)   # freezes at the FIRST occurrence

    got = dict(_engine(model, params).serve(
        iter([[(0, prompts[0], None, None, eos), (1, prompts[1])]]),
        max_new_tokens=8))
    np.testing.assert_array_equal(got[0], base[0][:stop + 1])
    if eos not in base[1].tolist():
        np.testing.assert_array_equal(got[1], base[1])   # neighbor untouched


def test_frame_serving_admission_control_overload(tiny_model_params):
    """More arrivals than slots: admission defers (FIFO) until retirements
    free slots; everything still finishes and the pool drains clean."""
    model, params = tiny_model_params
    rng = np.random.default_rng(7)
    prompts = {u: rng.integers(0, 200, (6 + u,)).astype(np.int32)
               for u in range(6)}
    e = _engine(model, params, max_ragged_batch_size=2)

    got = dict(e.serve(iter([[(u, prompts[u]) for u in prompts]]),
                       max_new_tokens=5, frame_slots=2))
    assert set(got) == set(prompts)
    assert all(len(v) == 5 for v in got.values())
    assert e.kv.free_blocks == e.kv.num_blocks - 1

    ref = _step_serve(_engine(model, params),
                      [(list(prompts), list(prompts.values()))], 5)
    for u in prompts:
        np.testing.assert_array_equal(ref[u], got[u])


def test_frame_serving_sampled_rows(tiny_model_params):
    """Per-row temperatures ride the device carry: a sampled row and greedy
    rows share one frame; the greedy rows still match the greedy baseline."""
    model, params = tiny_model_params
    rng = np.random.default_rng(8)
    prompts = {0: rng.integers(0, 200, (11,)).astype(np.int32),
               1: rng.integers(0, 200, (17,)).astype(np.int32)}

    base = dict(_engine(model, params).serve(
        iter([[(u, prompts[u]) for u in prompts]]), max_new_tokens=6))
    got = dict(_engine(model, params).serve(
        iter([[(0, prompts[0], None, 0.8), (1, prompts[1])]]),
        max_new_tokens=6))
    assert len(got[0]) == 6                      # sampled row completed
    np.testing.assert_array_equal(got[1], base[1])   # greedy row bit-exact


def test_run_batch_recompile_count_bounded(tiny_model_params):
    """Ragged batch-size sweep: the per-chunk jit cache must stay O(log) in
    live batch size (power-of-two padding), not O(B)."""
    model, params = tiny_model_params
    e = _engine(model, params)
    rng = np.random.default_rng(9)
    # admit one sequence per step: decode batch ramps 1,2,3,...,7 while each
    # step also runs a batch-1 prefill chunk
    for u in range(7):
        e.put([u], [rng.integers(0, 200, (5,)).astype(np.int32)])
        e.step()
    for _ in range(4):
        e.step()
    # programs: prefill chunk=16 at padded B=1, decode chunk=1 at padded
    # B in {1, 2, 4, 8} -> 5. Unpadded, the decode sweep alone compiles 7.
    # compile_count() is per-function, so the test can pin WHICH entry
    # point recompiled, not just the aggregate.
    cc = e.runner.compile_count()
    assert sum(cc.values()) <= 5, cc
    assert cc.get("chunk16", 0) <= 1 and cc.get("chunk1", 0) <= 4, cc
    # block tables come back as host numpy — one device transfer per step,
    # not one per sequence
    seq = e.state.seqs[0]
    assert isinstance(e.state.block_table(seq, 4), np.ndarray)


def test_frame_loop_recompile_count_bounded(tiny_model_params):
    """The frame jit retraces only per shape bucket: width in {chunk, 1} x
    power-of-two table/prompt widths — a long dynamic-arrival run stays at a
    handful of programs."""
    model, params = tiny_model_params
    e = _engine(model, params)
    rng = np.random.default_rng(10)

    def arrivals():
        for k in range(8):
            # staggered lengths force prompt-width regrowth + mixed frames
            yield [(k, rng.integers(0, 200, (4 + 7 * k,)).astype(np.int32))]

    got = dict(e.serve(arrivals(), max_new_tokens=6))
    assert len(got) == 8
    frame_fn = e.runner._fns["frame"]
    assert frame_fn._cache_size() <= 6


def test_frame_serving_admission_guards(tiny_model_params):
    """A duplicate in-flight uid is a client error (loud, before it can
    corrupt the uid<->slot mapping); an over-context budget is clamped so
    the slot table never outgrows max_seq_len."""
    model, params = tiny_model_params
    rng = np.random.default_rng(12)
    p = rng.integers(0, 200, (8,)).astype(np.int32)

    with pytest.raises(ValueError, match="already live"):
        list(_engine(model, params).serve(
            iter([[(0, p)], [(0, p)]]), max_new_tokens=64))

    # 100-token prompt in a 128-token context: budget 64 -> clamped to 27
    long_p = rng.integers(0, 200, (100,)).astype(np.int32)
    e = _engine(model, params)
    got = dict(e.serve(iter([[(0, long_p)]]), max_new_tokens=64))
    assert len(got[0]) == 128 - 100 - 1
    assert e.kv.free_blocks == e.kv.num_blocks - 1


def test_frame_serving_abandonment_releases_state(tiny_model_params):
    """Breaking out of serve() mid-stream (server shutdown, client error)
    must release every in-flight sequence: no leaked KV blocks, no stale
    descriptors that would feed old tokens to a later call reusing a uid."""
    model, params = tiny_model_params
    rng = np.random.default_rng(13)
    prompts = {u: rng.integers(0, 200, (10 + u,)).astype(np.int32)
               for u in range(4)}
    e = _engine(model, params)
    for _uid, _toks in e.serve(iter([[(u, prompts[u]) for u in prompts]]),
                               max_new_tokens=16):
        break                                   # abandon with 3 in flight
    assert not e.state.seqs
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    # the engine is reusable afterwards, uids included
    got = dict(e.serve(iter([[(0, prompts[0])]]), max_new_tokens=4))
    assert len(got[0]) == 4


# ---------------------------------------------------------------------------
# speculative decoding on the frame carry
# ---------------------------------------------------------------------------
# The speculative tests share module-scope engines and one greedy baseline:
# every fresh engine recompiles its serving programs from scratch on CPU, so
# reusing engines (their jit caches persist across serve() calls — serve
# leaves the engine clean) keeps the suite inside the tier-1 time budget.


SPEC_PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (200,))
                .astype(np.int32)[o:o + n]
                for u, (o, n) in enumerate(((0, 7), (10, 24), (40, 33),
                                            (80, 5)))}
SPEC_SCHEDULE = {0: [0, 1], 2: [2], 3: [3]}


def _spec_engine(model, params, draft_model=None, draft_params=None, **over):
    """Engine with a draft attached; draft defaults to a self-draft (same
    model, same params — the 100%-acceptance upper bound)."""
    e = _engine(model, params, **over)
    e.attach_draft(draft_model if draft_model is not None else model,
                   draft_params if draft_params is not None else params)
    return e


def _mid_stream_arrivals(prompts=None, schedule=None):
    prompts = SPEC_PROMPTS if prompts is None else prompts
    schedule = SPEC_SCHEDULE if schedule is None else schedule
    for k in range(max(schedule) + 2):
        yield [(u, prompts[u]) for u in schedule.get(k, [])]


@pytest.fixture(scope="module")
def greedy_base(tiny_model_params):
    """Non-speculative greedy serve() outputs for SPEC_PROMPTS — THE
    reference every speculative variant must reproduce bit-exactly."""
    model, params = tiny_model_params
    return dict(_engine(model, params).serve(_mid_stream_arrivals(),
                                             max_new_tokens=8))


@pytest.fixture(scope="module")
def self_draft_engine(tiny_model_params):
    model, params = tiny_model_params
    return _spec_engine(model, params)


@pytest.fixture(scope="module")
def distinct_draft_engine(tiny_model_params):
    """Draft with a different arch (1 layer) and a fresh init: proposals are
    effectively random, so essentially every speculative step rejects."""
    from deepspeed_tpu.models import build_model as _bm
    model, params = tiny_model_params
    draft = _bm("tiny", num_layers=1)
    return _spec_engine(model, params, draft_model=draft,
                        draft_params=draft.init(jax.random.PRNGKey(42)))


def test_spec_greedy_parity_self_draft(self_draft_engine, greedy_base):
    """Speculative serve() with draft == target is token-identical to the
    non-speculative frame loop under greedy decoding — including sequences
    admitted mid-decode — and emits > 2 tokens per target forward at
    gamma=2 (full acceptance, minus end-of-budget truncation)."""
    e = self_draft_engine
    got = dict(e.serve(_mid_stream_arrivals(), max_new_tokens=8, gamma=2))
    for u in SPEC_PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    sp = e.serve_stats["spec"]
    assert sp["tokens_per_target_forward"] > 2.0, sp
    # acceptance never synced the host: the frame only hands back the
    # (steps, B, gamma+1) token/emit pair
    assert sp["accepted_drafts"] > 0


def test_spec_greedy_parity_distinct_draft(distinct_draft_engine, greedy_base):
    """A DIFFERENT draft (1 layer, fresh init — near-zero acceptance) must
    still produce bit-identical greedy output: verification + in-graph
    rollback make draft quality a throughput knob, never a correctness one."""
    e = distinct_draft_engine
    got = dict(e.serve(_mid_stream_arrivals(), max_new_tokens=8, gamma=2))
    for u in SPEC_PROMPTS:
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u} diverged")
    assert e.serve_stats["spec"]["acceptance_rate"] < 1.0


def test_spec_rollback_forced_rejection(distinct_draft_engine, greedy_base):
    """The garbage draft forces a rejection + rollback on essentially every
    step; the committed watermark and host mirrors must stay consistent:
    emitted tokens match non-speculative serving, every row retires at
    exactly its budget, and the pool drains clean (rejected KV entries are
    overwritten in place, never freed)."""
    e = distinct_draft_engine
    got = dict(e.serve(iter([[(u, SPEC_PROMPTS[u]) for u in SPEC_PROMPTS]]),
                       max_new_tokens=8, gamma=2))
    assert set(got) == set(SPEC_PROMPTS)
    for u in SPEC_PROMPTS:
        assert len(got[u]) == 8            # full budget despite rollbacks
        np.testing.assert_array_equal(greedy_base[u], got[u],
                                      err_msg=f"uid={u}")
    sp = e.serve_stats["spec"]
    assert sp["acceptance_rate"] < 0.5, sp   # rejections actually happened
    assert e.kv.free_blocks == e.kv.num_blocks - 1
    assert not e.state.seqs                  # mirrors fully retired
    # the engine (and its draft pools) stay reusable after heavy rollback
    again = dict(e.serve(iter([[(0, SPEC_PROMPTS[0])]]), max_new_tokens=4))
    np.testing.assert_array_equal(again[0], greedy_base[0][:4])


def test_spec_in_graph_eos(self_draft_engine, greedy_base):
    """EOS inside an accepted draft run truncates the emit mask in-graph:
    the row keeps the EOS, drops the speculated tail, and retires."""
    e = self_draft_engine
    eos = int(greedy_base[0][2])       # uid 0's third token becomes its EOS
    stop = greedy_base[0].tolist().index(eos)
    got = dict(e.serve(
        iter([[(0, SPEC_PROMPTS[0], None, None, eos),
               (1, SPEC_PROMPTS[1])]]), max_new_tokens=8, gamma=2))
    np.testing.assert_array_equal(got[0], greedy_base[0][:stop + 1])
    if eos not in greedy_base[1].tolist():
        np.testing.assert_array_equal(got[1], greedy_base[1])


def test_spec_recompile_count_bounded(tiny_model_params):
    """Speculation adds ONE new entry point (spec_frame) with the same
    shape-bucket discipline: width in {chunk, 1} x pow2 table/prompt widths.
    The per-function compile_count pins exactly where programs come from."""
    model, params = tiny_model_params
    e = _spec_engine(model, params)     # fresh engine: counting programs
    rng = np.random.default_rng(10)

    def arrivals():
        for k in range(6):   # staggered lengths: prompt buckets 16 -> 32 -> 64
            yield [(k, rng.integers(0, 200, (4 + 7 * k,)).astype(np.int32))]

    got = dict(e.serve(arrivals(), max_new_tokens=4, gamma=2))
    assert len(got) == 6
    cc = e.runner.compile_count()
    assert cc.get("spec_frame", 0) <= 6, cc
    assert "frame" not in cc          # the non-spec frame never compiled


def test_spec_sampled_rows_complete(self_draft_engine, greedy_base):
    """temperature > 0 rides the speculative frame via rejection sampling:
    sampled rows complete their budget; greedy rows in the same frame stay
    bit-exact vs the non-speculative greedy baseline."""
    e = self_draft_engine
    got = dict(e.serve(
        iter([[(0, SPEC_PROMPTS[0], None, 0.8), (1, SPEC_PROMPTS[1])]]),
        max_new_tokens=8, gamma=2))
    assert len(got[0]) == 8
    np.testing.assert_array_equal(got[1], greedy_base[1])


def test_serve_rng_reproducible(self_draft_engine, tiny_model_params):
    """An explicit rng/seed threads into the frame carry: two sampled serves
    with the same seed are identical (speculative or not); the default path
    still draws from the engine's stream."""
    model, params = tiny_model_params

    def one(e, seed, **kw):
        return dict(e.serve(
            iter([[(0, SPEC_PROMPTS[0], None, 0.8),
                   (1, SPEC_PROMPTS[1], None, 0.8)]]),
            max_new_tokens=8, rng=seed, **kw))

    es = self_draft_engine
    a, b = one(es, 7, gamma=2), one(es, 7, gamma=2)
    for u in a:
        np.testing.assert_array_equal(a[u], b[u])
    en = _engine(model, params)
    c, d = one(en, 7, speculate=False), one(en, 7, speculate=False)
    for u in c:
        np.testing.assert_array_equal(c[u], d[u])


def test_adaptive_frame_steps_buckets(tiny_model_params):
    """Adaptive frame sizing: bursty arrivals shrink the frame to a small
    pow2 bucket (TTFT), a drained arrival stream recovers the full
    frame_steps (throughput); the chosen sizes surface in serve_stats."""
    model, params = tiny_model_params
    e = _engine(model, params, frame_steps=8, adaptive_frame_steps=True)
    rng = np.random.default_rng(3)

    def arrivals():
        for k in range(4):        # one arrival per poll: ewma ~ 1
            yield [(k, rng.integers(0, 200, (4,)).astype(np.int32))]

    got = dict(e.serve(arrivals(), max_new_tokens=48))
    assert len(got) == 4 and all(len(v) == 48 for v in got.values())
    hist = e.serve_stats["frame_steps_hist"]
    assert any(k < 8 for k in hist), hist      # shrank under arrivals
    assert 8 in hist, hist                     # recovered when drained
    assert e.serve_stats["frame_steps_last"] == 8
    # explicit frame_steps= pins the size even with the config flag on
    # (the same engine reuses its compiled {4, 8}-step programs)
    dict(e.serve(iter([[(9, rng.integers(0, 200, (4,)).astype(np.int32))]]),
                 max_new_tokens=8, frame_steps=4))
    assert set(e.serve_stats["frame_steps_hist"]) == {4}


def test_generate_degrades_to_stepwise_on_small_pool(tiny_model_params):
    """generate() with a KV pool too small for the compiled decode budget
    falls back to chunked step() serving instead of raising, and the tokens
    it does produce are the greedy prefix of the full-pool output."""
    model, params = tiny_model_params
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 200, (24,)).astype(np.int32)

    full = _engine(model, params).generate([prompt], max_new_tokens=32)[0]

    # trash + 3 blocks = 48 tokens: holds the 24-token prompt and some
    # decode, but not the 24 + 31 + 1 the compiled loop reserves up front
    small = _engine(model, params, num_kv_blocks=4)
    got = small.generate([prompt], max_new_tokens=32)[0]
    assert 0 < len(got) < 32                          # partial, no raise
    np.testing.assert_array_equal(got, full[:len(got)])
    small.flush(list(small.state.seqs))
    assert small.kv.free_blocks == small.kv.num_blocks - 1
