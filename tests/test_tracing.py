"""Distributed tracing + crash flight recorder suite (ISSUE 15).

Pins the tentpole contracts:

* ONE request = ONE connected span tree — shared trace id, exactly one
  root, intact parent chain (``tracing.validate_trace``) — across a
  scripted mid-stream kill/failover AND a prefill→decode handoff, with
  spans from BOTH replicas in the same tree;
* fleet-merged TTFT/E2E attribution: ``ds_fleet_ttft_ms`` records
  exactly ONE first-token sample per trace id, spanning handoff and
  failover (the PR-11 "record nothing on resumed spans" workaround is
  replaced; per-replica series stay resumed-blind);
* sampling: ``trace_sample_rate`` drops completed traces but faulted /
  shed / handed-off / failed-over / cancelled requests are ALWAYS kept;
* the flight recorder's bounded event ring, the postmortem bundle
  written on replica DEAD (killed replica's last-N events + every
  in-flight request's trace), and the Chrome-trace export shape;
* the ``dstpu_trace`` CLI renders an export and exits nonzero on a
  disconnected trace (the CI gate);
* cancel (client disconnect) and scheduler-shed requests still yield
  closed, connected, always-sampled traces.

Everything host-side at frame boundaries: under GRAFT_SANITIZE the
in-frame transfer guard runs over this whole suite (conftest lists it in
SERVING_SUITES) and must stay green — tracing adds zero device reads.
"""

import json
import socket
import threading
import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.faults import (RouterFaultInjector,
                                               snapshot_split)
from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier
from deepspeed_tpu.inference.v2.router import EngineRouter, RouterConfig
from deepspeed_tpu.inference.v2.tracing import (FlightRecorder,
                                                TraceCollector,
                                                validate_trace)
from deepspeed_tpu.models import build_model

BS, CHUNK, MAX_NEW = 16, 8, 8
RNG = np.random.default_rng(15)
PROMPTS = {u: RNG.integers(0, 200, (12,)).astype(np.int32)
           for u in range(8)}


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny", num_heads=8)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=BS, prefill_chunk_size=CHUNK,
              max_tokens_per_step=512, dtype="float32",
              max_ragged_batch_size=4, frame_steps=2,
              frame_retry_backoff_s=0.0)
    kw.update(over)
    return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                             params=params, max_seq_len=160)


def _assert_connected(trace):
    problems = validate_trace(trace["spans"])
    assert not problems, f"trace {trace['id']}: {problems}"


def _names(trace):
    return [s["name"] for s in trace["spans"]]


def _replicas_of(trace):
    return {s["replica"] for s in trace["spans"]} - {"router", "edge"}


# ---------------------------------------------------------------------------
# collector units (no engines)
# ---------------------------------------------------------------------------


def test_collector_bounds_sampling_and_validation():
    col = TraceCollector(sample_rate=0.0, max_traces=4,
                         max_spans_per_trace=3)
    # sample_rate=0: a plain completed trace is dropped...
    tid, root = col.mint("edge.recv", attrs={"uid": 1})
    col.note_first_token(tid, 0.5)
    col.note_done(tid, 1.0)
    col.finish(tid, status="ok")
    assert col.get(trace_id=tid) is None
    assert col.counters["traces_dropped"] == 1
    # ...but the fleet histograms recorded it anyway (attribution is
    # independent of span retention)
    assert col.fleet_ttft.total == 1
    assert col.fleet_e2e.total == 1
    # a MARKED trace survives sample_rate=0
    tid2, _ = col.mint("edge.recv", attrs={"uid": 2})
    col.mark(tid2, "fault")
    col.finish(tid2, status="poison_row")
    kept = col.get(trace_id=tid2)
    assert kept is not None and kept["status"] == "poison_row"
    # span budget: the 4th span of a 3-span-budget trace is refused
    tid3, r3 = col.mint("edge.recv")
    assert col.span(tid3, "a", 0.0, 1.0, parent=r3) is not None
    assert col.span(tid3, "b", 0.0, 1.0, parent=r3) is not None
    assert col.span(tid3, "c", 0.0, 1.0, parent=r3) is None
    assert col.counters["spans_truncated"] == 1
    # retention ring is bounded at max_traces
    for i in range(10):
        t, _ = col.mint("edge.recv")
        col.mark(t, "fault")
        col.finish(t, status="x")
    assert len(col.traces(include_open=False)) <= 4
    # validate_trace: orphan parents and double roots are named
    spans = [{"trace": "t", "sid": "s0", "parent": None, "name": "root"},
             {"trace": "t", "sid": "s1", "parent": "s9", "name": "leaf"}]
    assert any("orphan" in p for p in validate_trace(spans))
    spans[1]["parent"] = None
    assert any("root" in p for p in validate_trace(spans))
    assert validate_trace([]) == ["trace has no spans"]


def test_flight_recorder_ring_and_postmortem(tmp_path):
    col = TraceCollector()
    tid, _ = col.mint("edge.recv", attrs={"uid": 7})   # stays in flight
    fr = FlightRecorder(collector=col, max_events=4,
                        dump_dir=str(tmp_path))
    for i in range(8):
        fr.record("placement", replica="a", uid=i)
    assert len(fr.events) == 4                         # bounded ring
    assert fr.counters["events"] == 8
    assert not fr.dumps                                # nothing auto-dumped
    fr.record("replica_dead", replica="a", detail="strike budget")
    assert len(fr.dumps) == 1                          # auto-dump kind
    bundle = json.load(open(fr.dumps[0]))
    assert bundle["format"] == "dstpu-flight-bundle/1"
    assert bundle["reason"].startswith("replica_dead")
    assert any(e["kind"] == "replica_dead" for e in bundle["events"])
    # the in-flight request's trace rides the bundle
    assert [t["id"] for t in bundle["in_flight_traces"]] == [tid]
    assert "fleet_latency" in bundle


def test_chrome_export_shape():
    col = TraceCollector()
    tid, root = col.mint("edge.recv", replica="edge", t=1.0,
                         attrs={"uid": 3})
    col.span(tid, "engine.prefill", 1.1, 1.5, parent=root, replica="a")
    col.instant(tid, "emit", t=1.5, parent=root, replica="a")
    col.finish(tid, t=2.0, status="ok")
    doc = col.export_chrome()
    evs = doc["traceEvents"]
    procs = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert procs == {"edge", "a"}
    xs = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert {e["name"] for e in xs} == {"edge.recv", "engine.prefill"}
    assert [e["name"] for e in instants] == ["emit"]
    # µs relative to the earliest root
    pre = next(e for e in xs if e["name"] == "engine.prefill")
    assert pre["ts"] == pytest.approx(0.1e6)
    assert pre["dur"] == pytest.approx(0.4e6)
    # JSONL round-trips through validate_trace
    lines = [json.loads(ln) for ln in col.export_jsonl().splitlines()]
    assert not validate_trace(lines)


# ---------------------------------------------------------------------------
# single engine: tree shape, sampling of faulted/shed/cancelled requests
# ---------------------------------------------------------------------------


def test_single_engine_connected_trace(tiny_model_params):
    model, params = tiny_model_params
    eng = _engine(model, params)
    col = TraceCollector()
    eng.telemetry.set_tracer(col, replica="solo")
    out = dict(eng.serve(iter([[(u, PROMPTS[u]) for u in range(3)]]),
                         max_new_tokens=MAX_NEW))
    assert set(out) == {0, 1, 2}
    traces = col.traces()
    assert len(traces) == 3
    for t in traces:
        _assert_connected(t)
        assert not t["open"]
        assert t["status"] == "ok"
        names = _names(t)
        # tuple arrivals mint at the engine: root is engine.recv
        assert names[0] == "engine.recv"
        for want in ("engine.queue", "engine.prefill", "emit",
                     "engine.decode"):
            assert want in names, (want, names)
    snap = col.snapshot()
    assert snap["counters"]["ttft_samples"] == 3
    assert snap["counters"]["e2e_samples"] == 3
    assert snap["fleet_ttft_ms"]["count"] == 3
    # prometheus: the fleet-merged summaries + trace counters render
    text = col.render_prometheus()
    assert "ds_fleet_ttft_ms_count 3" in text
    assert "ds_fleet_e2e_ms_count 3" in text
    assert "ds_trace_traces_minted_total 3" in text


def test_cancel_and_shed_traces_always_sampled(tiny_model_params):
    """sample_rate=0 still keeps the traces worth debugging: a scheduler
    shed and a cancelled (deadline/disconnect path) request, each with a
    closed, connected trace carrying the terminal status."""
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    model, params = tiny_model_params
    eng = _engine(model, params)
    col = TraceCollector(sample_rate=0.0)
    eng.telemetry.set_tracer(col, replica="solo")
    sched = RequestScheduler(SchedulerConfig(tenant_max_queued=1))

    def arrivals():
        # same tenant, queue quota 1: the second submit sheds; the third
        # request expires by deadline before its first boundary admits it
        yield [{"uid": 0, "tokens": PROMPTS[0], "tenant": "t0"},
               {"uid": 1, "tokens": PROMPTS[1], "tenant": "t0"},
               {"uid": 2, "tokens": PROMPTS[2], "tenant": "t1",
                "deadline_ms": 1e-6}]

    out = dict(eng.serve(arrivals(), max_new_tokens=MAX_NEW,
                         scheduler=sched))
    assert set(out) == {0}
    traces = {t["uid"]: t for t in col.traces()}
    # uid 0 completed normally -> dropped at sample_rate=0
    assert 0 not in traces
    assert traces[1]["status"].startswith("shed:")
    assert "shed" in traces[1]["marks"]
    assert traces[2]["status"] in ("deadline_expired", "cancelled")
    for t in (traces[1], traces[2]):
        _assert_connected(t)
        assert not t["open"]
    # faulted/shed requests record no fleet E2E sample (mirrors the
    # per-replica histogram semantics)
    assert col.snapshot()["counters"]["e2e_samples"] == 1


# ---------------------------------------------------------------------------
# the tentpole: one connected trace across kill/failover and handoff
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_failover_one_connected_trace(tiny_model_params, tmp_path):
    """Scripted mid-stream kill (rejoin disabled => replica DEAD): the
    failed-over request's spans land on BOTH replicas under ONE trace id
    with an intact parent chain; fleet TTFT/E2E record exactly one
    sample per trace id; the postmortem bundle written on death holds
    the killed replica's events and the orphaned requests' traces."""
    model, params = tiny_model_params
    router = EngineRouter({"a": _engine(model, params),
                           "b": _engine(model, params)},
                          RouterConfig(rejoin=False))
    col, fr = router.attach_tracing(
        TraceCollector(), FlightRecorder(dump_dir=str(tmp_path)))
    faults = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 6, "engine": "a"}])
    out = dict(router.serve(iter([[(u, PROMPTS[u]) for u in range(6)]]),
                            max_new_tokens=48, faults=faults))
    assert faults.fired and len(out) == 6
    assert router.replica_status()["a"] == "dead"

    traces = col.traces()
    assert len(traces) == 6                 # ONE trace per request
    for t in traces:
        _assert_connected(t)
        assert not t["open"], f"trace {t['id']} never finished"
    crossed = [t for t in traces if len(_replicas_of(t)) > 1]
    assert crossed, "no trace spans both replicas after the failover"
    for t in crossed:
        assert "failover" in t["marks"]
        names = _names(t)
        assert "router.failover" in names
        # the continuation is a restore span on the peer, and the peer's
        # spans parent into the SAME tree (validated above)
        assert "engine.restore" in names
    # fleet-merged attribution: exactly one TTFT and one E2E per trace id
    snap = col.snapshot()
    assert snap["counters"]["ttft_samples"] == 6
    assert snap["counters"]["e2e_samples"] == 6
    # per-replica TTFT stays resumed-blind: total per-replica samples
    # equal fresh enqueues only (the failed-over request sampled once,
    # on its FIRST replica)
    per_replica = sum(
        r.engine.telemetry.hists["ttft"].total
        for r in router._replicas.values())
    assert per_replica == 6
    # postmortem bundle: written at death, carries the killed replica's
    # ring events and the then-in-flight requests' traces
    assert fr.dumps, "replica death wrote no bundle"
    bundle = json.load(open(fr.dumps[-1]))
    kinds = {e["kind"] for e in bundle["events"]}
    assert "engine_kill" in kinds and "replica_dead" in kinds
    assert any(e.get("replica") == "a" for e in bundle["events"])
    assert bundle["in_flight_traces"], "bundle lost the orphans' traces"
    for t in bundle["in_flight_traces"]:
        assert t["spans"], t


@pytest.mark.chaos
def test_handoff_one_connected_trace(tiny_model_params, tmp_path):
    """Disaggregated prefill→decode handoff: one connected trace across
    both roles, with the tier publish (prefill side) and the page
    restore (decode side) visible as spans, handoff always-sampled, and
    exactly one fleet TTFT sample (the prefill replica's first token)."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    pe = _engine(model, params, role="prefill", max_tokens_per_step=256)
    pe.attach_kv_tier(tier, tag="p")
    de = _engine(model, params, role="decode", max_tokens_per_step=256)
    de.attach_kv_tier(tier, tag="d")
    router = EngineRouter({"prefill0": pe, "decode0": de})
    col, fr = router.attach_tracing()
    long_p = RNG.integers(0, 200, (48,)).astype(np.int32)

    def arrivals():
        yield [{"uid": 0, "tokens": long_p, "max_new_tokens": 4},
               {"uid": 2, "tokens": PROMPTS[2], "max_new_tokens": MAX_NEW}]

    out = dict(router.serve(arrivals(), max_new_tokens=MAX_NEW))
    assert set(out) == {0, 2}
    assert router.counters["handoffs"] == 1
    traces = {t["uid"]: t for t in col.traces()}
    assert len(traces) == 2
    for t in traces.values():
        _assert_connected(t)
        assert not t["open"]
        assert t["status"] == "ok"
    ho = traces[0]
    assert "handoff" in ho["marks"]
    assert _replicas_of(ho) == {"prefill0", "decode0"}
    names = _names(ho)
    for want in ("router.ingest", "router.place", "engine.prefill",
                 "engine.handoff", "tier.publish", "kv.restore",
                 "engine.restore", "engine.decode"):
        assert want in names, (want, names)
    # one TTFT per TRACE: the prefill replica recorded it; the decode
    # replica's resumed first emission did not double-count
    snap = col.snapshot()
    assert snap["counters"]["ttft_samples"] == 2
    assert snap["counters"]["e2e_samples"] == 2
    # tier commits reached the flight ring
    assert any(e["kind"] == "tier_commit" for e in fr.events)
    assert any(e["kind"] == "handoff" for e in fr.events)


@pytest.mark.chaos
def test_disagg_handoff_plus_kill_chrome_export(tiny_model_params,
                                                tmp_path):
    """The acceptance scenario end to end: a disaggregated handoff AND a
    mid-stream kill/failover in ONE run — the handed-off request hops
    prefill0 → decode0 (handoff) → decode1 (failover), and the exported
    Chrome-trace JSON round-trips through the ``dstpu_trace`` loader
    with every request's spans sharing one trace id across ≥2 replicas
    and an intact parent chain."""
    model, params = tiny_model_params
    tier = KVSwapTier(str(tmp_path / "tier"), shared=True)
    engines = {}
    for name, role in (("prefill0", "prefill"), ("decode0", "decode"),
                       ("decode1", "decode")):
        e = _engine(model, params, role=role, max_tokens_per_step=256)
        e.attach_kv_tier(tier, tag=name)
        engines[name] = e
    router = EngineRouter(engines, RouterConfig(rejoin=False))
    col, fr = router.attach_tracing(
        TraceCollector(), FlightRecorder(dump_dir=str(tmp_path)))
    long_p = RNG.integers(0, 200, (48,)).astype(np.int32)

    def arrivals():
        # 48-token prompt, 12-token budget: prefill-heavy at the default
        # route ratio (48 >= 4 * 12), so the request handoffs first
        yield [{"uid": 0, "tokens": long_p, "max_new_tokens": 12,
                "session": "s0"}]

    # kill WHICHEVER decode replica the handoff lands on, a few ticks
    # into its decode: wrap the serial driver's _step so the kill keys
    # off the router's own assignment table (deterministic — the serial
    # tick clock and placement are), then let failover re-route
    killed = []
    state = {"owner": None, "owner_tick": None}
    orig_step = router._step

    def step_spy(r, tk, *a, **kw):
        owner = router._assignment.get(0)
        if state["owner"] is None and owner is not None \
                and router._roles[owner] != "prefill":
            state["owner"], state["owner_tick"] = owner, tk
        if state["owner"] is not None and not killed \
                and tk >= state["owner_tick"] + 3:
            if router._kill(state["owner"], tk, "scripted decode kill"):
                killed.append(state["owner"])
        return orig_step(r, tk, *a, **kw)

    router._step = step_spy
    out = dict(router.serve(arrivals(), max_new_tokens=12))
    assert set(out) == {0}
    assert killed, "the decode-side kill never fired"
    assert router.counters["handoffs"] >= 1
    assert router.counters["engine_kills"] == 1

    traces = col.traces()
    assert len(traces) == 1
    t = traces[0]
    _assert_connected(t)
    assert not t["open"] and t["status"] == "ok"
    assert {"handoff", "failover"} <= set(t["marks"])
    reps = _replicas_of(t)
    assert len(reps) >= 2 and "prefill0" in reps, reps
    # the acceptance artifact: Chrome JSON on disk, loaded back by the
    # CLI's parser, connected, spans on >= 2 replicas under ONE trace id
    export = tmp_path / "export.json"
    export.write_text(json.dumps(col.export_chrome()))
    cli = _load_cli()
    loaded = cli.load_spans(str(export))
    assert len(loaded) == 1
    (tid, spans), = loaded.items()
    assert not validate_trace(spans)
    span_reps = {s["replica"] for s in spans} - {"router", "edge"}
    assert len(span_reps) >= 2
    # the kill dumped a postmortem with the orphaned request's trace
    assert fr.dumps
    bundle = json.load(open(fr.dumps[-1]))
    assert any(tr["id"] == tid for tr in bundle["in_flight_traces"])
    # exactly one fleet TTFT/E2E sample across all three hops
    snap = col.snapshot()
    assert snap["counters"]["ttft_samples"] == 1
    assert snap["counters"]["e2e_samples"] == 1


# ---------------------------------------------------------------------------
# service edge: root at the edge, /debug/trace, disconnect trace
# ---------------------------------------------------------------------------


@pytest.mark.service
def test_edge_trace_debug_endpoint_and_disconnect(tiny_model_params):
    import http.client
    from deepspeed_tpu.inference.v2.service import (EdgeConfig, FleetDriver,
                                                    ServiceEdge)
    model, params = tiny_model_params
    router = EngineRouter({"a": _engine(model, params),
                           "b": _engine(model, params)})
    driver = FleetDriver(router)
    driver.start(max_new_tokens=MAX_NEW)
    edge = ServiceEdge(driver, EdgeConfig(keepalive_s=0.5)).start()
    try:
        body = {"prompt": [int(t) for t in PROMPTS[0]], "stream": False}
        conn = http.client.HTTPConnection("127.0.0.1", edge.edge_port,
                                          timeout=120)
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        uid = json.loads(resp.read())["uid"]
        # per-request lookup by uid, JSONL form -> connected, rooted at
        # the EDGE, spans from edge + router + one replica
        conn.request("GET", f"/debug/trace?uid={uid}&format=jsonl")
        spans = [json.loads(ln) for ln in
                 conn.getresponse().read().decode().splitlines()]
        assert not validate_trace(spans)
        root = next(s for s in spans if s["parent"] is None)
        assert root["name"] == "edge.recv" and root["replica"] == "edge"
        names = [s["name"] for s in spans]
        assert "edge.admit" in names and "router.place" in names
        # chrome form parses and carries the same trace
        conn.request("GET", f"/debug/trace?uid={uid}")
        chrome = json.loads(conn.getresponse().read())
        assert any(e.get("ph") == "X" for e in chrome["traceEvents"])
        # flight bundle over HTTP
        conn.request("GET", "/debug/flight")
        bundle = json.loads(conn.getresponse().read())
        assert bundle["format"] == "dstpu-flight-bundle/1"
        # /metrics carries the fleet-merged attribution series
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        assert "ds_fleet_ttft_ms_count 1" in text
        assert "ds_trace_traces_minted_total" in text
        assert "ds_flight_events_total" in text
        conn.close()

        # client disconnect mid-stream: the trace closes as a cancelled/
        # disconnect trace and stays sampled
        long_body = json.dumps({"prompt": [int(t) for t in PROMPTS[1]],
                                "max_new_tokens": 120}).encode()
        s = socket.create_connection(("127.0.0.1", edge.edge_port))
        s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Type: application/json\r\n"
                  + f"Content-Length: {len(long_body)}\r\n\r\n".encode()
                  + long_body)
        buf = b""
        while b"event: token" not in buf:
            chunk = s.recv(4096)
            assert chunk, f"stream ended early: {buf!r}"
            buf += chunk
        s.close()
        deadline = time.monotonic() + 60
        tr = None
        while time.monotonic() < deadline:
            tr = edge.tracer.get(uid=2)
            if tr is not None and not tr["open"]:
                break
            time.sleep(0.05)
        assert tr is not None and not tr["open"], tr
        assert not validate_trace(tr["spans"])
        assert ("disconnect" in tr["marks"]) or ("cancelled" in tr["marks"])
    finally:
        edge.shutdown()
        driver.stop()


# ---------------------------------------------------------------------------
# the CLI gate
# ---------------------------------------------------------------------------


def _load_cli():
    import importlib.machinery
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bin", "dstpu_trace")
    loader = importlib.machinery.SourceFileLoader("dstpu_trace_cli", path)
    spec = importlib.util.spec_from_loader("dstpu_trace_cli", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def test_dstpu_trace_cli_gate(tmp_path, monkeypatch, capsys):
    """The ASCII-timeline CLI is a parity-style gate: exit 0 + lanes on a
    connected export, exit 1 naming the orphan on a broken one. Exercised
    in-process (the script is import-safe) on both chrome and JSONL
    inputs."""
    cli = _load_cli()
    col = TraceCollector()
    tid, root = col.mint("edge.recv", replica="edge", t=0.0,
                         attrs={"uid": 5})
    col.span(tid, "engine.prefill", 0.1, 0.5, parent=root, replica="a")
    col.span(tid, "engine.decode", 0.5, 0.9, parent=root, replica="b")
    col.finish(tid, t=1.0, status="ok")
    good_chrome = tmp_path / "good.json"
    good_chrome.write_text(json.dumps(col.export_chrome()))
    good_jsonl = tmp_path / "good.jsonl"
    good_jsonl.write_text(col.export_jsonl())

    monkeypatch.setattr("sys.argv", ["dstpu_trace", str(good_chrome)])
    assert cli.main() == 0
    out = capsys.readouterr().out
    assert "all connected" in out
    assert "edge" in out and "engine.prefill" in out     # lanes rendered
    monkeypatch.setattr("sys.argv",
                        ["dstpu_trace", str(good_jsonl), "--uid", "5"])
    assert cli.main() == 0
    capsys.readouterr()

    # break the parent chain -> nonzero exit naming the orphan
    broken = [dict(s) for s in col.get(trace_id=tid)["spans"]]
    broken[1]["parent"] = "s777"
    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join(json.dumps(s) for s in broken) + "\n")
    monkeypatch.setattr("sys.argv", ["dstpu_trace", str(bad), "--check"])
    assert cli.main() == 1
    err = capsys.readouterr().err
    assert "DISCONNECTED" in err and "s777" in err


# ---------------------------------------------------------------------------
# snapshot round trip: the trace context survives serialization
# ---------------------------------------------------------------------------


def test_trace_context_survives_snapshot_split(tiny_model_params):
    model, params = tiny_model_params
    eng = _engine(model, params)
    col = TraceCollector()
    eng.telemetry.set_tracer(col, replica="solo")
    gen = eng.serve(iter([[(0, PROMPTS[0], 64)]]), max_new_tokens=64,
                    yield_boundaries=True)
    for ev in gen:
        if not isinstance(ev, tuple) and ev.dispatched:
            break                      # a live frame ran; ledger populated
    snap = eng.snapshot_serving_state()
    gen.close()
    assert json.loads(json.dumps(snap)) == snap   # JSON-serializable
    items = snapshot_split(snap)
    assert len(items) == 1
    tr = items[0]["trace"]
    assert tr is not None and tr["id"] in {t["id"] for t in col.traces()}
    assert tr["parent"] == "s0"
