"""GL204 fixtures: redundant-collective shapes inside a shard_map manual
region — wire bytes spent on values one collective already computes.

- ``dup_psum``           — the identical operand all-reduced twice on the
  same axis (a refactor that left both the helper's psum and the caller's);
- ``double_reduce``      — a psum applied to a psum's output: the value is
  already replica-invariant, so the second reduce silently multiplies by N;
- ``gather_then_reduce`` — an all-gather whose result is summed straight
  back down ((N-1)x the bytes of the psum computing the same thing — the
  shape the pre-ring quantized all-reduce had);
- ``clean``              — a single psum plus a LEGITIMATE gather (consumed
  whole) that must not trip any of the above.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))


def _program(name, fn, out_specs=P()):
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram
    mapped = shard_map(fn, mesh=_mesh(), in_specs=P("tp"),
                       out_specs=out_specs, check_rep=False)

    def trace():
        return jax.make_jaxpr(mapped)(jnp.ones((8, 4), jnp.float32))

    return TracedProgram(name=name, trace=trace, retrace=trace)


def dup_psum():
    def body(x):
        a = jax.lax.psum(x, "tp")
        b = jax.lax.psum(x, "tp")     # identical reduce, second wire trip
        return a + b
    return _program("fixture:dup_psum", body)


def double_reduce():
    def body(x):
        y = jax.lax.psum(x, "tp")
        return jax.lax.psum(y, "tp")  # already invariant: multiplies by N
    return _program("fixture:double_reduce", body)


def gather_then_reduce():
    def body(x):
        g = jax.lax.all_gather(x, "tp")          # (tp, ...) per shard
        return jnp.sum(g.astype(jnp.float32), axis=0)
    return _program("fixture:gather_then_reduce", body)


def clean():
    def body(x):
        red = jax.lax.psum(x, "tp")
        g = jax.lax.all_gather(x, "tp")          # consumed whole: fine
        return red + g.reshape(-1)[: x.shape[0] * x.shape[1]].reshape(x.shape)
    return _program("fixture:clean_cost", body)
