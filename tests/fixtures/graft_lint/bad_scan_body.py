"""GL001 fixture: a serving-style scan loop whose body calls
``jax.debug.print`` — a ``debug_callback`` host-sync primitive that would
fire EVERY step of every frame. The real scan bodies
(``model_runner._serving_scan_body``) must never contain one; this file is
what the TransferGuard check looks like when they do."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("steps",))
def bad_loop(carry, steps):
    def body(c, _):
        jax.debug.print("tok={}", c[0])   # the violation
        return c + 1, c
    carry, toks = jax.lax.scan(body, carry, None, length=steps)
    return carry, toks


def make_program():
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram
    arr = jnp.zeros((4,), jnp.int32)

    def trace():
        return bad_loop.trace(arr, steps=3)

    return TracedProgram(name="fixture:bad_scan_body", trace=trace,
                         retrace=trace, donate_argnums=(0,))
