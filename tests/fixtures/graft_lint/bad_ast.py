"""Family B fixture: one jitted function committing every AST-lintable
retrace hazard. ``tests/test_static_analysis.py`` golden-matches the
findings against the ``# expect: GLxxx`` markers, so rule drift shows up
as a diff here, not as silence.

This file is NEVER imported (np.zeros on a tracer would raise) — it is
parsed only.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("flag",))
def bad_jit(x, y, flag):
    if x.sum() > 0:                           # expect: GL101
        y = y + 1
    while y.any():                            # expect: GL101
        y = y - 1
    z = float(x)                              # expect: GL104
    w = np.zeros((4,))                        # expect: GL104
    v = jnp.zeros((4,), dtype=np.float64)     # expect: GL103
    print("tracing", flag)                    # expect: GL105
    u = int(y)  # graft-lint: disable=GL104 -- fixture: suppression must hold
    if flag:                                  # static arg: must NOT flag
        z = z + 1
    return z + w.sum() + v.sum() + u


def caller():
    return bad_jit(jnp.ones(3), jnp.ones(3), flag=[1, 2])   # expect: GL102
