"""GL003 fixtures: the three collective-structure failure modes inside a
``shard_map`` manual region.

- ``wrong_axis``   — a psum naming an axis no mesh defines (the classic
  copy-paste from a 2-D training mesh into the 1-D serving mesh);
- ``bad_ring``     — a ppermute whose perm double-delivers to one shard
  (a ring exchange built from it silently loses a chunk);
- ``leaky_output`` — an output DECLARED replicated that actually varies by
  shard (``axis_index`` reaches it with no collective in between). The
  frame loops compile with ``check_rep=False``, so only this static pass
  would catch it.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("tp",))


def _program(name, fn, out_specs):
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram
    mesh = _mesh()
    mapped = shard_map(fn, mesh=mesh, in_specs=P("tp"), out_specs=out_specs,
                       check_rep=False)

    def trace():
        return jax.make_jaxpr(mapped)(jnp.ones((8, 4), jnp.float32))

    return TracedProgram(name=name, trace=trace, retrace=trace)


def wrong_axis():
    def body(x):
        return jax.lax.psum(x, "dp")      # no mesh defines 'dp'
    return _program("fixture:wrong_axis_psum", body, P("tp"))


def bad_ring():
    def body(x):
        perm = [(0, 1), (1, 0), (2, 0)]   # shard 0 receives twice, 2 never
        return jax.lax.ppermute(x, "tp", perm)
    return _program("fixture:bad_ring_ppermute", body, P("tp"))


def leaky_output():
    def body(x):
        # shard-varying value flows to an output declared replicated —
        # each replica silently holds a different "replicated" result
        return jnp.sum(x) + jax.lax.axis_index("tp").astype(jnp.float32)
    return _program("fixture:leaky_replicated_output", body, P())


def clean():
    """The well-formed counterpart: psum makes the output genuinely
    replica-invariant, so the taint pass must stay silent."""
    def body(x):
        return jax.lax.psum(jnp.sum(x), "tp")
    return _program("fixture:clean_psum", body, P())
