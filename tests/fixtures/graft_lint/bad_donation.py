"""GL002 fixture (jaxpr half): a jit that donates a pool whose aval
matches NO output — XLA can never reuse the buffer, so the donation buys
nothing and the caller has still surrendered its reference. The serving
loops donate 10-13 carries each; every one must round-trip through the
outputs."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def bad_donate(pool, x):
    return jnp.sum(pool) + x      # (8, 8) donated, only scalars returned


def make_program():
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram

    def trace():
        return bad_donate.trace(jnp.zeros((8, 8), jnp.float32),
                                jnp.zeros((), jnp.float32))

    return TracedProgram(name="fixture:bad_donation", trace=trace,
                         retrace=trace, donate_argnums=(0,))


#: the AST half of GL002 — a dispatch that donates ``self.kv.k`` but keeps
#: decoding from the stale reference (check_donation_sites flags the call
#: because the donated argument is not among the assignment targets)
BAD_DISPATCH_SRC = '''\
def dispatch(self, runner, params):
    toks, emit, new_k = runner.frame_loop(params, self.kv.k)
    return toks, emit, self.kv.k      # reads the donated (dead) buffer
'''
