"""GL004 fixture: an entry point whose trace depends on trace-time state —
every trace with the SAME bucket-compatible shapes yields a different
jaxpr, so in production the jit cache misses on every call and the frame
pays a full retrace. The counter stands in for real offenders: fresh
closures per call, dict/set iteration order, "just read the wall clock
once" constants."""

import jax
import jax.numpy as jnp

_TRACES = [0]


def make_program():
    from deepspeed_tpu.analysis.jaxpr_checks import TracedProgram

    def build():
        @jax.jit
        def f(x):
            _TRACES[0] += 1
            if _TRACES[0] % 2:            # trace-time state leaks in
                return x * 2.0
            return x + 1.0
        return f

    def trace():
        return build().trace(jnp.zeros((4,), jnp.float32))

    return TracedProgram(name="fixture:bad_retrace", trace=trace,
                         retrace=trace)
