"""Config system tests. Models reference tests/unit/runtime/test_ds_config_dict.py."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig


def test_batch_resolution_all_given():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2},
        world_size=8)
    assert cfg.train_batch_size == 32


def test_batch_resolution_infer_gas():
    cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2}, world_size=8)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_resolution_infer_micro():
    cfg = DeepSpeedConfig({"train_batch_size": 32, "gradient_accumulation_steps": 2}, world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_batch_resolution_infer_train():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2}, world_size=8)
    assert cfg.train_batch_size == 32


def test_batch_mismatch_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            {"train_batch_size": 33, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2},
            world_size=8)


def test_no_batch_info_raises():
    with pytest.raises(ValueError):
        DeepSpeedConfig({}, world_size=8)


def test_zero_config_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 8})
    assert cfg.zero_config.stage == 0
    assert not cfg.zero_enabled


def test_zero_stage3_aliases():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 3,
            "stage3_prefetch_bucket_size": 1000,
            "stage3_max_live_parameters": 123,
            "offload_optimizer": {"device": "cpu"},
        }
    })
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.prefetch_bucket_size == 1000
    assert cfg.zero_config.max_live_parameters == 123
    assert cfg.zero_config.offload_optimizer.device == "cpu"
    assert cfg.zero_config.overlap_comm  # defaults True at stage 3


def test_fp16_bf16_exclusive():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_precision_dtype():
    import jax.numpy as jnp
    assert DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True}}).precision_dtype == jnp.bfloat16
    assert DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True}}).precision_dtype == jnp.float16
    assert DeepSpeedConfig({"train_batch_size": 8}).precision_dtype == jnp.float32


def test_duplicate_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p))


def test_json_string_config():
    cfg = DeepSpeedConfig('{"train_batch_size": 16}', world_size=8)
    assert cfg.train_batch_size == 16


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 100}},
    })
    assert cfg.optimizer.type == "AdamW"
    assert cfg.optimizer.params["lr"] == 3e-4
    assert cfg.scheduler.type == "WarmupLR"


def test_mesh_block():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "mesh": {"tensor": 4, "pipe": 2}})
    assert cfg.mesh.tensor == 4
    assert cfg.mesh.pipe == 2
