"""Test harness configuration.

Analog of the reference's ``tests/unit/common.py`` DistributedTest pattern:
multi-chip logic is tested on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4's TPU-build
implication) — ZeRO/pipeline/MoE/SP collectives execute for real across 8
simulated devices in one process.
"""

import os

# Must run before any backend is initialized. The axon sitecustomize imports
# jax at interpreter start with JAX_PLATFORMS=axon, so the env var is already
# latched — jax.config.update is the reliable override.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: anything wall-clock-sensitive (telemetry
    # latency-value assertions, benchmarks) carries this marker so the
    # deterministic CPU suite never flakes on timing
    config.addinivalue_line(
        "markers", "slow: wall-clock-sensitive or long-running; excluded "
        "from the tier-1 CPU suite (-m 'not slow')")
    # chaos tests are deterministic (scripted FaultInjector schedules, no
    # randomness, no wall-clock assertions) and run IN tier-1: fault
    # handling that is only exercised nightly is fault handling that rots
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection serving tests "
        "(tests/test_serving_faults.py); included in tier-1")
    # multichip tests run on the virtual 8-device CPU mesh this conftest
    # already forces (--xla_force_host_platform_device_count=8), so they are
    # tier-1-safe by construction and run in every PR; the marker exists so
    # `-m multichip` can run the sharded-serving suite focused (the verify
    # skill's forced-8-device job line)
    config.addinivalue_line(
        "markers", "multichip: exercises a multi-device mesh (virtual on "
        "CPU); tier-1-safe, selectable with -m multichip")


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test starts with a fresh (unset) global mesh."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    yield
    groups.reset_mesh()


@pytest.fixture
def mesh_8dp():
    from deepspeed_tpu.utils import groups
    return groups.set_mesh(groups.build_mesh(data=8))


@pytest.fixture
def mesh_2x4():
    """2-way data x 4-way tensor."""
    from deepspeed_tpu.utils import groups
    return groups.set_mesh(groups.build_mesh(data=2, tensor=4))


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
