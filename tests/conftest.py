"""Test harness configuration.

Analog of the reference's ``tests/unit/common.py`` DistributedTest pattern:
multi-chip logic is tested on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4's TPU-build
implication) — ZeRO/pipeline/MoE/SP collectives execute for real across 8
simulated devices in one process.
"""

import os

# Must run before any backend is initialized. The axon sitecustomize imports
# jax at interpreter start with JAX_PLATFORMS=axon, so the env var is already
# latched — jax.config.update is the reliable override.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# GRAFT_SANITIZE=1 arms the dynamic sanitizers (see "sanitizer mode"
# below): in-frame transfer guards on every serving test, strict rank
# promotion, NaN debugging on non-fault suites, and per-suite retrace
# budgets. Off by default so tier-1 timing is untouched.
SANITIZE = os.environ.get("GRAFT_SANITIZE", "0") == "1"

#: test modules that drive the frame serving loops — the suites the
#: sanitizer applies the in-frame transfer guard and retrace budget to
SERVING_SUITES = ("test_frame_serving", "test_serving_telemetry",
                  "test_serving_scheduler", "test_serving_faults",
                  "test_serving_tp", "test_kv_hierarchy", "test_router",
                  "test_disagg", "test_service", "test_tracing",
                  "test_quantized_serving")

#: fault-injection suites intentionally produce NaN logits (poison rows):
#: jax_debug_nans would abort the machinery under test
NAN_SUITES = ("test_serving_faults", "test_kv_hierarchy")

#: per-suite ceiling on compiled programs PER RUNNER (compile_count_total —
#: the monotonic recompile counter). Generous vs the handful of shape
#: buckets a healthy suite compiles; a retrace-per-frame bug blows past it
#: immediately. The static twin is graft-lint rule GL004.
RETRACE_BUDGET = {"default": 64}


def guard_frame_dispatch(monkeypatch):
    """THE single definition of "in-frame": wrap
    ``DeviceSlotTable.dispatch_frame`` in a device->host transfer guard.
    Everything outside it (admission, absorb, stats_delta, quarantine
    reads) is frame-BOUNDARY work and stays unguarded. Shared by the
    ``frame_transfer_guard`` fixture (the dedicated per-suite guard tests)
    and the GRAFT_SANITIZE=1 blanket mode, so the dynamic guard and the
    static TransferGuard check (graft-lint GL001) agree on scope."""
    from deepspeed_tpu.inference.v2.ragged_manager import DeviceSlotTable
    orig = DeviceSlotTable.dispatch_frame

    def guarded(self, *a, **kw):
        with jax.transfer_guard_device_to_host("disallow"):
            return orig(self, *a, **kw)

    monkeypatch.setattr(DeviceSlotTable, "dispatch_frame", guarded)


@pytest.fixture
def frame_transfer_guard(monkeypatch):
    """Opt-in fixture: the serving suites' zero-in-frame-transfer
    acceptance tests request this instead of re-defining the guard."""
    guard_frame_dispatch(monkeypatch)


@pytest.fixture(autouse=True)
def _sanitize(request, monkeypatch):
    """Sanitizer mode (GRAFT_SANITIZE=1): every serving test runs under
    the in-frame transfer guard, everything runs with strict rank
    promotion, and non-fault tests run with jax_debug_nans — the dynamic
    complements of graft-lint GL001/GL103 and the finite-check."""
    if not SANITIZE:
        yield
        return
    module = request.node.module.__name__.rsplit(".", 1)[-1]
    if module not in SERVING_SUITES:
        # the sanitizers police the SERVING stack's invariants; the
        # training/ops suites have their own (looser) broadcasting idiom
        yield
        return
    guard_frame_dispatch(monkeypatch)
    prev_rank = jax.config.jax_numpy_rank_promotion
    jax.config.update("jax_numpy_rank_promotion", "raise")
    prev_nans = jax.config.jax_debug_nans
    if module not in NAN_SUITES:
        jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_numpy_rank_promotion", prev_rank)
        jax.config.update("jax_debug_nans", prev_nans)


@pytest.fixture(autouse=True, scope="module")
def _retrace_budget(request):
    """Sanitizer mode: assert a per-suite retrace budget over every
    PagedModelRunner the module creates, via the monotonic
    ``compile_count_total()``. Catches the silent perf cliff (a retrace
    per serve() call) that per-test recompile assertions can miss when
    the engine is module-scoped."""
    module = request.node.name.rsplit("/", 1)[-1].rsplit(".", 1)[0]
    if not SANITIZE or module not in SERVING_SUITES:
        yield
        return
    from deepspeed_tpu.inference.v2.model_runner import PagedModelRunner
    runners = []
    orig_init = PagedModelRunner.__init__

    def tracking_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        runners.append(self)

    PagedModelRunner.__init__ = tracking_init
    try:
        yield
    finally:
        PagedModelRunner.__init__ = orig_init
        budget = RETRACE_BUDGET.get(module, RETRACE_BUDGET["default"])
        over = [(r, r.compile_count_total()) for r in runners
                if r.compile_count_total() > budget]
        assert not over, (
            f"{module}: retrace budget exceeded — "
            + ", ".join(f"runner compiled {n} programs (budget {budget}): "
                        f"{r.compile_count()}" for r, n in over))


def pytest_sessionfinish(session, exitstatus):
    """Sanitizer mode: print the graft-cost delta vs the committed
    baseline at session teardown, next to the per-suite retrace budgets —
    the dynamic session ends with the static ledger's verdict on the
    programs it just exercised. Only runs when a serving/analysis suite
    was collected (the tracing costs ~15s; a config-only run shouldn't
    pay it)."""
    if not SANITIZE:
        return
    suites = SERVING_SUITES + ("test_static_analysis", "test_cost_model")
    items = getattr(session, "items", []) or []
    if not any(it.nodeid.rsplit("/", 1)[-1].split(".py")[0] in suites
               for it in items):
        return
    try:
        import logging
        logging.getLogger("DeepSpeedTPU").setLevel(logging.ERROR)
        from deepspeed_tpu.analysis.cost_model import (load_cost_baseline,
                                                       run_cost_checks)
        from deepspeed_tpu.analysis.programs import build_cost_programs
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        baseline = load_cost_baseline(
            os.path.join(root, ".graft-cost-baseline.json"))
        findings, reports = run_cost_checks(build_cost_programs(),
                                            baseline=baseline)
        drift = [f for f in findings if f.rule == "GL201"]
        if drift:
            print(f"\n[graft-sanitize] cost-report delta: {len(drift)} "
                  "metric(s) off baseline:")
            for f in drift:
                print(f"[graft-sanitize]   {f.render()}")
        else:
            print(f"\n[graft-sanitize] cost report matches baseline "
                  f"({len(reports)} programs; retrace budgets above)")
        other = [f for f in findings if f.rule != "GL201"]
        for f in other:
            print(f"[graft-sanitize]   {f.render()}")
    except Exception as e:   # noqa: BLE001 — teardown must never mask results
        print(f"\n[graft-sanitize] cost-report delta unavailable: "
              f"{type(e).__name__}: {e}")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: anything wall-clock-sensitive (telemetry
    # latency-value assertions, benchmarks) carries this marker so the
    # deterministic CPU suite never flakes on timing
    config.addinivalue_line(
        "markers", "slow: wall-clock-sensitive or long-running; excluded "
        "from the tier-1 CPU suite (-m 'not slow')")
    # chaos tests are deterministic (scripted FaultInjector schedules, no
    # randomness, no wall-clock assertions) and run IN tier-1: fault
    # handling that is only exercised nightly is fault handling that rots
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection serving tests "
        "(tests/test_serving_faults.py); included in tier-1")
    # multichip tests run on the virtual 8-device CPU mesh this conftest
    # already forces (--xla_force_host_platform_device_count=8), so they are
    # tier-1-safe by construction and run in every PR; the marker exists so
    # `-m multichip` can run the sharded-serving suite focused (the verify
    # skill's forced-8-device job line)
    config.addinivalue_line(
        "markers", "multichip: exercises a multi-device mesh (virtual on "
        "CPU); tier-1-safe, selectable with -m multichip")
    # service-edge tests (tests/test_service.py) drive the thread-per-
    # replica fleet driver and the HTTP/SSE front-end on loopback; they
    # poll outcomes with generous deadlines (never assert on timing), so
    # they are tier-1-safe and run in every PR
    config.addinivalue_line(
        "markers", "service: thread-per-replica fleet driver + HTTP/SSE "
        "service-edge tests; included in tier-1, selectable with "
        "-m service")


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test starts with a fresh (unset) global mesh."""
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()
    yield
    groups.reset_mesh()


@pytest.fixture
def mesh_8dp():
    from deepspeed_tpu.utils import groups
    return groups.set_mesh(groups.build_mesh(data=8))


@pytest.fixture
def mesh_2x4():
    """2-way data x 4-way tensor."""
    from deepspeed_tpu.utils import groups
    return groups.set_mesh(groups.build_mesh(data=2, tensor=4))


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
