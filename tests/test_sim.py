"""Fleet-simulator suite (ISSUE 18): deterministic replay, snapshot /
resume, real-policy pinning, capacity answers, cost calibration.

Pins the tentpole contracts:

* twin runs of the same (config, trace) produce a BYTE-IDENTICAL event
  log (the determinism root — ``SimResult.checkpoint`` is its sha256);
* ``run(resume_checkpoint=...)`` re-derives the run and verifies the
  barrier digest; a tampered checkpoint raises instead of silently
  diverging;
* the sim drives the REAL policy objects — ``EngineRouter._place``,
  ``RequestScheduler.pick``, ``ServiceEdge.admission_check``,
  ``AutoscaleController.on_tick`` all execute (call-counted via
  monkeypatch) while ZERO device frames dispatch;
* a capacity question (smallest fleet meeting a TTFT SLO) answers in
  seconds of wall time;
* traces round-trip through ``save_trace``/``load_trace``;
* deliberate overload sheds at the EDGE (admission math, not engine
  starvation);
* ``tune`` emits a version-1 serve-config ``bin/dstpu_serve --config``
  can overlay;
* ``calibrate_from_boundaries`` fits per-ledger-program pairs and
  round-trips through JSON.
"""

import json
import time

import pytest

from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.router import EngineRouter
from deepspeed_tpu.inference.v2.scheduler import RequestScheduler
from deepspeed_tpu.inference.v2.service.autoscale import (AutoscaleConfig,
                                                          AutoscaleController)
from deepspeed_tpu.inference.v2.service.edge import EdgeConfig, ServiceEdge
from deepspeed_tpu.inference.v2.sim import (CostCalibration, FleetSimulator,
                                            FrameCostModel, SimConfig,
                                            load_trace, save_trace,
                                            synth_trace)
from deepspeed_tpu.inference.v2.sim.cost import (calibrate_from_boundaries,
                                                 fit_calibration,
                                                 load_calibration,
                                                 save_calibration)
from deepspeed_tpu.inference.v2.sim.tune import sweep_capacity, tune


def small_cfg(**kw):
    engine = kw.pop("engine", None) or RaggedInferenceEngineConfig(
        max_ragged_batch_size=8, frame_steps=8, prefill_chunk_size=64)
    return SimConfig(replicas=kw.pop("replicas", 2), engine=engine, **kw)


def small_trace(seed=3, rate=8.0, duration_s=6.0, profile="poisson"):
    return synth_trace(profile, rate=rate, duration_s=duration_s,
                       seed=seed, sessions=2)


# ---------------------------------------------------------------------
# determinism + snapshot/resume
# ---------------------------------------------------------------------

def test_event_log_byte_identical_across_runs():
    trace = small_trace()
    r1 = FleetSimulator(small_cfg()).run(trace)
    r2 = FleetSimulator(small_cfg()).run(trace)
    assert r1.completed == len(trace)
    assert r1.event_lines() == r2.event_lines()
    assert r1.checkpoint == r2.checkpoint
    assert r1.checkpoint["events"] == len(r1.events)


def test_profiles_are_seed_deterministic_and_distinct():
    for profile in ("poisson", "diurnal", "bursty", "heavy_tail"):
        a = synth_trace(profile, rate=6.0, duration_s=5.0, seed=7)
        b = synth_trace(profile, rate=6.0, duration_s=5.0, seed=7)
        assert a == b, profile
        c = synth_trace(profile, rate=6.0, duration_s=5.0, seed=8)
        assert a != c, profile


def test_snapshot_resume_reproduces_the_run():
    trace = small_trace()
    full = FleetSimulator(small_cfg()).run(trace)
    half = FleetSimulator(small_cfg()).run(
        trace, stop_after_events=len(full.events) // 2)
    assert half.checkpoint["events"] <= len(full.events)
    resumed = FleetSimulator(small_cfg()).run(
        trace, resume_checkpoint=half.checkpoint)
    assert resumed.event_lines() == full.event_lines()


def test_resume_from_diverged_checkpoint_raises():
    trace = small_trace()
    half = FleetSimulator(small_cfg()).run(trace, stop_after_events=20)
    bad = dict(half.checkpoint, sha256="0" * 64)
    with pytest.raises(RuntimeError, match="sha|barrier|diverg"):
        FleetSimulator(small_cfg()).run(trace, resume_checkpoint=bad)


# ---------------------------------------------------------------------
# the REAL policy stack runs; zero real frames dispatch
# ---------------------------------------------------------------------

def test_real_policy_objects_execute_and_no_frames_dispatch(monkeypatch):
    calls = {"place": 0, "pick": 0, "edge": 0, "tick": 0}

    orig_place = EngineRouter._place
    orig_pick = RequestScheduler.pick
    orig_edge = ServiceEdge.admission_check
    orig_tick = AutoscaleController.on_tick

    def count(key, orig):
        def wrapper(self, *a, **kw):
            calls[key] += 1
            return orig(self, *a, **kw)
        return wrapper

    monkeypatch.setattr(EngineRouter, "_place", count("place", orig_place))
    monkeypatch.setattr(RequestScheduler, "pick", count("pick", orig_pick))
    monkeypatch.setattr(ServiceEdge, "admission_check",
                        count("edge", orig_edge))
    monkeypatch.setattr(AutoscaleController, "on_tick",
                        count("tick", orig_tick))

    from deepspeed_tpu.inference.v2 import ragged_manager

    def no_dispatch(self, *a, **kw):
        raise AssertionError("the simulator dispatched a REAL frame")

    monkeypatch.setattr(ragged_manager.DeviceSlotTable, "run_frame",
                        no_dispatch)

    trace = small_trace()
    cfg = small_cfg(autoscale=AutoscaleConfig(),
                    edge=EdgeConfig(max_queued_tokens=100_000, trace=False))
    res = FleetSimulator(cfg).run(trace)
    assert res.completed == len(trace)
    assert res.virtual_frames > 0
    for key, n in calls.items():
        assert n > 0, f"policy hook {key} never executed"


# ---------------------------------------------------------------------
# capacity questions
# ---------------------------------------------------------------------

def test_capacity_sweep_answers_in_seconds():
    trace = small_trace(rate=12.0, duration_s=6.0)
    t0 = time.perf_counter()
    out = sweep_capacity(trace, small_cfg(), replica_counts=(1, 2, 4),
                         slo_ttft_p90_ms=10_000.0)
    wall = time.perf_counter() - t0
    assert wall < 30.0, f"capacity sweep took {wall:.1f}s"
    assert [r["replicas"] for r in out["rows"]] == [1, 2, 4]
    assert out["min_replicas_for_slo"] is not None
    for row in out["rows"]:
        assert row["completed"] == len(trace)


def test_trace_round_trip(tmp_path):
    trace = small_trace(profile="bursty")
    path = str(tmp_path / "workload.jsonl")
    save_trace(path, trace)
    assert load_trace(path) == trace


def test_edge_sheds_under_deliberate_pressure():
    # a one-replica fleet priced 100x slower than reality, fed 4x the
    # traffic, behind an edge allowing almost no queued prompt tokens:
    # the REAL admission math must shed at the EDGE
    cfg = small_cfg(
        replicas=1,
        engine=RaggedInferenceEngineConfig(
            max_ragged_batch_size=2, frame_steps=8, prefill_chunk_size=64),
        edge=EdgeConfig(max_queued_tokens=64, trace=False),
        calibration=CostCalibration(c0=0.25, k=1.0))
    trace = small_trace(rate=30.0, duration_s=4.0)
    res = FleetSimulator(cfg).run(trace)
    sheds = sum(1 for line in res.event_lines()
                if json.loads(line)["kind"] == "edge_shed")
    assert sheds > 0, "edge admission never shed under overload"


def test_tune_emits_loadable_serve_config(tmp_path):
    trace = small_trace(rate=6.0, duration_s=4.0)
    space = {"frame_steps": (4, 8), "prefill_chunk_size": (64,),
             "speculate_gamma": (0,), "max_ragged_batch_size": (8,)}
    best, rows = tune(trace, small_cfg(), space=space, mode="grid")
    assert best["version"] == 1
    assert rows and rows[0]["score"] == best["score"]
    # the exact gate bin/dstpu_serve --config applies before overlaying
    path = tmp_path / "serve.json"
    path.write_text(json.dumps(best))
    tuned = json.loads(path.read_text())
    assert tuned["version"] == 1
    for key in ("frame_steps", "prefill_chunk_size", "speculate_gamma",
                "max_ragged_batch_size"):
        assert key in tuned["engine"]
    assert "lookahead_reserve" in tuned["scheduler"]
    assert "max_queued_tokens" in tuned["edge"]


# ---------------------------------------------------------------------
# cost calibration
# ---------------------------------------------------------------------

def test_fit_calibration_recovers_affine_and_rejects_degenerate():
    fit = fit_calibration([(1.0, 0.011), (2.0, 0.021), (3.0, 0.031)])
    assert fit.c0 == pytest.approx(0.001, abs=1e-6)
    assert fit.k == pytest.approx(0.01, abs=1e-6)
    # one distinct work value -> no slope information -> defaults
    degenerate = fit_calibration([(1.0, 0.01), (1.0, 0.03)])
    assert (degenerate.c0, degenerate.k) == (CostCalibration().c0,
                                             CostCalibration().k)


def test_calibrate_from_boundaries_fits_per_program(tmp_path):
    model = FrameCostModel()
    # two frame shapes with dt far apart relative to their ledger work
    # gap — exactly the regime one global affine cannot represent
    samples = (
        [{"dt": 0.002, "steps": 4, "live": 1, "n_slots": 8, "width": 1}] * 8
        + [{"dt": 0.020, "steps": 4, "live": 1, "n_slots": 8,
            "width": 8}] * 8)
    cal = calibrate_from_boundaries(model, samples, warmup_factor=50.0)
    assert cal.per_program, "per-program refinement missing"
    narrow = model.frame_seconds(steps=4, live=1, n_slots=8, width=1)
    wide = model.frame_seconds(steps=4, live=1, n_slots=8, width=8)
    assert narrow == pytest.approx(0.002, rel=0.15)
    assert wide == pytest.approx(0.020, rel=0.15)
    # JSON round-trip preserves the refinement
    path = str(tmp_path / "cal.json")
    save_calibration(path, cal)
    loaded = load_calibration(path)
    assert loaded.per_program == cal.per_program
    assert loaded.for_program(next(iter(cal.per_program))) != (loaded.c0,
                                                               loaded.k) \
        or len(cal.per_program) == 1
    # a calibrated sim remains deterministic
    trace = small_trace(duration_s=4.0)
    r1 = FleetSimulator(small_cfg(calibration=loaded)).run(trace)
    r2 = FleetSimulator(small_cfg(calibration=loaded)).run(trace)
    assert r1.event_lines() == r2.event_lines()
