"""Chaos suite: fault-tolerant serving under scripted fault schedules.

Every test drives ``serve(..., faults=FaultInjector(schedule))`` with a
DETERMINISTIC schedule (faults keyed by frame-boundary index and uid — no
randomness, no wall-clock triggers except the deadline tests' own
deadlines) and pins the acceptance contract of ISSUE 5:

* surviving requests complete with greedy outputs token-identical to a
  fault-free run (transient dispatch failure, poison row, KV-alloc
  failure, kill-and-resume);
* no KV blocks leak — the allocator's free count returns to baseline
  after every scenario;
* the in-graph finite-check adds zero device→host transfers inside a
  frame (transfer guard around ``dispatch_frame``);
* faults are visible: structured ``FaultReason`` records in
  ``engine.fault_log`` and ``ds_serving_*`` counters.

Engine tests share one module-scope engine/baseline (the compiled frame
programs are reused across serves — same budget discipline as the
speculative and scheduler suites).
"""

import numpy as np
import jax
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  RaggedInferenceEngineConfig)
from deepspeed_tpu.inference.v2.faults import (FaultInjector, FaultSpec,
                                               FrameDispatchError,
                                               InjectedFault)
from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                  SchedulerConfig)
from deepspeed_tpu.models import build_model

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _mesh(mesh_8dp):
    yield


@pytest.fixture(scope="module")
def tiny_model_params():
    model = build_model("tiny")
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model, params, **over):
    kw = dict(kv_block_size=16, prefill_chunk_size=16, max_tokens_per_step=256,
              dtype="float32", max_ragged_batch_size=8, frame_steps=4,
              frame_retry_backoff_s=0.0)    # chaos tests need no real backoff
    kw.update(over)
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                          max_seq_len=128)
    e.params = jax.device_put(params)
    return e


PROMPTS = {u: np.random.default_rng(5).integers(0, 200, (200,))
           .astype(np.int32)[o:o + n]
           for u, (o, n) in enumerate(((0, 7), (10, 24), (40, 33), (80, 5)))}
SCHEDULE = {0: [0, 1], 2: [2], 3: [3]}


def _arrivals(schedule=None):
    schedule = SCHEDULE if schedule is None else schedule
    for k in range(max(schedule) + 2):
        yield [(u, PROMPTS[u]) for u in schedule.get(k, [])]


@pytest.fixture(scope="module")
def served_engine(tiny_model_params):
    model, params = tiny_model_params
    return _engine(model, params)


@pytest.fixture(scope="module")
def fault_free_base(served_engine):
    """THE reference outputs every chaos scenario's survivors must match."""
    return dict(served_engine.serve(_arrivals(), max_new_tokens=8))


def _assert_clean(e):
    assert e.kv.free_blocks == e.kv.num_blocks - 1   # trash block only
    assert not e.state.seqs
    assert not e._ledger


# ---------------------------------------------------------------------------
# fault spec / injector units (no model)
# ---------------------------------------------------------------------------


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor_strike", frame=0)
    with pytest.raises(ValueError, match="needs a target uid"):
        FaultSpec(kind="poison_row", frame=0)
    with pytest.raises(ValueError, match="times >= 1"):
        FaultSpec(kind="dispatch_exception", frame=0, times=0)
    with pytest.raises(ValueError, match="seconds"):
        FaultSpec(kind="slow_frame", frame=0, seconds=-1.0)


def test_injector_is_deterministic_and_rearms():
    inj = FaultInjector([
        {"kind": "dispatch_exception", "frame": 1, "times": 2},
        {"kind": "poison_row", "frame": 2, "uid": 7},
        {"kind": "kv_alloc_fail", "frame": 0, "times": 2},
    ])

    def run():
        events = []
        for frame in range(4):
            if inj.kv_alloc_blocked(frame):
                events.append(("alloc", frame))
            events.append(("poison", frame, inj.poison_uids(frame)))
            attempt = 0
            while True:
                try:
                    inj.before_dispatch(frame, attempt)
                    break
                except InjectedFault:
                    events.append(("raise", frame, attempt))
                    attempt += 1
        return events

    first = run()
    inj.begin_serve()                       # rearm: identical second run
    assert run() == first
    assert ("raise", 1, 0) in first and ("raise", 1, 1) in first
    assert ("poison", 2, [7]) in first
    assert ("alloc", 0) in first and ("alloc", 1) in first
    assert ("alloc", 2) not in first


# ---------------------------------------------------------------------------
# transient dispatch failure: bounded retry, token-identical recovery
# ---------------------------------------------------------------------------


def test_transient_dispatch_failure_recovers_token_identical(
        served_engine, fault_free_base):
    """Two consecutive dispatch failures at one frame are absorbed by the
    retry loop (the donated carry was never consumed) — outputs are
    token-identical to the fault-free run and the retries are counted."""
    e = served_engine
    inj = FaultInjector([{"kind": "dispatch_exception", "frame": 2,
                          "times": 2}])
    got = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert set(got) == set(fault_free_base)
    for u in fault_free_base:
        np.testing.assert_array_equal(fault_free_base[u], got[u],
                                      err_msg=f"uid={u}")
    assert len(inj.fired) == 2
    assert e.telemetry.counters["frame_retries"] == 2
    assert e.telemetry.counters["faults"] == 2
    retries = [f for f in e.fault_log if f.kind == "dispatch_retry"]
    assert len(retries) >= 2 and retries[-1].frame == 2
    _assert_clean(e)


def test_watchdog_flags_slow_frame(served_engine, fault_free_base):
    """An injected slow frame trips the wall-clock watchdog: counted and
    logged, never killed — outputs stay token-identical."""
    e = served_engine
    # threshold far above a natural CPU frame (~8 ms), far below the
    # injected stall: only the scripted slow frame deterministically trips
    e._config.watchdog_frame_ms = 100.0
    try:
        inj = FaultInjector([{"kind": "slow_frame", "frame": 1,
                              "seconds": 0.25}])
        got = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    finally:
        e._config.watchdog_frame_ms = None
    for u in fault_free_base:
        np.testing.assert_array_equal(fault_free_base[u], got[u])
    assert e.telemetry.counters["slow_frames"] >= 1
    assert any(f.kind == "slow_frame" and f.frame == 1
               for f in e.fault_log)
    assert inj.fired and inj.fired[0]["kind"] == "slow_frame"
    _assert_clean(e)


# ---------------------------------------------------------------------------
# poison-row quarantine
# ---------------------------------------------------------------------------


def test_poison_row_quarantined_siblings_unaffected(
        served_engine, fault_free_base):
    """A row whose logits go non-finite mid-decode is quarantined at the
    frame boundary: evicted, retired with a structured FaultReason carrying
    its committed partial output, never yielded — and every sibling's
    output is byte-identical to the fault-free run. The batch never dies
    for one request."""
    e = served_engine
    inj = FaultInjector([{"kind": "poison_row", "frame": 1, "uid": 1}])
    got = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert 1 not in got                      # quarantined, not yielded
    for u in (0, 2, 3):
        np.testing.assert_array_equal(fault_free_base[u], got[u],
                                      err_msg=f"uid={u}")
    fr = [f for f in e.fault_log if f.kind == "poison_row"][-1]
    assert fr.uid == 1 and fr.frame == 1
    # the partial output is the committed prefix of the healthy run: frames
    # BEFORE the poison emitted real tokens, the poisoned frame's tail was
    # suppressed by the in-graph emit mask
    assert fr.partial and fr.tokens_emitted == len(fr.partial)
    np.testing.assert_array_equal(
        np.asarray(fr.partial), fault_free_base[1][:len(fr.partial)])
    assert e.telemetry.counters["quarantined"] == 1
    prom = e.telemetry.render_prometheus()
    assert "ds_serving_quarantined_total 1" in prom
    assert 'ds_serving_faults_total{kind="poison_row"} 1' in prom
    _assert_clean(e)


def test_finite_check_adds_no_in_frame_transfers(served_engine,
                                                 frame_transfer_guard):
    """Acceptance guard: the finite-check/poison machinery rides the donated
    carry — frame dispatch performs ZERO device→host transfers even while a
    poison fault fires and a quarantine runs (conftest's shared guard)."""
    e = served_engine
    inj = FaultInjector([{"kind": "poison_row", "frame": 1, "uid": 1}])
    got = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert 1 not in got and set(got) == {0, 2, 3}
    assert [f.uid for f in e.fault_log
            if f.kind == "poison_row"][-1] == 1   # quarantine ran under guard
    _assert_clean(e)


# ---------------------------------------------------------------------------
# KV-allocation failure
# ---------------------------------------------------------------------------


def test_kv_alloc_failure_defers_then_recovers(served_engine,
                                               fault_free_base):
    """Injected allocation failures turn into admission deferrals (the
    graceful path), not crashes: arrivals wait out the fault window and
    complete token-identically."""
    e = served_engine
    inj = FaultInjector([{"kind": "kv_alloc_fail", "frame": 2, "times": 2}])
    got = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert set(got) == set(fault_free_base)
    for u in fault_free_base:
        np.testing.assert_array_equal(fault_free_base[u], got[u],
                                      err_msg=f"uid={u}")
    assert any(f.kind == "kv_alloc_failed" for f in e.fault_log)
    assert e.telemetry.counters["admission_deferrals"] >= 1
    _assert_clean(e)


# ---------------------------------------------------------------------------
# per-request deadlines
# ---------------------------------------------------------------------------


def test_deadline_expiry_frees_blocks_and_counts(served_engine,
                                                 fault_free_base):
    """A live row whose deadline_ms elapses is cancelled at the next frame
    boundary: KV blocks freed, a deadline_expired timeout retirement
    recorded (with the committed partial), telemetry visible — and the
    surviving row's output is untouched."""
    e = served_engine
    blocks_baseline = e.kv.free_blocks

    def arr():
        yield [(0, PROMPTS[0]),
               {"uid": 9, "tokens": PROMPTS[1], "deadline_ms": 0.5}]
        for _ in range(3):
            yield []

    got = dict(e.serve(arr(), max_new_tokens=8))
    assert 9 not in got
    np.testing.assert_array_equal(got[0], fault_free_base[0])
    fr = [f for f in e.fault_log if f.kind == "deadline_expired"][-1]
    assert fr.uid == 9 and "live row" in fr.detail
    assert e.telemetry.counters["deadline_expired"] == 1
    assert "ds_serving_deadline_expired_total 1" in \
        e.telemetry.render_prometheus()
    assert e.kv.free_blocks == blocks_baseline     # expiry freed its blocks
    _assert_clean(e)


def test_deadline_expiry_in_queue_before_admission(served_engine):
    """A QUEUED request past its deadline is cancelled before a slot or any
    KV blocks are ever spent on it (zero tokens emitted)."""
    e = served_engine
    # 2 slots, 3 arrivals: uid 22 queues behind 20/21 and expires waiting
    def arr():
        yield [{"uid": 20, "tokens": PROMPTS[1]},
               {"uid": 21, "tokens": PROMPTS[2]},
               {"uid": 22, "tokens": PROMPTS[3], "deadline_ms": 0.5}]
        for _ in range(2):
            yield []

    got = dict(e.serve(arr(), max_new_tokens=8, frame_slots=2))
    assert set(got) == {20, 21}
    fr = [f for f in e.fault_log if f.kind == "deadline_expired"][-1]
    assert fr.uid == 22 and "queued" in fr.detail
    assert fr.tokens_emitted == 0 and fr.partial is None
    _assert_clean(e)


def test_deadline_cancelled_before_preemption_or_aging(served_engine):
    """Scheduler integration: an expired queued interactive request is
    cancelled BEFORE the boundary's preemption pass — no live best-effort
    row is evicted on behalf of dead work."""
    e = served_engine

    def arr():
        yield [{"uid": 30, "tokens": PROMPTS[1], "priority": "best_effort"},
               {"uid": 31, "tokens": PROMPTS[2], "priority": "best_effort"}]
        yield []
        # a deadline so tight it is already past at the arrival's own
        # boundary: the expiry pass must cancel it before the preemption
        # pass can evict a live row on its behalf
        yield [{"uid": 32, "tokens": PROMPTS[0], "priority": "interactive",
                "deadline_ms": 1e-6}]
        for _ in range(2):
            yield []

    s = RequestScheduler(SchedulerConfig())
    got = dict(e.serve(arr(), max_new_tokens=12, frame_slots=2, scheduler=s))
    assert set(got) == {30, 31}
    assert s.summary["preempted"] == 0       # dead work preempted nobody
    fr = [f for f in e.fault_log if f.kind == "deadline_expired"][-1]
    assert fr.uid == 32 and fr.priority == "interactive"
    _assert_clean(e)


# ---------------------------------------------------------------------------
# kill-and-resume crash recovery
# ---------------------------------------------------------------------------


def test_kill_and_resume_token_identical(tiny_model_params, served_engine,
                                         fault_free_base):
    """A fatal dispatch failure (retry budget exhausted) surfaces as
    FrameDispatchError AFTER the engine auto-snapshots its request ledger;
    a FRESH engine resuming from the snapshot re-admits the in-flight
    requests and the union of pre-crash and post-resume outputs is
    token-identical to the fault-free run. Recovery is visible in
    ds_serving_recoveries_total and the recovery-time gauge."""
    model, params = tiny_model_params
    e = served_engine
    inj = FaultInjector([{"kind": "dispatch_exception", "frame": 3,
                          "times": 10}])
    collected = {}
    with pytest.raises(FrameDispatchError, match="resume_from"):
        for uid, toks in e.serve(_arrivals(), max_new_tokens=8, faults=inj):
            collected[uid] = toks
    assert any(f.kind == "dispatch_failed" for f in e.fault_log)
    _assert_clean(e)                          # crash cleanup left no leaks
    snap = e.last_crash_snapshot
    assert snap is not None and snap["version"] == 1
    in_flight = {r["uid"] for r in snap["requests"]}
    assert in_flight and in_flight.isdisjoint(collected)

    e2 = _engine(model, params)               # the restarted engine
    rest = dict(e2.serve(iter([[]]), max_new_tokens=8, resume_from=snap))
    collected.update(rest)
    assert set(collected) == set(fault_free_base)
    for u in fault_free_base:
        np.testing.assert_array_equal(fault_free_base[u], collected[u],
                                      err_msg=f"uid={u}")
    assert e2.telemetry.counters["recoveries"] == len(in_flight)
    assert e2.telemetry.gauges["last_recovery_ms"] > 0
    assert "ds_serving_recoveries_total" in e2.telemetry.render_prometheus()
    _assert_clean(e2)


def test_snapshot_restore_parity_without_crash(tiny_model_params,
                                               served_engine,
                                               fault_free_base):
    """snapshot_serving_state() works on a healthy engine too: abandon a
    serve mid-flight after snapshotting, resume the snapshot elsewhere, and
    the resumed outputs extend the committed prefixes token-identically."""
    model, params = tiny_model_params
    e = served_engine
    collected = {}
    gen = e.serve(_arrivals(), max_new_tokens=8)
    snap = None
    for uid, toks in gen:
        collected[uid] = toks
        snap = e.snapshot_serving_state()    # after the first retirement
        break
    gen.close()                              # abandon: cleanup must not
    _assert_clean(e)                         # invalidate the snapshot
    # the first retirement (uid 0, smallest budget) lands before the
    # abandoned generator ever polls uids 2/3 off the arrival schedule, so
    # the snapshot covers exactly the other in-flight request
    assert {r["uid"] for r in snap["requests"]} == {1}
    e2 = _engine(model, params)
    rest = dict(e2.serve(iter([[]]), max_new_tokens=8, resume_from=snap))
    collected.update(rest)
    assert set(collected) == {0, 1}
    for u in collected:
        np.testing.assert_array_equal(fault_free_base[u], collected[u],
                                      err_msg=f"uid={u}")
    _assert_clean(e2)


def test_resume_through_scheduler_preserves_metadata(tiny_model_params):
    """Resuming into a scheduled serve: snapshot tenant/priority ride the
    ledger, so resumed requests re-enter the policy queues in class order
    (and fault-free resumed outputs match the plain run)."""
    model, params = tiny_model_params
    e = _engine(model, params)
    base = dict(e.serve(_arrivals(), max_new_tokens=8))

    def arr():
        yield [{"uid": 0, "tokens": PROMPTS[0], "tenant": "acme",
                "priority": "interactive"},
               {"uid": 1, "tokens": PROMPTS[1], "tenant": "umbrella",
                "priority": "batch"}]

    inj = FaultInjector([{"kind": "dispatch_exception", "frame": 1,
                          "times": 10}])
    s = RequestScheduler(SchedulerConfig())
    with pytest.raises(FrameDispatchError):
        list(e.serve(arr(), max_new_tokens=8, scheduler=s, faults=inj))
    snap = e.last_crash_snapshot
    by_uid = {r["uid"]: r for r in snap["requests"]}
    assert by_uid[0]["tenant"] == "acme"
    assert by_uid[0]["priority"] == "interactive"
    assert by_uid[1]["priority"] == "batch"

    rest = dict(e.serve(iter([[]]), max_new_tokens=8,
                        scheduler=RequestScheduler(), resume_from=snap))
    for u in (0, 1):
        np.testing.assert_array_equal(base[u], rest[u], err_msg=f"uid={u}")
    _assert_clean(e)


def test_resume_bypasses_tenant_queue_quota(tiny_model_params):
    """Known issue (a): crash-recovery resume used to route previously-live
    requests through ``sched.submit()``, so ``tenant_max_queued`` could
    shed ACCEPTED mid-flight work and silently drop its committed tokens.
    Resume ingestion now bypasses the queue quota (the ``requeue_front``
    precedent for preempted work): every snapshot request completes,
    token-identical to the crash-free run, even when the tenant's quota is
    smaller than its in-flight count — and new (non-resume) arrivals still
    face the quota."""
    model, params = tiny_model_params
    e = _engine(model, params)
    base = dict(e.serve([[(0, PROMPTS[0]), (1, PROMPTS[1])]],
                        max_new_tokens=8))
    inj = FaultInjector([{"kind": "dispatch_exception", "frame": 1,
                          "times": 10}])

    def arr():
        yield [{"uid": 0, "tokens": PROMPTS[0], "tenant": "t"},
               {"uid": 1, "tokens": PROMPTS[1], "tenant": "t"}]

    with pytest.raises(FrameDispatchError):
        list(e.serve(arr(), max_new_tokens=8, scheduler=RequestScheduler(),
                     faults=inj))
    snap = e.last_crash_snapshot
    assert {r["uid"] for r in snap["requests"]} == {0, 1}
    # a quota of 1 would have shed uid 1 pre-fix; resume must not shed
    s = RequestScheduler(SchedulerConfig(tenant_max_queued=1))
    got = dict(e.serve(iter([[]]), max_new_tokens=8, scheduler=s,
                       resume_from=snap))
    assert set(got) == {0, 1}
    assert s.stats()["shed_total"] == 0
    for u in (0, 1):
        np.testing.assert_array_equal(base[u], got[u], err_msg=f"uid={u}")
    # the quota still applies to NEW submissions on the same scheduler
    from deepspeed_tpu.inference.v2.scheduler import Request
    s.submit(Request(uid=90, tokens=PROMPTS[0], limit=8, temp=0.0,
                     eos=None, tenant="t"))
    assert s.submit(Request(uid=91, tokens=PROMPTS[1], limit=8, temp=0.0,
                            eos=None, tenant="t")) is not None
    _assert_clean(e)
    # the shed uid stays reusable
    again = dict(e.serve(iter([[(1, PROMPTS[1])]]), max_new_tokens=4))
    assert len(again[1]) == 4
    _assert_clean(e)


# ---------------------------------------------------------------------------
# abandonment with faults mid-flight (satellite: preempted-row cleanup)
# ---------------------------------------------------------------------------


def test_abandonment_after_preemption_releases_everything(tiny_model_params):
    """Abandon a scheduled serve at the retirement right after a preemption
    (victim evicted, folded, re-queued — not yet re-admitted): the ledger
    sweep must release the preempted row's descriptor and folded tokens,
    and the engine stays reusable."""
    model, params = tiny_model_params
    e = _engine(model, params)

    def arr():
        yield [{"uid": 60, "tokens": PROMPTS[1], "priority": "best_effort"},
               {"uid": 61, "tokens": PROMPTS[2], "priority": "best_effort"}]
        yield []
        yield [{"uid": 62, "tokens": PROMPTS[0], "max_new_tokens": 4,
                "priority": "interactive"}]
        for _ in range(8):
            yield []

    s = RequestScheduler(SchedulerConfig())
    for _uid, _toks in e.serve(arr(), max_new_tokens=12, frame_slots=2,
                               scheduler=s):
        break          # the interactive retires first, victim still queued
    assert s.summary["preempted"] == 1
    _assert_clean(e)
    got = dict(e.serve(iter([[(60, PROMPTS[0])]]), max_new_tokens=4,
                       frame_slots=2))
    assert len(got[60]) == 4
    _assert_clean(e)


def test_fault_log_is_bounded(tiny_model_params):
    model, params = tiny_model_params
    e = _engine(model, params, fault_log_max=4)
    assert e.fault_log.maxlen == 4


# ---------------------------------------------------------------------------
# nonfinite_policy="repair": in-graph NaN repair (pre-fault-carry rollback)
# ---------------------------------------------------------------------------


def test_nonfinite_policy_validation(tiny_model_params):
    model, params = tiny_model_params
    with pytest.raises(ValueError, match="nonfinite_policy"):
        _engine(model, params, nonfinite_policy="hope")
    with pytest.raises(ValueError, match="nonfinite_repair_limit"):
        _engine(model, params, nonfinite_policy="repair",
                nonfinite_repair_limit=0)


@pytest.fixture(scope="module")
def repair_engine(tiny_model_params):
    model, params = tiny_model_params
    return _engine(model, params, nonfinite_policy="repair",
                   nonfinite_repair_limit=2)


def test_nonfinite_repair_transient_blip_parity(repair_engine,
                                                fault_free_base):
    """A one-frame poison blip under repair: the row rolls back to its
    pre-fault carry in-graph and CONTINUES — every request, including the
    poisoned one, finishes token-identical to the fault-free run (the
    quarantine policy retires the victim instead)."""
    e = repair_engine
    inj = FaultInjector([{"kind": "poison_row", "frame": 1, "uid": 1}])
    outs = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert inj.fired
    assert set(outs) == set(fault_free_base)
    for u, base in fault_free_base.items():
        assert np.array_equal(outs[u], base), f"uid={u}"
    kinds = [f.kind for f in e.fault_log]
    assert "nonfinite_repaired" in kinds
    assert "poison_row" not in kinds
    assert e.telemetry.counters["nonfinite_repaired"] >= 1
    assert e.telemetry.counters["quarantined"] == 0
    _assert_clean(e)


def test_nonfinite_repair_escalates_persistent_fault(repair_engine,
                                                     fault_free_base):
    """A fault that latches nonfinite_repair_limit consecutive boundaries
    is not a blip: the row escalates to the quarantine path, siblings
    stay token-identical."""
    e = repair_engine
    e.fault_log.clear()          # the log is engine-lifetime, not per-serve
    inj = FaultInjector([{"kind": "poison_row", "frame": f, "uid": 1}
                         for f in (1, 2, 3, 4, 5)])
    outs = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert 1 not in outs
    for u, base in fault_free_base.items():
        if u != 1:
            assert np.array_equal(outs[u], base), f"uid={u}"
    kinds = [f.kind for f in e.fault_log]
    assert kinds.count("nonfinite_repaired") == 2     # the repair budget
    assert kinds.count("poison_row") == 1             # then escalation
    assert kinds.index("poison_row") > kinds.index("nonfinite_repaired")
    _assert_clean(e)


def test_nonfinite_repair_speculative_parity(tiny_model_params,
                                             fault_free_base):
    """The rollback selects ride the SPECULATIVE frame carry too: a blip
    during draft/verify decode repairs token-identically (greedy spec
    output already equals plain greedy, so the plain baseline is the
    reference)."""
    model, params = tiny_model_params
    e = _engine(model, params, nonfinite_policy="repair")
    e.attach_draft(model, params)                     # self-draft
    inj = FaultInjector([{"kind": "poison_row", "frame": 2, "uid": 1}])
    outs = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    for u, base in fault_free_base.items():
        assert np.array_equal(outs[u], base), f"uid={u}"
    assert e.telemetry.counters["quarantined"] == 0
    _assert_clean(e)


def test_nonfinite_repair_inframe_transfer_guard(repair_engine,
                                                 fault_free_base,
                                                 frame_transfer_guard):
    """Repair adds only frame-BOUNDARY device traffic (latch read, batched
    clear, watermark resync): the in-frame transfer guard stays green."""
    e = repair_engine
    inj = FaultInjector([{"kind": "poison_row", "frame": 1, "uid": 1}])
    outs = dict(e.serve(_arrivals(), max_new_tokens=8, faults=inj))
    assert np.array_equal(outs[1], fault_free_base[1])
    _assert_clean(e)
