#!/usr/bin/env python
"""Host optimizer micro-benchmark: native C++ CPUAdam vs jnp (jit, cpu).

Analog of the reference's ``tests/perf/adam_test.py``. The native kernel
(``ops/csrc/adam/cpu_adam.cpp``, OpenMP + simd) is what ZeRO-Infinity
streaming uses on the host (``runtime/zero/infinity.py``); this shows why.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(n=4_000_000, iters=10):
    from deepspeed_tpu.ops.cpu_adam_native import cpu_adam_step

    rng = np.random.default_rng(0)
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    cpu_adam_step(p, g, m, v, 1, 1e-3)
    t0 = time.perf_counter()
    for i in range(2, iters + 2):
        cpu_adam_step(p, g, m, v, i, 1e-3)
    native = (time.perf_counter() - t0) / iters

    import jax
    import jax.numpy as jnp
    jax.config.update("jax_platforms", "cpu")

    @jax.jit
    def jnp_adam(p, g, m, v, step):
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** step)
        vh = v / (1 - b2 ** step)
        return p - lr * mh / (jnp.sqrt(vh) + eps), m, v

    pj, gj, mj, vj = map(jnp.asarray, (p, g, m, v))
    jax.block_until_ready(jnp_adam(pj, gj, mj, vj, 1))
    t0 = time.perf_counter()
    for i in range(2, iters + 2):
        out = jnp_adam(pj, gj, mj, vj, i)
    jax.block_until_ready(out)
    jnp_t = (time.perf_counter() - t0) / iters

    print(json.dumps({
        "metric": "cpu_adam_params_per_sec",
        "native": round(n / native / 1e6, 1),
        "jnp": round(n / jnp_t / 1e6, 1),
        "unit": "Mparams/s",
        "speedup": round(jnp_t / native, 2),
    }))


if __name__ == "__main__":
    main()
