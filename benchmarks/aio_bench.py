#!/usr/bin/env python
"""DeepNVMe I/O benchmark sweep.

Analog of the reference's ``csrc/aio/py_test/aio_bench_perf_sweep.py``
(BASELINE row: 10 GB/s reads / 5 GB/s writes on 4xNVMe RAID-0): sweeps
(queue depth, block size, O_DIRECT) over the native async I/O engine
(``ops/csrc/aio/deepspeed_aio.cpp``) and prints one JSON line with the best
read/write bandwidth. Point --dir at the NVMe mount to benchmark.
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_one(path, size_bytes, queue_depth, block_size, direct, iters=3):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    h = AsyncIOHandle(queue_depth=queue_depth, block_size=block_size,
                      use_direct=direct)
    buf = np.random.default_rng(0).integers(0, 255, size_bytes, np.uint8)
    # write bandwidth
    t0 = time.perf_counter()
    for _ in range(iters):
        h.async_pwrite(buf, path)
        errs = h.wait()
        assert not errs, f"aio write errors: {errs}"
        os.sync() if direct else None
    w_bw = size_bytes * iters / (time.perf_counter() - t0) / 1e9
    # read bandwidth (drop page cache effect is limited without root; O_DIRECT
    # bypasses it)
    out = np.empty_like(buf)
    t0 = time.perf_counter()
    for _ in range(iters):
        h.async_pread(out, path)
        errs = h.wait()
        assert not errs, f"aio read errors: {errs}"
    r_bw = size_bytes * iters / (time.perf_counter() - t0) / 1e9
    return r_bw, w_bw


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default=None, help="target dir (NVMe mount)")
    ap.add_argument("--size-mb", type=int, default=256)
    args = ap.parse_args()
    d = args.dir or tempfile.mkdtemp()
    path = os.path.join(d, "aio_bench.bin")
    size = args.size_mb << 20

    # per-regime bests: the swap path (OptimizerSwapper / Infinity _GroupStore)
    # opens handles BUFFERED, so the buffered number is what training
    # actually sees — but it rides the page cache on this single-boot-volume
    # host, so the O_DIRECT row is reported alongside as the raw-device
    # throughput (r4 review: the cache regime must be stated in the best row)
    bests = {False: {"read_gbps": 0.0, "write_gbps": 0.0},
             True: {"read_gbps": 0.0, "write_gbps": 0.0}}
    results = []
    for qd in (4, 8, 16):
        for bs_mb in (1, 8):
            for direct in (False, True):
                try:
                    r, w = bench_one(path, size, qd, bs_mb << 20, direct)
                except Exception as e:
                    results.append({"qd": qd, "bs_mb": bs_mb, "direct": direct,
                                    "error": str(e)[:80]})
                    continue
                results.append({"qd": qd, "bs_mb": bs_mb, "direct": direct,
                                "read_gbps": round(r, 2), "write_gbps": round(w, 2)})
                b = bests[direct]
                if r > b["read_gbps"]:
                    b.update(read_gbps=round(r, 2), read_cfg=(qd, bs_mb))
                if w > b["write_gbps"]:
                    b.update(write_gbps=round(w, 2), write_cfg=(qd, bs_mb))
    try:
        os.unlink(path)
    except OSError:
        pass
    best = {
        **bests[False],
        "cache_regime": (
            "BUFFERED (page-cache-assisted): this is the configuration the "
            "swap path actually uses (AsyncIOHandle default) and benefits "
            "from on repeated swap-in of hot groups, but it is NOT a "
            "raw-device number on this single-boot-volume host — see "
            "best_o_direct for the uncached throughput"),
    }
    print(json.dumps({"metric": "aio_bandwidth", "unit": "GB/s",
                      "best": best, "best_o_direct": bests[True],
                      "sweep": results}))


if __name__ == "__main__":
    main()
