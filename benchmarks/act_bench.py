"""Activation-checkpointing variants: device-memory deltas on the real chip.

The r4 review asked for a measured memory-delta row next to the remat
policies (reference ``activation_checkpointing/checkpointing.py:486`` CPU
checkpointing + partitioned activations): XLA's compiled memory analysis for
one gpt2-small train step under each policy — temp allocation is where the
saved activations live, so the delta IS the lever's size. ``dots_offload``
additionally reports host-memory residency (the offloaded checkpoints).

Prints one JSON line.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(remat, batch=8, seq=1024):
    import jax
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, get_config
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    cfg = get_config("gpt2-small", max_seq_len=seq)
    model = build_model(cfg.replace(dtype="bfloat16", remat=remat))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": batch, "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 1}, "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9})
    rng = np.random.default_rng(0)
    batch_h = engine.stage_batch({
        "input_ids": rng.integers(0, 50257, (batch, seq), dtype=np.int32),
        "labels": rng.integers(0, 50257, (batch, seq), dtype=np.int32)})
    lowered = engine._train_step_fn.lower(
        engine.module_params, engine.opt_state, engine.scaler_state,
        batch_h, engine._next_lr_device(), gas=1)
    mem = lowered.compile().memory_analysis()
    row = {"remat": remat,
           "temp_mb": round(getattr(mem, "temp_size_in_bytes", -1) / 2**20, 1),
           "argument_mb": round(getattr(mem, "argument_size_in_bytes", -1) / 2**20, 1)}
    # the step also RUNS under the policy (compile-only numbers can hide
    # lowering failures)
    loss = engine.train_batch(batch_h)
    row["loss_finite"] = bool(np.isfinite(float(loss)))
    return row


def main():
    rows = [measure(r) for r in ("none", "dots", "dots_offload")]
    by = {r["remat"]: r for r in rows}
    out = {
        "metric": "activation_checkpointing_memory",
        "model": "gpt2-small", "batch": 8, "seq": 1024,
        "rows": rows,
        "temp_saved_mb_dots_vs_none": round(
            by["none"]["temp_mb"] - by["dots"]["temp_mb"], 1),
        "temp_saved_mb_offload_vs_dots": round(
            by["dots"]["temp_mb"] - by["dots_offload"]["temp_mb"], 1),
        "note": "XLA compiled-memory analysis of the full train step: temp "
                "holds the saved activations; dots_offload parks checkpoints "
                "in pinned host memory (device temp shrinks further at a "
                "host-transfer cost — the long-context memory lever). "
                "partition_activations' temp delta is asserted on the "
                "virtual TP mesh in tests/test_engine.py::"
                "test_partitioned_activations_parity_and_memory",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
