#!/usr/bin/env python
"""Collective bandwidth benchmark (ICI allgather / reduce-scatter / all-reduce
/ all-to-all) — one of the BASELINE.json metrics.

Analog of the reference's ``ds_bench`` / DeepSpeedExamples comm benchmarks:
sweeps message sizes, reports algorithmic bandwidth per collective.

Usage: python benchmarks/comm_bench.py [--sizes 1048576,16777216] [--trials 20]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(sizes, trials):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.utils import groups

    dist.init_distributed(verbose=False)
    n = groups.get_world_size()
    results = []
    for size in sizes:
        x = jnp.ones((size // 4,), jnp.float32)  # size bytes
        for name, fn, vol_factor in (
                ("all_reduce", lambda t: dist.all_reduce(t, group="data"), 2 * (n - 1) / n),
                ("all_gather", lambda t: dist.all_gather_into_tensor(
                    jax.device_put(t, groups.named_sharding("data")), group="data"),
                 (n - 1) / n),
                ("reduce_scatter", lambda t: dist.reduce_scatter_tensor(t, group="data"),
                 (n - 1) / n),
                ("all_to_all", lambda t: dist.all_to_all_single(
                    jax.device_put(t, groups.named_sharding("data")), group="data"),
                 (n - 1) / n),
        ):
            out = fn(x)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            outs = [fn(x) for _ in range(trials)]
            jax.block_until_ready(outs)
            dt = (time.perf_counter() - t0) / trials
            busbw = size * vol_factor / dt / 1e9
            results.append({"op": name, "bytes": size, "time_us": round(dt * 1e6, 1),
                            "busbw_GBps": round(busbw, 2)})
    return {"world": n, "results": results}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=str, default="1048576,16777216,134217728")
    p.add_argument("--trials", type=int, default=20)
    args = p.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]
    print(json.dumps(run(sizes, args.trials)))


if __name__ == "__main__":
    main()
