#!/usr/bin/env python
"""Serving benchmark: FastGen-analog measured end to end.

Produces the recorded artifact the round-2 review demanded (SERVING_rNN.json
via `python benchmarks/serving_bench.py > SERVING_rNN.json`): one JSON object
with a row per workload — decode-heavy, prefill-heavy, and mixed Dynamic-
SplitFuse — each carrying tokens/sec, per-step latency p50/p95, KV-pool
utilization, and host-scheduler overhead, plus the paged-Pallas vs XLA-gather
decode delta. Reference bar shape: ``blogs/deepspeed-fastgen/README.md:28,139``
(FastGen reports effective throughput and p50/p95 latency trade-offs; the
absolute rows here are gpt2-small-class on one v5e chip).

Methodology (tunneled single-chip platform, see bench.py):
- decode throughput uses the COMPILED multi-token loop (one dispatch for N
  tokens) — per-dispatch tunnel latency would otherwise dominate;
- the mixed workload intentionally uses host-driven ``step()`` so the number
  includes the real SplitFuse scheduler cost, which is reported separately
  as ``sched_overhead_pct`` (host wall-time share of the step loop);
- timings sync via device_get of values data-dependent on the step.
"""

import json
import logging
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _logs_to_stderr():
    """The package logger streams to stdout (reference behavior); the bench
    must keep stdout pure JSON so `> SERVING_rNN.json` works as documented.
    Importing the logger first forces its handler to exist — redirecting
    before the package's lazy first import would silently do nothing."""
    from deepspeed_tpu.utils.logging import logger as _pkg_logger
    for h in _pkg_logger.handlers:
        if hasattr(h, "stream"):
            h.stream = sys.stderr


def _mk_engine(model_name, batch, max_seq_len=None, expected_context=None):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model
    cfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=max(batch, 16),
        max_tokens_per_step=max(batch * 2, 768),
        # the bench knows its workload; a server would pass its SLA numbers
        expected_context=expected_context,
        expected_concurrency=batch if expected_context else None,
    )
    model = build_model(model_name)
    return InferenceEngineV2(model, cfg, max_seq_len=max_seq_len)


def bench_platform_floor():
    """Measured per-op floor of the tunneled chip — the context for every
    absolute number in this artifact: streamed-HBM ops cost ~2 ms regardless
    of size (~15 GB/s effective vs the 819 GB/s v5e spec), so decode steps
    are op-floor-bound here, not a property of the engine design."""
    import time
    import jax
    import jax.numpy as jnp
    from jax import lax
    n = 32 * 1024 * 1024 // 2
    xs = jnp.ones((8, n), jnp.bfloat16)

    @jax.jit
    def run(xs, c):
        def body(c, x):
            return c + jnp.sum(x.astype(jnp.float32)), ()
        def rep(c, _):
            c, _n = lax.scan(body, c, xs)
            return c, ()
        c, _ = lax.scan(rep, c, None, length=6)
        return c

    c0 = jnp.zeros((), jnp.float32)
    run(xs, c0)
    jax.device_get(run(xs, c0))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(run(xs, c0))
        best = min(best, time.perf_counter() - t0)
    per = best / 48
    return {"workload": "platform-floor",
            "stream_32mb_op_ms": round(per * 1e3, 3),
            "effective_hbm_gbps": round(32 / 1024 / per, 1)}


def _kv_util(eng):
    total = eng.kv.num_blocks
    return round(1.0 - eng.kv.free_blocks / total, 4)


def bench_decode(model_name, batch, prompt_len, new_tokens):
    """Decode-heavy: steady-state generation throughput (compiled loop).
    The pool is workload-auto-sized (expected_context = prompt + generation
    budget) — r4's decode rows sat at 25% utilization on the memory-fraction
    default."""
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, eng.model.cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(batch)]
    eng.generate(prompts, max_new_tokens=4)          # compile both step counts
    eng.generate(prompts, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=4)
    t1 = time.perf_counter()
    # KV utilization at the deepest point of the long run
    eng.put(list(range(batch)), prompts)
    while any(eng.state.seqs[u].in_prefill for u in range(batch)):
        eng.step()
    util = _kv_util(eng)
    eng.flush(list(range(batch)))
    t1b = time.perf_counter()
    eng.generate(prompts, max_new_tokens=new_tokens)
    t2 = time.perf_counter()
    decode_dt = (t2 - t1b) - (t1 - t0)               # marginal decode cost
    toks = batch * (new_tokens - 4)
    return {
        "workload": "decode-heavy", "model": model_name,
        "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "decode_tok_per_sec": round(toks / decode_dt, 1),
        "decode_ms_per_token_per_seq": round(decode_dt / (new_tokens - 4) * 1e3, 2),
        "e2e_tok_per_sec": round(batch * new_tokens / (t2 - t1b), 1),
        "kv_util_after_prefill": util,
    }


def bench_prefill(model_name, batch, prompt_len):
    """Prefill-heavy: prompt-token ingestion throughput via SplitFuse chunks."""
    eng = _mk_engine(model_name, batch, expected_context=prompt_len + 1)
    rng = np.random.default_rng(1)

    def run():
        prompts = [rng.integers(0, eng.model.cfg.vocab_size,
                                (prompt_len,)).astype(np.int32)
                   for _ in range(batch)]
        uids = list(range(batch))
        eng.put(uids, prompts)
        lat = []
        t0 = time.perf_counter()
        while any(eng.state.seqs[u].in_prefill for u in uids):
            s = time.perf_counter()
            eng.step()
            lat.append(time.perf_counter() - s)
        dt = time.perf_counter() - t0
        util = _kv_util(eng)
        eng.flush(uids)
        return dt, lat, util

    run()                                             # compile
    dt, lat, util = run()
    total = batch * prompt_len
    return {
        "workload": "prefill-heavy", "batch": batch, "prompt_len": prompt_len,
        "prefill_tok_per_sec": round(total / dt, 1),
        "step_ms_p50": round(statistics.median(lat) * 1e3, 2),
        "step_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 2),
        "kv_util_peak": util,
    }


def bench_mixed(model_name, batch, prompt_len, new_tokens):
    """Mixed SplitFuse: half the fleet decodes while half prefills — the
    host-driven step() loop, so the scheduler cost is IN the number."""
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    rng = np.random.default_rng(2)
    vocab = eng.model.cfg.vocab_size

    def run():
        uids_a = list(range(0, batch // 2))
        uids_b = list(range(batch // 2, batch))
        eng.put(uids_a, [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
                         for _ in uids_a])
        # drive group A into decode
        while any(eng.state.seqs[u].in_prefill for u in uids_a):
            eng.step()
        # group B arrives: steps now fuse B's prefill chunks with A's decodes
        eng.put(uids_b, [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
                         for _ in uids_b])
        lat, produced = [], 0
        # time the scheduler from INSIDE step() (wrapping the bound method)
        # so each iteration schedules exactly once
        sched_box = [0.0]
        orig_schedule = eng._schedule

        def timed_schedule():
            s = time.perf_counter()
            out = orig_schedule()
            sched_box[0] += time.perf_counter() - s
            return out

        eng._schedule = timed_schedule
        t0 = time.perf_counter()
        while (any(eng.state.seqs[u].in_prefill for u in uids_b)
               or min(len(eng.state.seqs[u].generated) for u in uids_a + uids_b)
               < new_tokens):
            s = time.perf_counter()
            out = eng.step()
            produced += len(out)
            lat.append(time.perf_counter() - s)
        dt = time.perf_counter() - t0
        eng._schedule = orig_schedule
        sched_t = sched_box[0]
        util = _kv_util(eng)
        eng.flush(uids_a + uids_b)
        return dt, lat, sched_t, produced, util

    run()                                             # compile
    dt, lat, sched_t, produced, util = run()
    return {
        "workload": "mixed-splitfuse", "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "generated_tok_per_sec": round(produced / dt, 1),
        "step_ms_p50": round(statistics.median(lat) * 1e3, 2),
        "step_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 2),
        "sched_overhead_pct": round(100 * sched_t / dt, 2),
        "steps": len(lat), "kv_util_peak": util,
    }


def _poisson_schedule(vocab, prompt_len, n_arrivals, rate_hz, seed=3):
    """The shared Poisson arrival schedule (fixed seed): every dynamic
    serving contender — frame loop, speculative frame loop, host step loop —
    must measure against the SAME (prompts, offsets), or the side-by-side
    columns stop being comparable."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]
    gaps = rng.exponential(1.0 / rate_hz, n_arrivals)
    gaps[0] = 0.0
    return prompts, np.cumsum(gaps)


def _wallclock_arrivals(prompts, offsets, t_start):
    """serve() arrivals clock: each poll yields whatever the schedule says
    is due by now (possibly nothing)."""
    nxt = 0
    while nxt < len(prompts):
        now = time.perf_counter() - t_start
        due = []
        while nxt < len(prompts) and offsets[nxt] <= now:
            due.append((nxt, prompts[nxt]))
            nxt += 1
        yield due


def bench_mixed_dynamic(model_name, batch, prompt_len, new_tokens,
                        n_arrivals=32, rate_hz=40.0, frame_steps=8):
    """Dynamic arrivals (Poisson, fixed seed): the frame-based serve() loop
    vs the host-driven step() loop on the SAME arrival schedule. This is the
    workload the frame loop exists for — mixed-splitfuse showed the host
    step loop at ~1/9.5 of the statically-compiled path; here both
    contenders ingest mid-stream arrivals, so the gap this tracks is pure
    host-scheduling overhead, not admission capability."""
    from deepspeed_tpu.inference.v2.ragged_manager import DeviceSlotTable
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    prompts, offsets = _poisson_schedule(eng.model.cfg.vocab_size, prompt_len,
                                         n_arrivals, rate_hz)

    def run_frames():
        """serve() with wall-clock Poisson arrivals; returns (produced, dt,
        device_time) — dt - device_time is the host boundary cost."""
        arrivals = _wallclock_arrivals(prompts, offsets, time.perf_counter())
        dev_box = [0.0]
        orig_run = DeviceSlotTable.run_frame

        def timed_run(self, *a, **kw):
            s = time.perf_counter()
            out = orig_run(self, *a, **kw)
            dev_box[0] += time.perf_counter() - s
            return out

        DeviceSlotTable.run_frame = timed_run
        produced = 0
        try:
            t0 = time.perf_counter()
            for _uid, toks in eng.serve(arrivals, max_new_tokens=new_tokens,
                                        frame_steps=frame_steps):
                produced += len(toks)
            dt = time.perf_counter() - t0
        finally:
            DeviceSlotTable.run_frame = orig_run
        return produced, dt, dev_box[0]

    def run_host_steps():
        """The pre-frame-loop contender: put()+step() per token, same
        schedule, same admission control as serve() (full prompt+budget
        block reservation, FIFO deferral when the pool can't hold it —
        step() grows KV lazily, so without the reservation an over-admitted
        batch dies mid-decode)."""
        live, counts, produced = set(), {}, 0
        queue, nxt = [], 0
        final = prompt_len + new_tokens + 1

        def can_admit():
            growth = sum(eng.kv.blocks_for(final) -
                         len(eng.state.seqs[u].blocks) for u in live)
            return (len(live) < batch and
                    eng.kv.free_blocks - growth >= eng.kv.blocks_for(final))

        t0 = time.perf_counter()
        while nxt < n_arrivals or queue or live:
            now = time.perf_counter() - t0
            while nxt < n_arrivals and offsets[nxt] <= now:
                queue.append(nxt)
                nxt += 1
            while queue and can_admit():
                u = queue.pop(0)
                eng.put([u], [prompts[u]])
                counts[u] = 0
                live.add(u)
            if not live:
                continue
            out = eng.step()
            for u, _t in out.items():
                counts[u] += 1
                if counts[u] >= new_tokens:
                    eng.state.seqs[u].done = True
                    produced += counts[u]
                    eng.flush([u])
                    live.discard(u)
        return produced, time.perf_counter() - t0

    run_frames()                                      # compile both widths
    f_produced, f_dt, f_dev = run_frames()
    # telemetry state of the measured run: TTFT/ITL/E2E/queue-wait
    # percentile summaries ride in the bench JSON
    telemetry = {
        "latency_ms": eng.telemetry.latency_ms(),
        # run-AVERAGE occupancy and run-PEAK KV pressure (the live gauges
        # hold the near-empty final drain frame's figures, useless for
        # comparing configurations)
        "occupancy_avg": eng.telemetry.snapshot()["derived"]["occupancy_avg"],
        "kv_blocks_in_use_peak":
            eng.telemetry.gauges["kv_blocks_in_use_peak"],
        "admission_deferrals": eng.telemetry.counters["admission_deferrals"],
        "recompiled_programs": eng.runner.compile_count_total(),
    }
    run_host_steps()                                  # compile
    h_produced, h_dt = run_host_steps()
    return {
        "workload": "mixed-splitfuse-dynamic", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals, "arrival_rate_hz": rate_hz,
        "frame_steps": frame_steps,
        "frame_tok_per_sec": round(f_produced / f_dt, 1),
        "sched_overhead_pct": round(100 * (f_dt - f_dev) / f_dt, 2),
        "telemetry": telemetry,
        "host_step_tok_per_sec": round(h_produced / h_dt, 1),
        "frame_speedup": round((f_produced / f_dt) / (h_produced / h_dt), 2),
        "note": "same Poisson schedule for both loops; frame_tok_per_sec is "
                "the device-resident frame loop (host touches the loop only "
                "at frame boundaries), host_step_tok_per_sec the per-step "
                "host scheduler this PR retires for dynamic traffic",
    }


def bench_mixed_dynamic_spec(model_name, batch, prompt_len, new_tokens,
                             n_arrivals=32, rate_hz=40.0, frame_steps=8,
                             gamma=2):
    """Speculative decoding on the frame carry, measured on the SAME
    mixed-splitfuse-dynamic Poisson schedule as the non-speculative frame
    loop and the host step loop (same seed => identical arrival offsets).

    The draft is a SELF-draft (draft == target params): the high-acceptance
    upper bound, so ``tokens_per_target_forward`` approaches gamma+1 and the
    row isolates the architecture win (fewer target forwards per emitted
    token, zero extra host<->device transfers inside a frame) from draft
    quality. Wall-clock speedup additionally depends on the draft/target
    cost ratio — a self-draft pays the full target cost per proposal, so on
    real deployments expect a small draft and read acceptance_rate +
    tokens_per_target_forward to size the win."""
    base = bench_mixed_dynamic(model_name, batch, prompt_len, new_tokens,
                               n_arrivals=n_arrivals, rate_hz=rate_hz,
                               frame_steps=frame_steps)
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    eng.attach_draft(eng.model, eng.params)
    prompts, offsets = _poisson_schedule(eng.model.cfg.vocab_size, prompt_len,
                                         n_arrivals, rate_hz)

    def run_spec():
        arrivals = _wallclock_arrivals(prompts, offsets, time.perf_counter())
        produced = 0
        t0 = time.perf_counter()
        for _uid, toks in eng.serve(arrivals, max_new_tokens=new_tokens,
                                    frame_steps=frame_steps, gamma=gamma):
            produced += len(toks)
        return produced, time.perf_counter() - t0

    run_spec()                                     # compile both widths
    produced, dt = run_spec()
    sp = eng.serve_stats["spec"]
    spec_tps = round(produced / dt, 1)
    return {
        "workload": "mixed-splitfuse-dynamic-spec", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals, "arrival_rate_hz": rate_hz,
        "frame_steps": frame_steps, "gamma": gamma, "draft": "self",
        "acceptance_rate": sp["acceptance_rate"],
        "tokens_per_target_forward": sp["tokens_per_target_forward"],
        "spec_frame_tok_per_sec": spec_tps,
        "frame_tok_per_sec": base.get("frame_tok_per_sec"),
        "host_step_tok_per_sec": base.get("host_step_tok_per_sec"),
        "spec_vs_frame_speedup": round(
            spec_tps / base["frame_tok_per_sec"], 2)
            if base.get("frame_tok_per_sec") else None,
        "spec_vs_host_step_speedup": round(
            spec_tps / base["host_step_tok_per_sec"], 2)
            if base.get("host_step_tok_per_sec") else None,
        "note": "same Poisson schedule for all three loops; the self-draft "
                "row bounds acceptance from above — wall-clock speedup on "
                "real serving scales with (1 + acceptance*gamma) / "
                "(1 + gamma*draft_cost_ratio)",
    }


def bench_telemetry_overhead(model_name, batch, prompt_len, new_tokens,
                             n_arrivals=16, repeats=5, assert_budget=False):
    """Telemetry-on vs telemetry-off serving throughput on an IDENTICAL
    deterministic arrival schedule (one arrival per frame-boundary poll — no
    wall clock, so both modes see byte-identical admission timing).

    The in-graph counters are always compiled into the frame, so the delta
    isolates exactly the host stats path this PR adds: the per-frame counter
    sync, lifecycle histograms, and view updates. ``repeats`` paired rounds
    in balanced order; the reported overhead is the geometric mean of the
    per-order median on/off ratios (see the inline measurement notes). In
    the smoke configuration (``assert_budget=True``) the run FAILS if that
    estimate exceeds 2% — the telemetry budget is a tested contract, not an
    aspiration."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 1000, (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def run_once(eng):
        def arrivals():
            for u, p in enumerate(prompts):
                yield [(u, p)]
        produced = 0
        t0 = time.perf_counter()
        for _uid, toks in eng.serve(arrivals(), max_new_tokens=new_tokens):
            produced += len(toks)
        return produced, time.perf_counter() - t0

    # both modes on ONE engine (identical compiled programs — the in-graph
    # counters are always part of the frame), measured as PAIRED rounds:
    # each round times on and off back to back and contributes one on/off
    # ratio, so box-wide slowdowns (shared-CPU noise dwarfs the µs-scale
    # host stats path at smoke size) hit both halves alike and cancel.
    # Rounds run in BALANCED order (half on-first, half off-first) because
    # the first serve after a mode switch pays a measurable cache penalty
    # on a contended box; the geometric mean of the two per-order medians
    # cancels that bias, which a single median over alternating rounds
    # does not (odd counts leave one order over-represented).
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    run_once(eng)                                     # compile
    ratios = {("on", "off"): [], ("off", "on"): []}
    best = {"on": 1e9, "off": 1e9}
    produced = 0

    def measure_rounds(n):
        nonlocal produced
        for r in range(n):
            dts = {}
            order = ("on", "off") if r % 2 == 0 else ("off", "on")
            for mode in order:
                eng.telemetry.enabled = mode == "on"
                produced, dts[mode] = run_once(eng)
                best[mode] = min(best[mode], dts[mode])
            ratios[order].append(dts["on"] / dts["off"])

    def estimate():
        meds = [statistics.median(v) for v in ratios.values() if v]
        g = 1.0
        for m in meds:
            g *= m
        return 100 * (g ** (1.0 / len(meds)) - 1.0)

    rounds = 2 * ((repeats + 1) // 2)                 # round UP to balanced
    measure_rounds(rounds)
    # one retry pass absorbs a fully contended measurement window before
    # the smoke assert fires (fresh rounds fold into the medians)
    if assert_budget and estimate() >= 2.0:
        measure_rounds(rounds)
    eng.telemetry.enabled = True
    run_once(eng)                                     # telemetry for the row
    tel_summary = eng.telemetry.latency_ms()
    results = {m: {"tok_per_sec": round(produced / b, 1),
                   "best_s": round(b, 4)} for m, b in best.items()}
    all_ratios = [r for v in ratios.values() for r in v]
    overhead_pct = round(estimate(), 2)
    overhead_pct_min = round(100 * (min(all_ratios) - 1.0), 2)
    row = {
        "workload": "telemetry-overhead", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals, "repeats": repeats,
        "paired_rounds_run": len(all_ratios),   # may exceed repeats (retry)
        "telemetry_on_tok_per_sec": results["on"]["tok_per_sec"],
        "telemetry_off_tok_per_sec": results["off"]["tok_per_sec"],
        "overhead_pct": overhead_pct,
        "overhead_pct_min": overhead_pct_min,
        "within_2pct_budget": overhead_pct < 2.0,
        "latency_ms": tel_summary,
        "note": "same deterministic schedule both modes; in-graph counters "
                "are compiled in regardless, so this is the host stats "
                "path alone. overhead_pct = geometric mean of the "
                "per-order median paired on/off ratios (cancels both "
                "box-wide noise and first-runner bias); overhead_pct_min "
                "is the single cleanest round",
    }
    if assert_budget:
        assert overhead_pct < 2.0, \
            f"telemetry overhead {overhead_pct}% exceeds the 2% budget: {row}"
    return row


def bench_tracing_overhead(model_name, batch, prompt_len, new_tokens,
                           n_arrivals=16, repeats=5, assert_budget=False):
    """Tracing-on vs tracing-off serving throughput on an IDENTICAL
    deterministic arrival schedule — the distributed-tracing twin of
    ``bench_telemetry_overhead`` (same paired-round/balanced-order
    measurement; see its inline notes). Telemetry is ENABLED in both
    modes, so the delta isolates exactly what the tracing PR adds: span
    minting, boundary span appends into the ``TraceCollector``, and the
    one-sample-per-trace fleet histograms. Spans are stamped at frame
    boundaries only — the compiled frames are byte-identical either way —
    so the budget is the same <2% contract the telemetry row pins
    (asserted in the smoke configuration, reported on TPU)."""
    from deepspeed_tpu.inference.v2.tracing import TraceCollector
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 1000, (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def run_once(eng):
        def arrivals():
            for u, p in enumerate(prompts):
                yield [(u, p)]
        produced = 0
        t0 = time.perf_counter()
        for _uid, toks in eng.serve(arrivals(), max_new_tokens=new_tokens):
            produced += len(toks)
        return produced, time.perf_counter() - t0

    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + new_tokens)
    collector = TraceCollector(max_traces=64)   # steady-state bounded ring
    run_once(eng)                               # compile
    ratios = {("on", "off"): [], ("off", "on"): []}
    best = {"on": 1e9, "off": 1e9}
    produced = 0

    def measure_rounds(n):
        nonlocal produced
        for r in range(n):
            dts = {}
            order = ("on", "off") if r % 2 == 0 else ("off", "on")
            for mode in order:
                eng.telemetry.set_tracer(
                    collector if mode == "on" else None, replica="bench")
                produced, dts[mode] = run_once(eng)
                best[mode] = min(best[mode], dts[mode])
            ratios[order].append(dts["on"] / dts["off"])

    def estimate():
        meds = [statistics.median(v) for v in ratios.values() if v]
        g = 1.0
        for m in meds:
            g *= m
        return 100 * (g ** (1.0 / len(meds)) - 1.0)

    rounds = 2 * ((repeats + 1) // 2)
    measure_rounds(rounds)
    if assert_budget and estimate() >= 2.0:
        measure_rounds(rounds)                  # retry absorbs a noisy window
    eng.telemetry.set_tracer(None)
    all_ratios = [r for v in ratios.values() for r in v]
    overhead_pct = round(estimate(), 2)
    results = {m: {"tok_per_sec": round(produced / b, 1),
                   "best_s": round(b, 4)} for m, b in best.items()}
    snap = collector.snapshot()
    row = {
        "workload": "tracing-overhead", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals, "repeats": repeats,
        "paired_rounds_run": len(all_ratios),
        "tracing_on_tok_per_sec": results["on"]["tok_per_sec"],
        "tracing_off_tok_per_sec": results["off"]["tok_per_sec"],
        "overhead_pct": overhead_pct,
        "overhead_pct_min": round(100 * (min(all_ratios) - 1.0), 2),
        "within_2pct_budget": overhead_pct < 2.0,
        "traces_minted": snap["counters"]["traces_minted"],
        "spans_recorded": snap["counters"]["spans_recorded"],
        "fleet_ttft_ms": snap["fleet_ttft_ms"],
        "note": "same deterministic schedule both modes, telemetry ON in "
                "both — the delta is span production + collection alone "
                "(frame-boundary stamps, no compiled-program change). "
                "Measurement = geometric mean of per-order median paired "
                "on/off ratios, the telemetry row's estimator",
    }
    if assert_budget:
        assert overhead_pct < 2.0, \
            f"tracing overhead {overhead_pct}% exceeds the 2% budget: {row}"
    return row


def bench_scheduler(model_name, batch, prompt_len, new_tokens,
                    slo_ttft_ms=None):
    """FIFO vs SLO-aware scheduling under a DETERMINISTIC 2-tenant overload
    schedule (arrivals keyed to frame-boundary polls, no wall clock, so
    both modes see identical admission opportunities):

    * tenant "bulk" front-loads a burst of 2x-slot-count best-effort long
      jobs that saturates the table and queues deep (its queue quota sheds
      the deepest arrivals deterministically);
    * tenant "chat" then streams short interactive requests with a TTFT
      SLO.

    FIFO serves the burst in arrival order, so every chat request waits
    behind bulk; the scheduler jumps chat over the queue and preempts live
    bulk rows (plus SLO shedding/deferral and frame shrinking when the
    measured TTFT p90 actually breaches the target — wall-clock-dependent,
    so the deterministic shed in this row comes from the bulk queue
    quota). Per-class TTFT p90 comes from recorded spans, computed
    identically for both modes; goodput counts retired tokens only (shed
    work produces nothing)."""
    import jax
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    # the SLO target is meant to be breachable-but-sane for the platform;
    # CPU smoke frames are ~ms-scale, so a TPU-grade 50 ms target would
    # just pin the control loop at critical and measure compile noise
    if slo_ttft_ms is None:
        slo_ttft_ms = 50.0 if jax.default_backend() == "tpu" else 1000.0
    n_slots = batch
    n_bulk, n_chat = 2 * batch, batch
    # bulk jobs must OUTLIVE many frames (that is what makes the burst an
    # overload instead of a blip): several frames' worth of decode budget
    bulk_new = 6 * new_tokens
    chat_new = max(4, new_tokens // 2)
    eng = _mk_engine(model_name, batch,
                     expected_context=prompt_len + bulk_new)
    eng.telemetry.record_spans = True
    rng = np.random.default_rng(11)
    vocab = eng.model.cfg.vocab_size
    bulk_p = [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
              for _ in range(n_bulk)]
    chat_p = [rng.integers(0, vocab, (prompt_len // 4,)).astype(np.int32)
              for _ in range(n_chat)]
    classes = {u: "best_effort" for u in range(n_bulk)}
    classes.update({n_bulk + i: "interactive" for i in range(n_chat)})

    def arrivals():
        yield [{"uid": u, "tokens": bulk_p[u], "max_new_tokens": bulk_new,
                "tenant": "bulk", "priority": "best_effort"}
               for u in range(n_bulk)]
        for i in range(n_chat):
            yield []
            yield [{"uid": n_bulk + i, "tokens": chat_p[i],
                    "max_new_tokens": chat_new, "tenant": "chat",
                    "priority": "interactive", "slo_ms": slo_ttft_ms}]

    def mk_sched():
        return RequestScheduler(SchedulerConfig(
            slo_ttft_ms=slo_ttft_ms,
            tenant_weights={"chat": 2.0, "bulk": 1.0},
            # bulk may queue at most one table's worth beyond its live
            # rows; the burst's tail sheds with a structured reason
            tenant_max_queued=n_slots, aging_frames=16))

    def run(scheduler):
        produced = 0
        t0 = time.perf_counter()
        for _uid, toks in eng.serve(arrivals(), max_new_tokens=new_tokens,
                                    frame_slots=n_slots,
                                    scheduler=scheduler):
            produced += len(toks)
        dt = time.perf_counter() - t0
        spans = {s["uid"]: s for s in eng.telemetry.spans}
        ttft = {"interactive": [], "best_effort": []}
        for u, cls in classes.items():
            s = spans.get(u)
            if s is not None and s.get("first_token_t") is not None:
                ttft[cls].append((s["first_token_t"] - s["enqueue_t"]) * 1e3)
        eng.telemetry.spans.clear()
        out = {
            "goodput_tok_per_sec": round(produced / dt, 1),
            "completed_requests": len(spans),
        }
        for cls, vals in ttft.items():
            out[f"{cls}_ttft_p90_ms"] = round(
                float(np.percentile(vals, 90)), 2) if vals else None
            out[f"{cls}_completed"] = len(vals)
        return out

    # warm BOTH paths (the scheduler run compiles extra programs: the
    # re-prefill prompt bucket after a preemption, pressure-capped frame
    # steps) so neither timed run pays compile
    run(None)
    run(mk_sched())
    eng.telemetry.spans.clear()
    fifo = run(None)
    sched = mk_sched()
    slo = run(sched)
    submitted = n_bulk + n_chat
    slo.update({
        "shed_requests": sched.stats()["shed_total"],
        "shed_rate": round(sched.stats()["shed_total"] / submitted, 4),
        "preempted": sched.stats()["preempted"],
        "admitted_by_class": sched.stats()["admitted_by_class"],
        "slo_risk_final": sched.stats()["risk"],
    })
    fi, si = fifo["interactive_ttft_p90_ms"], slo["interactive_ttft_p90_ms"]
    return {
        "workload": "scheduler-slo", "batch": batch, "slots": n_slots,
        "prompt_len": prompt_len, "bulk_new_tokens": bulk_new,
        "chat_new_tokens": chat_new,
        "bulk_requests": n_bulk, "chat_requests": n_chat,
        "slo_ttft_ms": slo_ttft_ms,
        "fifo": fifo, "slo_aware": slo,
        "interactive_ttft_p90_speedup": round(fi / si, 2)
        if fi and si else None,
        "note": "deterministic 2-tenant overload, identical arrival "
                "schedule both modes; goodput counts retired tokens only "
                "(shed best-effort work produces none). The SLO row should "
                "show interactive TTFT p90 well under FIFO's — chat "
                "arrivals jump the bulk queue and preempt live bulk rows "
                "— at the cost of shed/deferred bulk work",
    }


def bench_chaos(model_name, batch, prompt_len, new_tokens, n_arrivals=12):
    """Fault-tolerant serving under a FIXED fault schedule vs the
    fault-free baseline, on one deterministic arrival schedule (one
    arrival per frame-boundary poll — no wall clock, so both runs see
    identical admission timing).

    Three measured legs:

    * **baseline** — fault-free serve (goodput reference);
    * **chaos** — same schedule under transient dispatch failures
      (absorbed by bounded retry), one poisoned row (quarantined
      mid-flight), and a KV-alloc failure window (admission deferral);
      overhead = the goodput cost of surviving all of it;
    * **kill+resume** — same schedule again, crashed by a fatal dispatch
      fault mid-run, then resumed from the automatic ledger snapshot;
      reports the recovery-time gauge and end-to-end goodput including
      the crash.

    Correctness is asserted inline (surviving outputs token-identical to
    the baseline, KV pool drained) — the chaos row doubles as a smoke
    check, mirroring the telemetry-overhead row's tested-contract style."""
    from deepspeed_tpu.inference.v2.faults import (FaultInjector,
                                                   FrameDispatchError)
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, 1000, (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def arrivals():
        for u, p in enumerate(prompts):
            yield [(u, p)]

    def mk():
        eng = _mk_engine(model_name, batch,
                         expected_context=prompt_len + new_tokens)
        eng._config.frame_retry_backoff_s = 0.0   # measure work, not sleep
        return eng

    def run(eng, faults=None, resume_from=None, arr=None):
        outs, produced = {}, 0
        t0 = time.perf_counter()
        for uid, toks in eng.serve(arr if arr is not None else arrivals(),
                                   max_new_tokens=new_tokens, faults=faults,
                                   resume_from=resume_from):
            outs[uid] = toks
            produced += len(toks)
        return outs, produced, time.perf_counter() - t0

    eng = mk()
    run(eng)                                         # compile
    base_outs, base_produced, base_dt = run(eng)

    poison_uid = n_arrivals // 2
    chaos_schedule = [
        {"kind": "dispatch_exception", "frame": 2, "times": 2},
        {"kind": "poison_row", "frame": n_arrivals // 2, "uid": poison_uid},
        {"kind": "kv_alloc_fail", "frame": 4, "times": 2},
    ]
    inj = FaultInjector(chaos_schedule)
    chaos_outs, chaos_produced, chaos_dt = run(eng, faults=inj)
    assert poison_uid not in chaos_outs, "poisoned row must not be yielded"
    for u, toks in chaos_outs.items():
        np.testing.assert_array_equal(base_outs[u], toks,
                                      err_msg=f"uid={u} diverged under chaos")
    assert eng.kv.free_blocks == eng.kv.num_blocks - 1
    chaos_counters = {k: eng.telemetry.counters[k]
                      for k in ("faults", "quarantined", "frame_retries",
                                "deadline_expired")}

    # ---- kill + resume: fatal fault mid-run, resume from the snapshot ----
    fatal = FaultInjector([{"kind": "dispatch_exception",
                            "frame": n_arrivals // 2, "times": 100}])
    resumed_outs, produced_crash = {}, 0
    t0 = time.perf_counter()
    try:
        for uid, toks in eng.serve(arrivals(), max_new_tokens=new_tokens,
                                   faults=fatal):
            resumed_outs[uid] = toks
            produced_crash += len(toks)
        raise AssertionError("fatal fault schedule did not crash the serve")
    except FrameDispatchError:
        pass
    snap = eng.last_crash_snapshot
    in_flight = len(snap["requests"])
    rest, produced_rest, _ = run(eng, resume_from=snap, arr=iter([[]]))
    resume_dt = time.perf_counter() - t0
    resumed_outs.update(rest)
    for u, toks in resumed_outs.items():
        np.testing.assert_array_equal(
            base_outs[u], toks, err_msg=f"uid={u} diverged across restart")
    # arrivals the crashed run never polled are the front-end's to replay;
    # completeness here covers everything the engine had accepted
    recovery_ms = eng.telemetry.gauges["last_recovery_ms"]

    base_tps = base_produced / base_dt
    chaos_tps = chaos_produced / chaos_dt
    resume_tps = (produced_crash + produced_rest) / resume_dt
    return {
        "workload": "chaos-serving", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals,
        "fault_schedule": chaos_schedule,
        "baseline_tok_per_sec": round(base_tps, 1),
        "chaos_tok_per_sec": round(chaos_tps, 1),
        # per-token time under chaos vs baseline (goodput-normalized, so
        # the quarantined row's missing tokens don't read as overhead)
        "chaos_overhead_pct": round(
            100 * ((chaos_dt / chaos_produced)
                   / (base_dt / base_produced) - 1), 2)
        if chaos_produced else None,
        "chaos_goodput_ratio": round(chaos_tps / base_tps, 4),
        "chaos_counters": chaos_counters,
        "kill_resume": {
            "in_flight_at_crash": in_flight,
            "recovery_ms": recovery_ms,
            "goodput_tok_per_sec": round(resume_tps, 1),
            "goodput_ratio_vs_baseline": round(resume_tps / base_tps, 4),
            "recoveries": eng.telemetry.counters["recoveries"],
        },
        "note": "same deterministic schedule all three legs; chaos leg "
                "survives 2 transient dispatch failures + 1 poisoned row "
                "+ a 2-boundary KV-alloc outage (survivor outputs asserted "
                "token-identical, pool drain asserted); kill+resume leg "
                "crashes mid-run and resumes from the automatic ledger "
                "snapshot (outputs asserted token-identical across the "
                "restart)",
    }


def bench_router(model_name, batch, prompt_len, new_tokens, n_arrivals=12):
    """Multi-engine router: fleet goodput under a deterministic engine-kill
    schedule vs the no-failure fleet baseline, on one deterministic arrival
    schedule (one arrival per router tick, every request pinned to ONE
    replica by session affinity so the kill actually orphans work).

    Three measured legs:

    * **single** — one engine, no router (the pre-PR reference; its greedy
      outputs are THE parity target for both fleet legs);
    * **fleet** — two replicas behind ``EngineRouter``, fault-free
      (placement + cooperative stepping overhead);
    * **kill+failover** — same schedule, the affinity-pinned replica
      hard-killed mid-stream by the scripted ``RouterFaultInjector``; the
      router splits its snapshot per-request and re-admits everything on
      the survivor. Reports the kill/baseline goodput ratio and the
      router's failover ``recovery_ms`` (last kill -> every orphaned
      request re-placed on a healthy peer's feed).

    Correctness is asserted inline (every accepted request completes on
    every leg, token-identical to the single-engine run; zero
    requests_failed; the victim ends quarantined) — the row doubles as a
    smoke check, mirroring bench_chaos's tested-contract style."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.faults import RouterFaultInjector
    from deepspeed_tpu.inference.v2.router import (EngineRouter,
                                                   RouterConfig, QUARANTINED)
    from deepspeed_tpu.models import build_model

    # one model + params shared by every replica: heterogeneous DEGREES are
    # the tests' business (tp=1<->tp=8 under the multichip marker); the
    # bench measures routing overhead and failover, which need identical
    # weights for the token-identity asserts to mean anything
    model = build_model(model_name)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(29)
    prompts = [rng.integers(0, model.cfg.vocab_size - 5,
                            (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def arrivals():
        # dict arrivals, ALL up front, one session: affinity pins the
        # whole stream to a single replica and the front-loaded queue
        # (slots < arrivals) guarantees the tick-3 kill orphans live rows
        # AND queued work — the failover path under real load, not a kill
        # of an already-idle replica
        yield [{"uid": u, "tokens": p, "session": "pinned"}
               for u, p in enumerate(prompts)]

    def mk():
        # slots below the arrival count build a real queue; small frames
        # keep requests in flight across several router ticks
        cfg = RaggedInferenceEngineConfig(
            max_ragged_batch_size=batch,
            max_tokens_per_step=max(batch * 2, 768),
            frame_steps=2,
            expected_context=prompt_len + new_tokens,
            expected_concurrency=batch)
        eng = InferenceEngineV2(model, cfg, params=params,
                                max_seq_len=prompt_len + new_tokens + 2)
        eng._config.frame_retry_backoff_s = 0.0   # measure work, not sleep
        return eng

    engines = {"a": mk(), "b": mk()}

    def run(router=None, faults=None):
        src = engines["a"].serve(arrivals(), max_new_tokens=new_tokens) \
            if router is None else \
            router.serve(arrivals(), max_new_tokens=new_tokens,
                         faults=faults)
        outs, produced = {}, 0
        t0 = time.perf_counter()
        for uid, toks in src:
            outs[uid] = toks
            produced += len(toks)
        return outs, produced, time.perf_counter() - t0

    run()                                            # compile engine a
    # compile engine b too (the failover leg lands everything on it; a
    # cold survivor would bill its frame compiles to recovery)
    outs_b, _, _ = run(EngineRouter({"b": engines["b"]}))
    base_outs, base_produced, base_dt = run()
    for u, toks in outs_b.items():
        np.testing.assert_array_equal(
            base_outs[u], toks, err_msg=f"uid={u}: replicas diverged")

    # backoff must exceed the WORST-CASE run length in ticks (the big TPU
    # workload runs for hundreds of decode ticks): if the victim rejoins
    # mid-run, the final QUARANTINED assert below fails even though
    # failover itself worked
    mk_router = lambda: EngineRouter(    # noqa: E731 — two identical legs
        engines, RouterConfig(quarantine_backoff_ticks=1 << 20))
    fleet_outs, fleet_produced, fleet_dt = run(mk_router())
    for u, toks in fleet_outs.items():
        np.testing.assert_array_equal(
            base_outs[u], toks, err_msg=f"uid={u} diverged behind router")

    router = mk_router()
    victim = router._pick("pinned")
    inj = RouterFaultInjector(
        [{"kind": "engine_kill", "tick": 3, "engine": victim}])
    kill_outs, kill_produced, kill_dt = run(router, faults=inj)
    for u, toks in kill_outs.items():
        np.testing.assert_array_equal(
            base_outs[u], toks,
            err_msg=f"uid={u} diverged across kill+failover")
    assert set(kill_outs) == set(base_outs), \
        "every accepted request must complete across the failover"
    st = router.stats()
    assert st["counters"]["requests_failed"] == 0
    assert st["counters"]["engine_kills"] == 1
    assert st["counters"]["reroutes"] >= 1, \
        "the kill must orphan in-flight work (else the leg measured nothing)"
    assert st["replicas"][victim] == QUARANTINED
    for eng in engines.values():
        assert eng.kv.free_blocks == eng.kv.num_blocks - 1, \
            "KV pool must drain on every replica"

    base_tps = base_produced / base_dt
    fleet_tps = fleet_produced / fleet_dt
    kill_tps = kill_produced / kill_dt
    return {
        "workload": "router-failover", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals, "replicas": 2,
        "kill_schedule": [{"kind": "engine_kill", "tick": 3,
                           "engine": victim}],
        "single_engine_tok_per_sec": round(base_tps, 1),
        "fleet_tok_per_sec": round(fleet_tps, 1),
        "fleet_goodput_ratio": round(fleet_tps / base_tps, 4),
        "kill_tok_per_sec": round(kill_tps, 1),
        "kill_goodput_ratio": round(kill_tps / base_tps, 4),
        "recovery_ms": router.last_recovery_ms,
        "router_counters": {k: st["counters"][k]
                            for k in ("placements", "failovers", "reroutes",
                                      "completions", "requests_failed")},
        "note": "same deterministic pinned-session schedule all three "
                "legs; fleet leg measures routing overhead (one engine "
                "does the work — affinity pins the session), kill leg "
                "hard-kills the pinned replica at tick 3 and fails every "
                "in-flight request over to the survivor via per-request "
                "snapshot split (outputs asserted token-identical to the "
                "single-engine run, zero requests_failed); recovery_ms is "
                "kill -> all orphans re-placed, excluding the survivor's "
                "own re-prefill (its recovery gauges cover that)",
    }


def bench_disagg(model_name, batch, long_prompt, short_prompt,
                 long_new, short_new, n_long=5, n_short=8,
                 assert_contract=True, model_overrides=None, chunk=None):
    """Disaggregated prefill/decode fleet vs the monolithic fleet at
    EQUAL replica count, on one deterministic long-prompt/short-decode
    mix (the workload disaggregation exists for: long prefills stall a
    monolithic replica's frame boundary — every decode row coasting in
    its wide frames pays chunk-sized steps — while a decode replica that
    never sees a wide frame streams at width-1 cost).

    Three measured legs, same arrival schedule:

    * **single** — one unified engine (greedy outputs are THE parity
      target for both fleets);
    * **mono fleet** — two unified replicas behind ``EngineRouter``
      (every replica does both jobs);
    * **disagg fleet** — one prefill + one decode replica over a SHARED
      ``KVSwapTier``: prefill-heavy arrivals route to the prefill
      replica, which publishes committed pages at the watermark and
      hands off; the decode replica restores the pages and streams.

    Reports fleet-merged TTFT p90 and decode ITL p90 per leg — EXACT
    percentiles over raw samples, measured on per-replica BUSY-TIME
    clocks (each engine's clock advances only while its own frames run:
    the latency a thread-per-replica driver delivers, since the serial
    cooperative router would sum every replica's frame into every
    wall-clock gap and mask exactly the contention disaggregation
    removes; resumed continuations record no TTFT, so a handoff
    request's TTFT is its true first token on the prefill side). Each
    fleet leg is the MEDIAN of 5 interleaved rounds. ASSERTS (CPU smoke)
    the tentpole contract: the disagg fleet improves BOTH percentiles vs
    the mono fleet — operationalized as winning the strict MAJORITY of
    PAIRED rounds per metric (round i's legs run back-to-back, so the
    pairing cancels the slow shared-box drift that leaks into aggregate
    medians) — with all outputs token-identical to the single engine. The CPU-smoke margins are modest (a few percent on latency,
    ~1.4x throughput): the stock tiny model's frames are
    dispatch-overhead-bound, so the wide-frame FLOP tax the architecture
    removes is mostly invisible here — the real-chip economics (a chunk-
    wide frame costs chunk x a decode frame) are where the split pays."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier
    from deepspeed_tpu.inference.v2.router import EngineRouter, RouterConfig
    from deepspeed_tpu.inference.v2.telemetry import LogBucketHistogram
    from deepspeed_tpu.models import build_model
    import tempfile

    # finer latency buckets for THIS bench: the telemetry default (x2
    # geometric growth) quantizes p90 to within a factor of 2 — a real
    # 1.5-2x fleet-level gap can land both legs in one bucket and read
    # as a tie. 1.15x growth resolves ~15% differences; restored in the
    # finally below so no other row inherits it.
    growth_defaults = LogBucketHistogram.__init__.__defaults__
    LogBucketHistogram.__init__.__defaults__ = (1e-4, 1.15, 120)
    # ...and keep RAW samples beside the buckets: the percentile CONTRACT
    # below compares two fleets whose true gap can sit inside one bucket —
    # exact sample percentiles make a tie mean "actually equal", not
    # "same bucket". Restored in the finally.
    _orig_record = LogBucketHistogram.record

    def _recording(self, value, count=1):
        _orig_record(self, value, count)
        if count > 0:
            self._raw = getattr(self, "_raw", [])
            self._raw.extend([value] * count)

    LogBucketHistogram.record = _recording

    try:
        model = build_model(model_name, **(model_overrides or {}))
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(31)
        chunk = chunk or max(16, long_prompt // 8)
        longs = {u: rng.integers(0, model.cfg.vocab_size - 5,
                                 (long_prompt,)).astype(np.int32)
                 for u in range(n_long)}
        shorts = {100 + u: rng.integers(0, model.cfg.vocab_size - 5,
                                        (short_prompt,)).astype(np.int32)
                  for u in range(n_short)}

        def arrivals():
            # a realistic interactive mix: BURSTS of short requests (>90%
            # of arrivals — the population whose p90 the SLO story is
            # about; bursty admission matters because the frame width is
            # global, so one boundary admits a whole burst with a single
            # chunk-wide frame instead of going wide every tick) with
            # long prompts dripped in between bursts. On the mono fleet
            # each long stretches its replica's frames to chunk width for
            # the whole prefill, taxing every short decoding beside it;
            # concurrency stays under the slot count so queueing never
            # masks the frame-latency effect.
            items = list(shorts.items())
            long_items = list(longs.items())
            burst = max(4, n_short // max(1, n_long + 1))
            burst_every = max(6, short_new // 2)
            long_every = max(2, (n_long + 1 and
                                 (burst_every * (n_long + 2)) //
                                 max(1, n_long + 1)))
            tick = 0
            while items or long_items:
                b = []
                if items and tick % burst_every == 0:
                    for _ in range(burst):
                        if items:
                            u, t = items.pop(0)
                            b.append({"uid": u, "tokens": t,
                                      "max_new_tokens": short_new})
                if long_items and tick % long_every == long_every // 2:
                    u, t = long_items.pop(0)
                    b.append({"uid": u, "tokens": t,
                              "max_new_tokens": long_new})
                yield b
                tick += 1

        def mk(**over):
            kw = dict(max_ragged_batch_size=batch,
                      max_tokens_per_step=max(batch * 2, 768),
                      prefill_chunk_size=chunk, frame_steps=2,
                      expected_context=long_prompt + short_new,
                      expected_concurrency=batch)
            kw.update(over)
            eng = InferenceEngineV2(
                model, RaggedInferenceEngineConfig(**kw), params=params,
                max_seq_len=long_prompt + max(long_new, short_new) + 2)
            eng._config.frame_retry_backoff_s = 0.0
            return eng

        def merged_p90_ms(engines, name):
            raw = [v for e in engines
                   for v in getattr(e.telemetry.hists[name], "_raw", [])]
            if not raw:
                return None
            return round(float(np.percentile(np.asarray(raw), 90)) * 1e3, 3)

        class _BusyClock:
            """Per-replica BUSY-TIME clock: advances only while THIS
            engine's frames execute. The serial cooperative router sums
            every replica's frame into every wall-clock gap — both legs
            would measure the same tick time, masking exactly the
            contention disaggregation removes. Busy time is the latency a
            thread-per-replica driver (ROADMAP item 2a) delivers: a
            decode row's inter-token gap is ITS replica's frame time, so
            a monolithic replica's wide prefill frames tax its decode
            stream and a disaggregated decode replica's never do."""

            def __init__(self):
                self.t = 0.0

            def __call__(self):
                return self.t

        def attach_busy_clock(eng):
            clk = _BusyClock()
            orig = eng._run_frame_resilient

            def timed(slots, width, steps, greedy, draft, faults, frame):
                t0 = time.perf_counter()
                try:
                    return orig(slots, width, steps, greedy, draft,
                                faults, frame)
                finally:
                    clk.t += time.perf_counter() - t0

            eng._run_frame_resilient = timed
            eng._clock = clk
            eng.telemetry.clock = clk

        def run(src):
            outs, produced = {}, 0
            t0 = time.perf_counter()
            for uid, toks in src:
                outs[uid] = toks
                produced += len(toks)
            return outs, produced, time.perf_counter() - t0

        # --- single engine: compile + parity base ---
        single = mk()
        run(single.serve(arrivals(), max_new_tokens=short_new))  # compile pass
        base_outs, base_produced, base_dt = run(
            single.serve(arrivals(), max_new_tokens=short_new))

        def mk_timed(**over):
            eng = mk(**over)
            attach_busy_clock(eng)
            return eng

        def leg(engines, router_cfg=None):
            router = EngineRouter(engines, router_cfg or RouterConfig())
            outs, produced, dt = run(
                router.serve(arrivals(), max_new_tokens=short_new))
            for u, toks in outs.items():
                np.testing.assert_array_equal(
                    base_outs[u], toks, err_msg=f"uid={u} diverged")
            assert set(outs) == set(base_outs), \
                "every accepted request must complete"
            engs = [r.engine for r in router._replicas.values()]
            row = {
                "tok_per_sec": round(produced / dt, 1),
                "ttft_p90_ms": merged_p90_ms(engs, "ttft"),
                "itl_p90_ms": merged_p90_ms(engs, "itl"),
                "counters": {k: router.counters[k]
                             for k in ("placements", "handoffs",
                                       "requests_failed")},
            }
            if router._tier is not None:
                row["tier"] = dict(router._tier.stats)
            for e in engs:
                e.telemetry.set_base_labels(engine=None, model=None, role=None)
            return row

        # --- mono fleet: two unified replicas (compile both) ---
        mono_engines = {"u0": mk_timed(), "u1": mk_timed()}
        leg(dict(mono_engines))                                  # compile pass

        # --- disagg fleet: prefill + decode over one shared tier ---
        pe = mk_timed(role="prefill")
        de = mk_timed(role="decode")
        disagg_engines = {"prefill": pe, "decode": de}
        cfg = RouterConfig(prefill_route_min_prompt=min(64, long_prompt))

        def fresh_tier():
            # a FRESH tier per pass: an earlier pass's prefix records would
            # otherwise let the next pass admit its prompts at the
            # watermark (warm-tier advantage the mono leg doesn't get)
            t = KVSwapTier(tempfile.mkdtemp(prefix="dstpu_disagg_tier_"),
                           shared=True)
            pe.attach_kv_tier(t, tag="p")
            de.attach_kv_tier(t, tag="d")
            return t

        fresh_tier()
        leg(dict(disagg_engines), cfg)                           # compile pass

        # measured rounds, INTERLEAVED (mono, disagg, mono, disagg, ...)
        # with per-leg MEDIANS: single wall-clock rounds on a shared box
        # swing several-fold (the telemetry-overhead bench's lesson), and
        # the percentile contract below must reflect the workload, not
        # which leg drew the noisy round. Parity is asserted EVERY round.
        mono_rounds, disagg_rounds = [], []
        for _ in range(5):
            mono_rounds.append(leg(mono_engines))
            fresh_tier()
            disagg_rounds.append(leg(disagg_engines, cfg))

        def median_leg(rounds):
            out = dict(rounds[-1])     # counters/tier from the last round
            for k in ("tok_per_sec", "ttft_p90_ms", "itl_p90_ms"):
                out[k] = round(float(np.median([r[k] for r in rounds])), 3)
            return out

        mono = median_leg(mono_rounds)
        disagg = median_leg(disagg_rounds)
        for r in disagg_rounds:
            assert r["counters"]["handoffs"] >= n_long, \
                "every long prompt must hand off (else the leg measured " \
                "nothing)"
        for eng in (single, *mono_engines.values(), pe, de):
            assert eng.kv.free_blocks == eng.kv.num_blocks - 1, \
                "KV pool must drain on every replica"
        # the contract is a PAIRED per-round sign test: round i's mono and
        # disagg passes run back-to-back, so comparing within the pair
        # cancels the slow box drift that still leaks into aggregate
        # medians (sequential rounds on a shared box degrade severalfold
        # over a run). "Improves" = disagg wins the strict majority of
        # paired rounds on BOTH percentiles.
        pair_wins = {
            m: sum(1 for r_m, r_d in zip(mono_rounds, disagg_rounds)
                   if r_d[m] < r_m[m])
            for m in ("ttft_p90_ms", "itl_p90_ms")}
        if assert_contract:
            need = len(mono_rounds) // 2 + 1
            assert pair_wins["ttft_p90_ms"] >= need, \
                (f"disagg TTFT p90 must beat the monolithic fleet in a "
                 f"majority of paired rounds: won "
                 f"{pair_wins['ttft_p90_ms']}/{len(mono_rounds)} "
                 f"(medians {disagg['ttft_p90_ms']} vs "
                 f"{mono['ttft_p90_ms']} ms)")
            assert pair_wins["itl_p90_ms"] >= need, \
                (f"disagg decode ITL p90 must beat the monolithic fleet in "
                 f"a majority of paired rounds: won "
                 f"{pair_wins['itl_p90_ms']}/{len(mono_rounds)} "
                 f"(medians {disagg['itl_p90_ms']} vs "
                 f"{mono['itl_p90_ms']} ms)")

        return {
            "workload": "disagg-serving", "batch": batch,
            "long_prompt": long_prompt, "short_prompt": short_prompt,
            "long_new_tokens": long_new, "short_new_tokens": short_new,
            "n_long": n_long, "n_short": n_short, "chunk": chunk,
            "replicas": 2,
            "single_tok_per_sec": round(base_produced / base_dt, 1),
            "mono_fleet": mono,
            "disagg_fleet": disagg,
            "paired_round_wins": {k: f"{v}/{len(mono_rounds)}"
                                  for k, v in pair_wins.items()},
            "rounds": {
                "mono": [{k: r[k] for k in ("ttft_p90_ms", "itl_p90_ms",
                                            "tok_per_sec")}
                         for r in mono_rounds],
                "disagg": [{k: r[k] for k in ("ttft_p90_ms", "itl_p90_ms",
                                              "tok_per_sec")}
                           for r in disagg_rounds],
            },
            "ttft_p90_speedup": round(mono["ttft_p90_ms"]
                                      / disagg["ttft_p90_ms"], 3),
            "itl_p90_speedup": round(mono["itl_p90_ms"]
                                     / disagg["itl_p90_ms"], 3),
            "note": "same deterministic bursty long-prompt/short-decode "
                    "schedule on all three legs; TTFT/ITL are EXACT p90s "
                    "over raw samples on per-replica BUSY-TIME clocks "
                    "(thread-per-replica latency semantics — the serial "
                    "cooperative driver would charge every replica's frame "
                    "to every wall-clock gap), fleet-merged (handoff "
                    "continuations record no TTFT), median of 5 "
                    "interleaved rounds per fleet leg. The disagg leg "
                    "routes prefill-heavy arrivals to the prefill replica "
                    "(queued-prompt-token scoring), hands off committed "
                    "pages through the shared tier at the watermark, and "
                    "keeps long-prefill wide frames off the decode "
                    "replica's stream — outputs asserted token-identical "
                    "to the single engine on every leg; smoke margins are "
                    "modest because stock-tiny frames are overhead-bound "
                    "(see docstring)",
        }
    finally:
        LogBucketHistogram.__init__.__defaults__ = growth_defaults
        LogBucketHistogram.record = _orig_record


def bench_prefix_cache(model_name, batch, prompt_len, new_tokens,
                       n_arrivals=12, tail_len=8,
                       assert_contract=True):
    """KV memory hierarchy: prefix-cache hit-rate sweep on a deterministic
    shared-prefix arrival schedule (one arrival per frame-boundary poll —
    no wall clock in the schedule, so every leg sees identical admission
    timing).

    For each share fraction f, ``f * n_arrivals`` requests carry one long
    shared prefix plus a short unique tail (the multi-turn / system-prompt
    shape) and the rest are fully unique. Each point runs a cache-OFF
    baseline and a fresh cache-ON engine on the same schedule, asserting
    greedy outputs token-identical, and reports measured hit rate, TTFT
    p50/p90, and goodput. The ISSUE-8 acceptance contract — >= 2x TTFT p90
    at >= 50% hit rate — is asserted inline at the full-share point (like
    the telemetry-overhead budget, a swallowed assert is not an assert)."""
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model
    rng = np.random.default_rng(21)
    shared = rng.integers(0, 1000, (prompt_len,)).astype(np.int32)
    # two passes per leg (warm + measured): tails and unique prompts are
    # PER-PASS, so the measured pass can only hit via the shared prefix —
    # the thing the sweep is measuring — never via a replayed full prompt
    tails = [[rng.integers(0, 1000, (tail_len,)).astype(np.int32)
              for _ in range(n_arrivals)] for _ in range(2)]
    uniques = [[rng.integers(0, 1000,
                             (prompt_len + tail_len,)).astype(np.int32)
                for _ in range(n_arrivals)] for _ in range(2)]

    def arrivals(share_frac, pass_no):
        n_shared = int(round(share_frac * n_arrivals))
        for u in range(n_arrivals):
            p = np.concatenate([shared, tails[pass_no][u]]) \
                if u < n_shared else uniques[pass_no][u]
            yield [(pass_no * 100 + u, p)]

    def mk(prefix):
        model = build_model(model_name)
        # hit granularity is a full KV block rounded to the prefill chunk:
        # size both so the shared prefix spans several chunks (the v5e-
        # tuned 128 block would leave a 128-token prefix as ONE chunk and
        # measure nothing but the boundary)
        # frame_steps=1: every scan step is an admission boundary, the
        # regime a TTFT-sensitive deployment runs in (the adaptive sizer
        # picks small frames under bursty interactive traffic). An 8-step
        # frame would complete the whole 5-chunk prefill INSIDE one frame
        # and quantize TTFT to the frame boundary on both legs.
        # slots sized to the in-flight population so TTFT measures SERVICE
        # time (the prefill the cache removes), not slot-queueing — a
        # saturated table hides any admission-side win behind queue wait
        slots = max(batch, 8)
        cfg = RaggedInferenceEngineConfig(
            max_ragged_batch_size=slots,
            kv_block_size=32, prefill_chunk_size=32, frame_steps=1,
            expected_context=prompt_len + tail_len + new_tokens,
            expected_concurrency=slots,
            prefix_cache=prefix)
        return InferenceEngineV2(
            model, cfg,
            max_seq_len=prompt_len + tail_len + new_tokens + 2)

    def run(eng, share_frac, pass_no):
        outs, produced = {}, 0
        t0 = time.perf_counter()
        for uid, toks in eng.serve(arrivals(share_frac, pass_no),
                                   max_new_tokens=new_tokens):
            outs[uid] = toks
            produced += len(toks)
        dt = time.perf_counter() - t0
        lat = eng.telemetry.latency_ms()
        c = eng.telemetry.counters
        return outs, {
            "tok_per_sec": round(produced / dt, 1),
            "ttft_p50_ms": lat["ttft"]["p50"],
            "ttft_p90_ms": lat["ttft"]["p90"],
            "prefill_tokens": c["prefill_tokens"],
            "hit_rate": round(c["prefix_hits"] / c["prefix_lookups"], 4)
            if c["prefix_lookups"] else None,
            "hit_tokens": c["prefix_hit_tokens"],
        }

    def leg(prefix, frac):
        # frame programs are per-engine jits: one full warm pass compiles
        # BOTH frame widths (and, cache-on, the shared COW copy program)
        # so no measured request's TTFT absorbs a compile — the
        # bench_chaos warm-then-measure discipline. The warm pass also
        # pre-populates the cache-on leg's prefix index, so the measured
        # pass reports the steady-state hit rate.
        eng = mk(prefix)
        run(eng, frac, 0)
        return (eng,) + run(eng, frac, 1)

    sweep = []
    for frac in (0.0, 0.5, 1.0):
        _, base_outs, base = leg(False, frac)
        # the cached leg runs cache-ON at every point — share 0.0 is the
        # overhead row (all lookups miss, publishes still happen)
        eng, outs, cached = leg(True, frac)
        for u, toks in base_outs.items():
            np.testing.assert_array_equal(
                toks, outs[u],
                err_msg=f"uid={u} diverged cache-on at share={frac}")
        speed = (round(base["ttft_p90_ms"] / cached["ttft_p90_ms"], 3)
                 if cached["ttft_p90_ms"] else None)
        sweep.append({
            "share_frac": frac,
            "hit_rate": cached["hit_rate"],
            "hit_tokens": cached["hit_tokens"],
            "cold": {k: base[k] for k in
                     ("tok_per_sec", "ttft_p50_ms", "ttft_p90_ms",
                      "prefill_tokens")},
            "cached": {k: cached[k] for k in
                       ("tok_per_sec", "ttft_p50_ms", "ttft_p90_ms",
                        "prefill_tokens")},
            "ttft_p90_speedup": speed,
            "goodput_ratio": round(cached["tok_per_sec"]
                                   / base["tok_per_sec"], 4),
        })
    full = sweep[-1]
    if assert_contract:
        assert full["hit_rate"] >= 0.5, \
            f"hit rate {full['hit_rate']} < 0.5 on the full-share schedule"
        assert full["ttft_p90_speedup"] >= 2.0, \
            f"TTFT p90 speedup {full['ttft_p90_speedup']} < 2x at " \
            f"hit rate {full['hit_rate']}"
    return {
        "workload": "prefix-cache", "batch": batch,
        "shared_prefix_len": prompt_len, "tail_len": tail_len,
        "new_tokens": new_tokens, "arrivals": n_arrivals,
        "sweep": sweep,
        "note": "deterministic shared-prefix schedule (one arrival per "
                "boundary); every point asserts greedy outputs "
                "token-identical cache-on vs cache-off; full-share point "
                "asserts >= 2x TTFT p90 at >= 50% hit rate (ISSUE-8 "
                "acceptance). TTFT percentiles come from x2-growth "
                "log-bucket histograms, so ratios are quantized to powers "
                "of two — a 2.0 at hit_rate 0 is one bucket of scheduling "
                "noise, not a cache effect (prefill_tokens is the "
                "noise-free column)",
    }


def bench_tp(model_name, batch, prompt_len, new_tokens, tp, n_arrivals=8):
    """Tensor-parallel frame serving: tokens/s/chip scaling vs the
    single-chip baseline on one deterministic arrival schedule.

    Three engines run the IDENTICAL schedule:

    * **pre-PR baseline** — a default-config engine (the exact pre-TP code
      path: ``tp=1`` never touches shard_map);
    * **tp=1** — an engine constructed with ``tp=1`` explicitly; its
      outputs are asserted BYTE-IDENTICAL to the baseline (the tp knob at
      degree 1 must be a no-op, not a slightly different program);
    * **tp=N** — the shard_map engine; greedy outputs asserted
      token-identical, throughput reported absolute and per chip.

    A fourth leg re-runs tp=N with the int8-quantized collectives for the
    traffic-vs-exactness tradeoff row (completion asserted, tokens not —
    that's the tolerance contract, see tests/test_serving_tp.py).

    On this single-chip container the mesh is the virtual-8-CPU-device one
    (``--tp`` forces it before jax initializes), so per-chip numbers model
    PARALLELIZATION OVERHEAD only — 8 simulated devices share one host's
    cores and real ICI wins don't exist here. The honest headline is
    tokens/s/chip RATIO vs tp=1, not absolute throughput."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model

    # the stock "tiny" has 4 heads; the TP row needs every sharded axis
    # divisible by the mesh degree
    model = (build_model(model_name, num_heads=8) if model_name == "tiny"
             else build_model(model_name))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, model.cfg.vocab_size - 5,
                            (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def arrivals():
        for i in range(0, n_arrivals, 2):
            yield [(i + j, prompts[i + j])
                   for j in range(2) if i + j < n_arrivals]

    def mk(**over):
        kw = dict(max_ragged_batch_size=batch, kv_block_size=16,
                  prefill_chunk_size=16, max_tokens_per_step=256,
                  dtype="float32", frame_steps=8,
                  expected_context=prompt_len + new_tokens,
                  expected_concurrency=batch)
        kw.update(over)
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                                 params=params,
                                 max_seq_len=prompt_len + new_tokens + 2)

    def run(eng):
        outs, produced = {}, 0
        t0 = time.perf_counter()
        for uid, toks in eng.serve(arrivals(), max_new_tokens=new_tokens):
            outs[uid] = toks
            produced += len(toks)
        return outs, produced, time.perf_counter() - t0

    legs = {}
    base_outs = None
    eng_pre = mk()                       # default config == pre-PR engine
    run(eng_pre)                         # compile
    base_outs, base_produced, base_dt = run(eng_pre)

    eng1 = mk(tp=1)
    run(eng1)
    tp1_outs, _p, tp1_dt = run(eng1)
    for u, toks in base_outs.items():
        # byte-identical, not merely token-identical: same dtype, same values
        assert toks.dtype == tp1_outs[u].dtype
        np.testing.assert_array_equal(
            toks, tp1_outs[u],
            err_msg=f"uid={u}: tp=1 engine diverged from the pre-PR path")
    legs["tp1_tok_per_sec"] = round(base_produced / tp1_dt, 1)

    engN = mk(tp=tp)
    run(engN)
    tpN_outs, tpN_produced, tpN_dt = run(engN)
    for u, toks in base_outs.items():
        np.testing.assert_array_equal(
            toks, tpN_outs[u],
            err_msg=f"uid={u}: tp={tp} diverged from single-chip greedy")
    legs[f"tp{tp}_tok_per_sec"] = round(tpN_produced / tpN_dt, 1)
    legs[f"tp{tp}_tok_per_sec_per_chip"] = round(tpN_produced / tpN_dt / tp, 2)

    engQ = mk(tp=tp, tp_quantized_collectives=True)
    run(engQ)
    q_outs, q_produced, q_dt = run(engQ)
    assert len(q_outs) == n_arrivals and q_produced == tpN_produced, \
        "quantized-collective serve must still complete every budget"
    legs[f"tp{tp}_quantized_tok_per_sec"] = round(q_produced / q_dt, 1)

    per_chip_ratio = (tpN_produced / tpN_dt / tp) / (base_produced / base_dt)
    return {
        "workload": "tp-serving", "tp": tp, "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals,
        "baseline_tok_per_sec": round(base_produced / base_dt, 1),
        **legs,
        "scaling_tok_per_sec_per_chip_vs_tp1": round(per_chip_ratio, 4),
        "platform_devices": jax.device_count(),
        "note": "virtual CPU mesh on this container: per-chip ratio "
                "measures sharding overhead, not real multi-chip speedup "
                "(8 simulated devices share one host); tp=1 asserted "
                "byte-identical to the pre-PR engine, tp=N asserted "
                "token-identical, quantized leg asserted complete",
    }


def bench_quant(model_name, batch, prompt_len, new_tokens, n_arrivals=8):
    """Quantized serving at a FIXED KV HBM byte budget: f32 pages vs int8
    pages vs int8 pages + int8 weights.

    All three legs get the SAME byte budget for their KV pools; each
    converts it to however many blocks its resident page representation
    affords (int8 pages pack the row as D int8 + 4 scale-lane bytes, so
    they fit ~2.7x the blocks at f32 D=64). The capacity claim is then
    measured, not computed: every leg serves the identical arrival burst
    and reports how many slots were concurrently live before the first
    KV-pressure admission deferral — the int8 legs should carry the whole
    burst where the f32 leg defers.

    Tolerance contracts ride inline, exactly as the tests pin them
    (tests/test_quantized_serving.py): int8-KV greedy outputs are asserted
    TOKEN-IDENTICAL to the f32 leg (write-once pages), while the
    weight-quantized leg is asserted to complete every budget (argmax may
    legitimately flip near-ties)."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model

    model = build_model(model_name)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, model.cfg.vocab_size - 5,
                            (prompt_len,)).astype(np.int32)
               for _ in range(n_arrivals)]

    def mk(num_kv_blocks=None, **over):
        kw = dict(max_ragged_batch_size=batch, kv_block_size=16,
                  prefill_chunk_size=16, max_tokens_per_step=256,
                  dtype="float32", frame_steps=4, frame_retry_backoff_s=0.0,
                  num_kv_blocks=num_kv_blocks)
        kw.update(over)
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                                 params=params,
                                 max_seq_len=prompt_len + new_tokens + 2)

    # probe each representation's resident block footprint, then hand every
    # leg the same byte budget: enough f32 blocks for ~3 of the 8 arrivals
    # (so the f32 leg measurably defers), which the int8 page format turns
    # into headroom for the full burst
    f32_block_bytes = mk().kv.block_bytes
    int8_block_bytes = mk(kv_dtype="int8").kv.block_bytes
    blocks_per_seq = -(-(prompt_len + new_tokens + 1) // 16)
    hbm_budget = (3 * blocks_per_seq + 2) * f32_block_bytes

    def run(eng):
        """Serve the burst, sampling the live-slot gauge at every emission
        (frame-grained). With the slot table sized past the burst, the
        high-water mark IS the slots-until-first-deferral figure: a
        KV-bound engine admits up to pool capacity and defers the rest at
        that same boundary, so the peak reads the stall point."""
        outs, produced, peak = {}, 0, 0
        t0 = time.perf_counter()
        for uid, toks in eng.serve(iter([[(u, p) for u, p in
                                          enumerate(prompts)]]),
                                   max_new_tokens=new_tokens):
            peak = max(peak, int(eng.telemetry.gauges["live_slots"]))
            outs[uid] = toks
            produced += len(toks)
        dt = time.perf_counter() - t0
        if not eng.telemetry.counters["admission_deferrals"]:
            peak = n_arrivals            # the whole burst fit at once
        return outs, produced, dt, peak

    def leg(name, **over):
        eng = mk(num_kv_blocks=max(2, hbm_budget
                                   // eng_block_bytes[name]), **over)
        run(eng)                         # compile
        outs, produced, dt, slots = run(eng)
        return eng, outs, {
            f"{name}_tok_per_sec": round(produced / dt, 1),
            f"{name}_kv_blocks": eng.kv.num_blocks,
            f"{name}_kv_block_bytes": eng.kv.block_bytes,
            f"{name}_slots_until_first_deferral": slots,
            f"{name}_admission_deferrals":
                eng.telemetry.counters["admission_deferrals"],
        }

    eng_block_bytes = {"f32": f32_block_bytes,
                       "int8_kv": int8_block_bytes,
                       "int8_kv_w8": int8_block_bytes}
    _, base_outs, row_f32 = leg("f32")
    _, kv_outs, row_kv = leg("int8_kv", kv_dtype="int8")
    for u, toks in base_outs.items():
        np.testing.assert_array_equal(
            toks, kv_outs[u],
            err_msg=f"uid={u}: int8-KV diverged from f32 greedy")
    _, w_outs, row_w = leg("int8_kv_w8", kv_dtype="int8",
                           weight_dtype="int8")
    assert len(w_outs) == n_arrivals and \
        all(len(t) == new_tokens for t in w_outs.values()), \
        "weight-quantized serve must still complete every budget"

    return {
        "workload": "quant-serving", "batch": batch,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "arrivals": n_arrivals,
        "kv_hbm_budget_bytes": hbm_budget,
        **row_f32, **row_kv, **row_w,
        "kv_block_bytes_ratio_f32_over_int8": round(
            f32_block_bytes / int8_block_bytes, 2),
        "slots_ratio_int8_over_f32": round(
            row_kv["int8_kv_slots_until_first_deferral"]
            / max(1, row_f32["f32_slots_until_first_deferral"]), 2),
        "note": "identical arrival burst per leg at one KV byte budget; "
                "int8-KV outputs asserted token-identical to f32, "
                "weight-quantized leg asserted complete; tiny-model CPU "
                "tok/s measures dequant overhead at toy shapes, not the "
                "HBM-bandwidth win the page format buys on real chips",
    }


def bench_mixed_compiled(model_name, batch, prompt_lens, new_tokens):
    """Mixed SplitFuse via the COMPILED loop (generate_compiled): staggered
    prompt lengths make early finishers decode inside wide prefill steps —
    the same fused mixed step, with zero host driving between steps."""
    eng = _mk_engine(model_name, batch)
    rng = np.random.default_rng(2)
    vocab = eng.model.cfg.vocab_size
    prompts = [rng.integers(0, vocab, (prompt_lens[i % len(prompt_lens)],))
               .astype(np.int32) for i in range(batch)]
    eng.generate_compiled(prompts, max_new_tokens=new_tokens)   # compile
    t0 = time.perf_counter()
    outs = eng.generate_compiled(prompts, max_new_tokens=new_tokens)
    dt = time.perf_counter() - t0
    produced = sum(len(o) for o in outs)
    return {
        "workload": "mixed-splitfuse-compiled", "batch": batch,
        "prompt_lens": list(prompt_lens), "new_tokens": new_tokens,
        "generated_tok_per_sec": round(produced / dt, 1),
        "e2e_tok_per_sec": round(
            (produced + sum(len(p) for p in prompts)) / dt, 1),
        "note": "one jit for chunked prefill + staggered transitions + "
                "decode; compare generated_tok_per_sec with the host-driven "
                "mixed-splitfuse row",
    }


def bench_decode_collapse_probe(model_name, prompt_len, new_tokens):
    """Round-3 left the batch-64 decode collapse (3.2x the batch-32 step
    time) unexplained. Probe the two candidate causes directly: KV-pool
    size (bigger pool -> more HBM touched per page scatter?) and batch
    scaling of the paged kernel grid."""
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model

    def decode_rate(batch, num_blocks):
        cfg = RaggedInferenceEngineConfig(
            max_ragged_batch_size=max(batch, 16),
            max_tokens_per_step=max(batch * 2, 768),
            num_kv_blocks=num_blocks)
        eng = InferenceEngineV2(build_model(model_name), cfg)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, eng.model.cfg.vocab_size,
                                (prompt_len,)).astype(np.int32)
                   for _ in range(batch)]
        eng.generate(prompts, max_new_tokens=4)
        eng.generate(prompts, max_new_tokens=new_tokens)
        t0 = time.perf_counter()
        eng.generate(prompts, max_new_tokens=4)
        t1 = time.perf_counter()
        eng.generate(prompts, max_new_tokens=new_tokens)
        t2 = time.perf_counter()
        return batch * (new_tokens - 4) / ((t2 - t1) - (t1 - t0))

    bs = 128
    blocks_for = lambda b: b * ((prompt_len + new_tokens) // bs + 2) + 1
    r64_small = decode_rate(64, blocks_for(64))       # tight pool
    # 2x, not 4x: pools past ~500 blocks hit the tunnel compile-helper's
    # memory limit (HTTP 500 — the same wall as the batch-32 train config)
    r64_big = decode_rate(64, blocks_for(64) * 2)
    r32 = decode_rate(32, blocks_for(64))             # same pool, half batch
    pool_sensitive = r64_big < 0.8 * r64_small
    return {
        "workload": "decode-collapse-probe", "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "b64_tight_pool_tok_per_sec": round(r64_small, 1),
        "b64_2x_pool_tok_per_sec": round(r64_big, 1),
        "b32_same_pool_tok_per_sec": round(r32, 1),
        "verdict": ("pool-size-bound (page scatter touches the whole pool)"
                    if pool_sensitive else
                    "batch-scaling-bound (per-step cost superlinear in B "
                    "with pool size ruled out)"),
    }


def bench_woq_delta():
    """Fused WOQ matmul vs bf16 dense at serving shapes. Round 2 promised a
    recorded bandwidth delta; the round-3 platform-floor row explains why
    this chip cannot show one (every streamed op pays the ~2 ms floor, so
    int4's 4x smaller weight read is invisible) — this row records the
    MEASURED ratio next to that explanation instead of leaving it implied."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.pallas.woq_matmul import quantize_woq, woq_matmul

    rng = np.random.default_rng(0)
    rows = []
    for m, k, n, bits in ((1, 4096, 4096, 4), (16, 4096, 4096, 4),
                          (16, 4096, 4096, 8)):
        w = jnp.asarray(rng.normal(size=(k, n)) * 0.02, jnp.bfloat16)
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.bfloat16)
        fused = quantize_woq(w, bits, 128)
        # metadata ints stay static via closure; the packed arrays ride as
        # jit args (closing over them would bake multi-MB constants — the
        # tunnel rejects those with HTTP 413)
        meta = {f: fused[f] for f in ("bits", "group_size", "shape")}

        @jax.jit
        def dense(x, w):
            (y,), _ = jax.lax.scan(lambda c, _: ((jnp.tanh(c[0] @ w),), ()),
                                   (x,), None, length=32)
            return y

        @jax.jit
        def quant(x, q, scales):
            qs = {**meta, "q": q, "scales": scales}
            (y,), _ = jax.lax.scan(
                lambda c, _: ((jnp.tanh(woq_matmul(c[0], qs)),), ()),
                (x,), None, length=32)
            return y

        q_arr, s_arr = fused["q"], fused["scales"]
        jax.device_get(dense(x, w)); jax.device_get(quant(x, q_arr, s_arr))
        td = tq = 1e9
        for _ in range(3):
            t0 = time.perf_counter(); jax.device_get(dense(x, w))
            td = min(td, time.perf_counter() - t0)
            t0 = time.perf_counter(); jax.device_get(quant(x, q_arr, s_arr))
            tq = min(tq, time.perf_counter() - t0)
        rows.append({"m": m, "k": k, "n": n, "bits": bits,
                     "dense_ms_per_op": round(td / 32 * 1e3, 3),
                     "woq_ms_per_op": round(tq / 32 * 1e3, 3),
                     "woq_speedup": round(td / tq, 3)})
    return {"workload": "woq-kernel-delta", "rows": rows,
            "note": "expected ~= 1.0x on this chip: the platform-floor row "
                    "shows a ~2 ms per-op latency floor / ~15 GB/s effective "
                    "streamed HBM, so the 4x-8x smaller weight fetch cannot "
                    "surface; the kernel's win is HBM-bandwidth-bound "
                    "hardware (parity tests cover correctness)"}


def bench_kernel_delta(model_name, batch, prompt_len, new_tokens, repeats=2):
    """Paged-Pallas vs XLA-gather decode delta (same workload, kernel off).

    Measured TWICE per mode (tunnel noise is +/-40% at ms scale; r03
    recorded an 18.3x delta here that later runs could not reproduce —
    repeats + best-of keep one bad window from minting a fake headline)."""
    rows = {}
    for mode, env in (("paged_pallas", "0"), ("xla_gather", "1")):
        os.environ["DS_TPU_DISABLE_PALLAS"] = env
        try:
            vals = [bench_decode(model_name, batch, prompt_len,
                                 new_tokens)["decode_tok_per_sec"]
                    for _ in range(repeats)]
            rows[mode] = max(vals)
            rows[mode + "_runs"] = vals
        finally:
            os.environ.pop("DS_TPU_DISABLE_PALLAS", None)
    if rows.get("xla_gather"):
        rows["pallas_speedup"] = round(rows["paged_pallas"] / rows["xla_gather"], 3)
    return {"workload": "kernel-delta", "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, **rows}


def bench_service(model_name, batch, prompt_len, new_tokens,
                  n_arrivals=12, sessions=200, turns=2,
                  assert_contract=True):
    """The service edge measured as traffic experiences it (ISSUE 14).

    Four legs:

    * **routing-overhead** — the SAME front-loaded burst through the
      serial cooperative router and the thread-per-replica
      ``FleetDriver`` (identical policy state), outputs asserted
      token-identical; the tok/s ratio is what true concurrency buys
      over one host thread stepping replicas in turn (paired rounds,
      median).
    * **closed-loop load** — ``load_gen`` drives ``sessions`` concurrent
      closed-loop SSE sessions with think-time against a real HTTP
      endpoint; every streamed byte is compared against a direct
      single-engine ``serve()`` of the same schedule. ZERO parity
      violations is a hard contract.
    * **edge-admission** — a no-think burst against a deliberately tiny
      edge queue budget: the fleet must shed at the EDGE (429 +
      Retry-After) while every replica's local scheduler sheds NOTHING
      (the ordering contract: back-pressure belongs at the front door),
      and the closed-loop clients must still complete by honoring
      Retry-After.
    * **autoscale** — a load swing (burst -> idle -> long-prompt burst)
      against a 3-replica shared-tier fleet under the
      ``AutoscaleController``: expects >=1 scale_down (idle drain),
      >=1 scale_up (rejoin under backlog), and >=1 prefill role flip,
      with all outputs token-identical.

    All asserts are CPU-smoke contracts (``assert_contract``); on TPU
    they are reported, not asserted."""
    import jax
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import load_gen
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.kv_hierarchy import KVSwapTier
    from deepspeed_tpu.inference.v2.router import EngineRouter, RouterConfig
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    from deepspeed_tpu.inference.v2.service import (AutoscaleConfig,
                                                    AutoscaleController,
                                                    EdgeConfig, FleetDriver,
                                                    ServiceEdge)
    from deepspeed_tpu.models import build_model
    import tempfile

    model = build_model(model_name, num_heads=8)
    params = model.init(jax.random.PRNGKey(0))
    max_seq = 4 * (prompt_len + new_tokens) + 32

    def mk(**over):
        kw = dict(kv_block_size=16, prefill_chunk_size=8,
                  max_tokens_per_step=1024, dtype="float32",
                  max_ragged_batch_size=batch, frame_steps=2,
                  frame_retry_backoff_s=0.0)
        kw.update(over)
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(**kw),
                                 params=params, max_seq_len=max_seq)

    rng = np.random.default_rng(12)
    prompts = {u: rng.integers(0, 200, (prompt_len,)).astype(np.int32)
               for u in range(n_arrivals)}

    def burst():
        yield [(u, prompts[u]) for u in sorted(prompts)]

    # ---- leg 1: routing overhead, serial vs threaded, paired rounds ----
    def run_driver(threaded):
        router = EngineRouter(
            {"a": mk(), "b": mk()},
            RouterConfig(driver="threaded" if threaded else "serial"))
        t0 = time.perf_counter()
        outs = dict(router.serve(burst(), max_new_tokens=new_tokens))
        dt = time.perf_counter() - t0
        toks = sum(len(v) for v in outs.values())
        return outs, toks / dt, dt

    ref_outs, _, _ = run_driver(False)      # warm trace round (discarded)
    rounds = []
    for _ in range(3):
        s_outs, s_rate, s_dt = run_driver(False)
        t_outs, t_rate, t_dt = run_driver(True)
        for u in ref_outs:
            assert np.array_equal(s_outs[u], ref_outs[u]), f"serial uid={u}"
            assert np.array_equal(t_outs[u], ref_outs[u]), \
                f"threaded driver outputs diverge at uid={u}"
        rounds.append({"serial_tok_per_sec": round(s_rate, 1),
                       "threaded_tok_per_sec": round(t_rate, 1),
                       "speedup": round(t_rate / s_rate, 3)})
    speedup = statistics.median(r["speedup"] for r in rounds)
    routing = {"rounds": rounds,
               "threaded_over_serial_tok_per_sec": round(speedup, 3),
               "note": "same front-loaded burst, token-identical asserted "
                       "each round; CPU smoke shares one physical device "
                       "across replicas, so the overlap win is bounded by "
                       "host-side scheduling, not compute parallelism"}

    # ---- leg 2: closed-loop load against the real endpoint ----
    sched = load_gen.build_schedule(sessions, turns, prompt_len,
                                    new_tokens, think_ms=200.0, seed=3)
    router, driver, edge, mk_ref = load_gen.build_fleet(
        2, batch, max_seq_len=max_seq, scheduler=False)
    try:
        # the reference MUST be the fleet's own engine family (mk_ref):
        # on TPU the bench model differs from build_fleet's tiny smoke
        # fleet, and a cross-model "parity" count would be noise
        ref = load_gen.direct_reference(mk_ref, sched)
        report = load_gen.run_load("127.0.0.1", edge.edge_port, sched,
                                   sessions, turns)
        violations = load_gen.check_parity(report, ref)
        report.pop("_results")
        report["parity_violations"] = violations
        report["edge_counters"] = dict(edge.counters)
        if assert_contract:
            assert report["completed"] == report["requests"], \
                f"{report['n_failures']} sessions failed: " \
                f"{report['failures'][:3]}"
            assert violations == 0, \
                f"{violations} token-parity violations between the SSE " \
                "stream and direct serve()"
    finally:
        edge.shutdown()
        driver.stop()

    # ---- leg 3: edge admission sheds BEFORE any local scheduler shed ----
    shed_sessions = 40
    shed_sched = load_gen.build_schedule(shed_sessions, 1, prompt_len,
                                         new_tokens, think_ms=0.0, seed=5)
    mk2_ref = mk                 # leg 3's fleet IS built from mk()
    router2 = EngineRouter({"replica0": mk()})
    driver2 = FleetDriver(router2)
    driver2.start(max_new_tokens=new_tokens,
                  scheduler_factory=lambda: RequestScheduler(SchedulerConfig(
                      tenant_max_queued=16, lookahead_reserve=True)))
    edge2 = ServiceEdge(driver2, EdgeConfig(
        max_queued_tokens=4 * prompt_len,
        retry_after_min_s=0.2, retry_after_max_s=2.0)).start()
    try:
        ref2 = load_gen.direct_reference(mk2_ref, shed_sched)
        rep2 = load_gen.run_load("127.0.0.1", edge2.edge_port, shed_sched,
                                 shed_sessions, 1, max_shed_retries=200)
        v2 = load_gen.check_parity(rep2, ref2)
        rep2.pop("_results")
        local_sheds = sum(
            r.engine.telemetry.counters["requests_shed"]
            for r in router2._replicas.values())
        edge_leg = {
            "sessions": shed_sessions,
            "edge_sheds": edge2.counters["sheds"],
            "local_scheduler_sheds": local_sheds,
            "completed": rep2["completed"],
            "requests": rep2["requests"],
            "parity_violations": v2,
            "sheds_retried": rep2["edge_sheds_seen"],
            "retry_wait_total_s": rep2["retry_wait_s"],
            "note": "tiny edge queue budget (max_queued_tokens="
                    f"{4 * prompt_len}): the 429/Retry-After path must "
                    "engage at the edge while every replica's scheduler "
                    "sheds nothing, and closed-loop retries must still "
                    "complete every request",
        }
        if assert_contract:
            assert edge2.counters["sheds"] > 0, \
                "overload burst never tripped edge admission"
            assert local_sheds == 0, \
                f"{local_sheds} local scheduler sheds — the edge must " \
                "shed first"
            assert rep2["completed"] == rep2["requests"], \
                f"edge-shed leg lost requests: {rep2['failures'][:3]}"
            assert v2 == 0, f"{v2} parity violations in the shed leg"
    finally:
        edge2.shutdown()
        driver2.stop()

    # ---- leg 4: autoscale (drain/rejoin + prefill role flip) ----
    td = tempfile.mkdtemp()
    tier = KVSwapTier(os.path.join(td, "tier"), shared=True)
    engines = {}
    for n in ("replica0", "replica1", "replica2"):
        e = mk(max_tokens_per_step=2048)
        e.attach_kv_tier(tier, tag=n)
        engines[n] = e
    router3 = EngineRouter(engines)
    ctl = AutoscaleController(AutoscaleConfig(
        evaluate_every_s=0.15, sustain=2, min_live_replicas=1,
        flip_prefill_high=100, flip_dwell_s=2.0))
    driver3 = FleetDriver(router3, autoscaler=ctl)
    driver3.start(max_new_tokens=new_tokens)
    results = {}
    lock = __import__("threading").Lock()

    def sub_for(uid):
        def sub(ev):
            if ev["type"] == "done":
                with lock:
                    results[uid] = ev["tokens"]
        return sub

    try:
        shorts = {u: [int(t) for t in prompts[u]] for u in range(4)}
        for u, p in shorts.items():
            driver3.submit({"uid": u, "tokens": p,
                            "max_new_tokens": new_tokens}, sub_for(u))
        t0 = time.monotonic()
        while len(results) < len(shorts) and time.monotonic() - t0 < 120:
            time.sleep(0.05)
        time.sleep(2.0)                      # idle window -> scale_down
        # oversubscribe the surviving replica's slot table (and KV pool)
        # so queued-token pressure SUSTAINS — a burst the frame absorbs
        # into free slots in one boundary never registers as pressure
        plen = (max_seq - new_tokens - 2) // 8 * 8
        longs = {100 + i: [int(t) for t in rng.integers(0, 200, (plen,))]
                 for i in range(3 * batch)}
        for u, p in longs.items():           # burst -> scale_up + flip
            driver3.submit({"uid": u, "tokens": p, "max_new_tokens": 4},
                           sub_for(u))
        t0 = time.monotonic()
        while len(results) < len(shorts) + len(longs) and \
                time.monotonic() - t0 < 180:
            time.sleep(0.05)
        time.sleep(2.5)                      # drain window -> flip back
        scale = {k: v for k, v in router3.counters.items()
                 if k.startswith("scale")}
        auto_leg = {
            "completed": len(results),
            "requests": len(shorts) + len(longs),
            "events": [{k: e[k] for k in ("tick", "action", "replica")}
                       for e in ctl.events],
            "counters": scale,
            "final_status": router3.replica_status(),
            "final_roles": dict(router3._roles),
        }
        if assert_contract:
            assert len(results) == len(shorts) + len(longs), \
                "autoscale leg lost requests"
            assert scale["scale_down"] >= 1, "idle fleet never scaled down"
            assert scale["scale_up"] >= 1, \
                "backlogged fleet never rejoined parked capacity"
            assert scale["scale_role_flips"] >= 1, \
                "prefill pressure never flipped a replica"
    finally:
        driver3.stop()

    return {
        "workload": "service-edge",
        "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens, "replicas": 2,
        "routing_overhead": routing,
        "loadgen": report,
        "edge_admission": edge_leg,
        "autoscale": auto_leg,
        "note": "load_gen drives real HTTP/SSE sessions against the "
                "threaded fleet driver; parity checks compare every "
                "streamed token against a direct single-engine serve() "
                "of the same schedule. CPU smoke: absolute rates are "
                "dispatch-bound, the contracts (parity, shed ordering, "
                "autoscale round-trip) are the measurement",
    }


def bench_sim_check(timeout_s=300):
    """Run ``bin/dstpu_sim --check`` as a subprocess and surface its JSON
    verdict as a bench row. The check is the simulator's own CI smoke
    (deterministic twin runs, snapshot/resume digest, full completion,
    virtual frames only, answers-in-seconds); a breach is an
    AssertionError here so the default row set's exit-code contract
    catches it like the telemetry/tracing budgets."""
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bin", "dstpu_sim"), "--check"],
        capture_output=True, text=True, timeout=timeout_s,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        verdict = json.loads(proc.stdout)
    except ValueError:
        verdict = {"ok": False, "failures": [
            {"check": "json_output",
             "detail": (proc.stdout or proc.stderr)[:300]}]}
    row = {"workload": "sim-check", "exit_code": proc.returncode, **verdict}
    assert proc.returncode == 0 and verdict.get("ok"), \
        f"dstpu_sim --check failed: {verdict.get('failures')}"
    return row


def bench_sim_fidelity(model_name, batch=8, tolerance=0.6,
                       rate=4.0, duration_s=8.0, assert_contract=True):
    """Sim-vs-real fidelity gate (ISSUE 18): replay ONE recorded arrival
    schedule through the live engine (wall clock, real frames) and
    through the fleet simulator (virtual clock, priced frames), and
    assert the sim's predicted TTFT/ITL p50/p90 land within a stated
    RELATIVE tolerance of the measured run.

    Method:

    * the schedule is a seeded Poisson trace (``sim.traffic.synth_trace``
      — the exact input ``bin/dstpu_sim`` replays); prompts are the
      trace's deterministic token fillers, vocab-clamped for the live
      model (the sim never runs the model, so only LENGTHS must match);
    * the cost model is calibrated from a DIFFERENT-seed schedule's live
      PER-FRAME wall timings, each stamped with the frame's real
      (width, steps, live) plan — prefill frames run
      width=prefill_chunk_size and price from the ledger's wide bucket,
      so the fit sees two distinct work clusters (fitting and scoring
      on the same run would grade the fit, not the sim);
    * live legs repeat until a replay pays no XLA compile stall: frame
      composition shifts with wall timing, so novel (width, steps)
      shapes can keep compiling for a few passes — the virtual fleet
      never compiles, so the measured legs must not either;
    * both sides run the same single-replica deployment (same engine
      config, same ``RequestScheduler``) and both measure
      schedule-relative latency: TTFT = first emission boundary minus
      the arrival's SCHEDULED time, ITL = (retire - first)/(n-1).

    The tolerance is deliberately coarse (default 60% relative): the sim
    prices frames with a two-parameter affine model over static ledger
    counts, so it predicts capacity-planning magnitudes, not
    microseconds. The gate pins that the prediction stays the right
    SIZE — a regression that doubles live TTFT or halves sim cost
    breaches it."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig, ServeBoundary)
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    from deepspeed_tpu.inference.v2.sim import (FleetSimulator, SimConfig,
                                                synth_trace)
    from deepspeed_tpu.inference.v2.sim.cost import (
        FrameCostModel, calibrate_from_boundaries)
    from deepspeed_tpu.inference.v2.sim.traffic import (prompt_for,
                                                        session_prefix_for)
    from deepspeed_tpu.models import build_model

    # generations long enough that ITL spans many frames: a short
    # generation retires in the boundary that emitted its first token,
    # so (retire - first)/(n - 1) quantizes to zero and the comparison
    # grades boundary-stamp granularity, not the cost model
    frame_steps, chunk, max_new = 4, 8, 48
    shape = dict(rate=rate, duration_s=duration_s, prompt_mean=12,
                 prompt_max=24, new_tokens_mean=24, new_tokens_max=max_new,
                 sessions=2)
    trace = synth_trace("poisson", seed=9, **shape)       # measured
    cal_trace = synth_trace("poisson", seed=11, **shape)  # calibration

    model = build_model(model_name, num_heads=8)
    params = model.init(jax.random.PRNGKey(0))
    vocab = model.cfg.vocab_size
    max_seq = 2 * (24 + max_new) + 32
    # ONE engine config for both legs: the sim derives its KV block
    # pool and admission limits from the same fields, so any drift here
    # would grade config skew, not fidelity
    eng_cfg = RaggedInferenceEngineConfig(
        kv_block_size=16, prefill_chunk_size=chunk,
        max_tokens_per_step=1024, dtype="float32",
        max_ragged_batch_size=batch, frame_steps=frame_steps,
        frame_retry_backoff_s=0.0)
    eng = InferenceEngineV2(model, eng_cfg, params=params,
                            max_seq_len=max_seq)

    def items_for(tr):
        out = []
        for ev in tr:
            prefix = (session_prefix_for(ev["session"], vocab=vocab)
                      if ev.get("session") else None)
            item = {"uid": int(ev["uid"]),
                    "tokens": np.asarray(
                        prompt_for(int(ev["uid"]), int(ev["prompt_tokens"]),
                                   vocab=vocab, session_prefix=prefix),
                        np.int32)}
            if ev.get("max_new_tokens") is not None:
                item["max_new_tokens"] = int(ev["max_new_tokens"])
            for k in ("tenant", "priority", "slo_ms", "session"):
                if ev.get(k) is not None:
                    item[k] = ev[k]
            out.append((float(ev["t"]), item))
        return out

    frames = []               # per-boundary (dt, width, steps, live)
    prev_mark = [None, 0.0]   # (boundary index, wall stamp) last frame
    orig_rfr = eng._run_frame_resilient

    def timed_rfr(slots, width, cur_steps, greedy, draft, faults, frame):
        out = orig_rfr(slots, width, cur_steps, greedy, draft, faults,
                       frame)
        t1 = time.monotonic()
        if prev_mark[0] == frame - 1:
            # consecutive dispatched boundaries: the delta prices one
            # FULL boundary — dispatch plus the host work around it
            # (admission, absorb, retirement) that the sim's virtual
            # advance must also represent — stamped with this frame's
            # real plan so prefill and decode boundaries land in their
            # own ledger programs
            frames.append({"dt": t1 - prev_mark[1],
                           "width": int(width), "steps": int(cur_steps),
                           "live": slots.live_count(), "n_slots": batch})
        prev_mark[0], prev_mark[1] = frame, t1
        return out

    eng._run_frame_resilient = timed_rfr

    def live_replay(tr):
        """Wall-clock replay; returns (ttfts, itls, boundaries,
        completed) with schedule-relative latencies in seconds."""
        sched_items = items_for(tr)
        prev_mark[0] = None          # boundary counter restarts
        t0 = time.monotonic()

        def arrivals():
            nxt = 0
            while nxt < len(sched_items):
                now = time.monotonic() - t0
                due = []
                while nxt < len(sched_items) and sched_items[nxt][0] <= now:
                    due.append(sched_items[nxt][1])
                    nxt += 1
                yield due

        sched_t = {it["uid"]: t0 + t for t, it in sched_items}
        first_t, last_t, emitted, retired = {}, {}, {}, 0
        for ev in eng.serve(arrivals(), max_new_tokens=max_new,
                            scheduler=RequestScheduler(SchedulerConfig()),
                            yield_boundaries=True):
            if isinstance(ev, ServeBoundary):
                # ITL spans first..LAST observed emission: the retire
                # tuple can arrive boundaries before the device's
                # trailing emit flags drain, so stamping retirement
                # would understate the span
                for uid, toks in (ev.emissions or {}).items():
                    if toks:
                        if uid not in first_t:
                            first_t[uid] = ev.t
                        last_t[uid] = ev.t
                        emitted[uid] = emitted.get(uid, 0) + len(toks)
            elif isinstance(ev, tuple):
                retired += 1
        ttfts = sorted(first_t[u] - sched_t[u] for u in first_t)
        itls = sorted((last_t[u] - first_t[u]) / (emitted[u] - 1)
                      for u in first_t if emitted.get(u, 0) > 1)
        return ttfts, itls, None, retired

    def quiet_replay(tr, attempts=5, stall_s=0.30):
        """Replay until no frame pays an XLA compile stall: the frame
        mix shifts with wall timing, so novel (width, steps) shapes can
        keep compiling for a few passes."""
        out = None
        for _ in range(attempts):
            frames.clear()
            out = live_replay(tr)
            if max((f["dt"] for f in frames), default=0.0) < stall_s:
                break
        return out

    quiet_replay(cal_trace)                           # calibration run
    # ``frames`` holds the quiet calibration replay's real per-frame
    # timings. warmup_factor is wide open: quiet_replay already removed
    # compile stalls, and a wide prefill frame legitimately costs ~7x a
    # decode frame — the default 5x-median cutoff would drop exactly
    # the samples the TTFT prediction needs.
    cal = calibrate_from_boundaries(FrameCostModel(), list(frames),
                                    warmup_factor=50.0)

    def pcts(xs):
        return {p: round(float(np.percentile(xs, p)) * 1e3, 3)
                if xs else None for p in (50, 90)}

    # measured leg: median percentile over three quiet replays — a
    # single replay's tail is at the mercy of one host hiccup, and the
    # gate must grade the cost model, not the benchmark machine
    reps = [quiet_replay(trace) for _ in range(3)]
    live_completed = min(r[3] for r in reps)
    live = {m: {p: round(float(np.median(
                [pcts(r[idx])[p] for r in reps
                 if pcts(r[idx])[p] is not None] or [np.nan])), 3)
                for p in (50, 90)}
            for idx, m in ((0, "ttft"), (1, "itl"))}
    for m in live:
        for p in (50, 90):
            if np.isnan(live[m][p]):
                live[m][p] = None

    sim_cfg = SimConfig(
        replicas=1, engine=eng_cfg, max_seq_len=max_seq,
        scheduler=SchedulerConfig(), max_new_tokens=max_new,
        calibration=cal)
    res = FleetSimulator(sim_cfg).run(trace)
    comparisons = []
    for metric in ("ttft", "itl"):
        for p in (50, 90):
            lv = live[metric][p]
            sv = res.latency[metric][f"p{p}"]
            if lv is None or sv is None or lv <= 0:
                continue
            err = abs(sv - lv) / lv
            comparisons.append({
                "metric": f"{metric}_p{p}", "live_ms": lv,
                "sim_ms": round(sv, 3), "rel_err": round(err, 3),
                "within": err <= tolerance})
    row = {
        "workload": "sim-fidelity", "batch": batch,
        "frame_steps": frame_steps, "prefill_chunk": chunk,
        "requests": len(trace), "live_completed": live_completed,
        "sim_completed": res.completed,
        "tolerance_rel": tolerance,
        "calibration": cal.to_json(),
        "comparisons": comparisons,
        "live_ms": live,
        "sim_ms": {"ttft": res.latency["ttft"],
                   "itl": res.latency["itl"]},
        "sim_virtual_frames": res.virtual_frames,
        "note": "one recorded Poisson schedule replayed through the live "
                "engine (wall clock) and the fleet simulator (virtual "
                "clock, cost model calibrated on a different-seed "
                "schedule's boundary deltas); schedule-relative TTFT/ITL "
                "p50/p90 must agree within the stated relative tolerance",
    }
    if assert_contract:
        assert live_completed == len(trace), \
            f"live replay lost requests: {live_completed}/{len(trace)}"
        assert res.completed == len(trace), \
            f"sim lost requests: {res.completed}/{len(trace)}"
        assert comparisons, "no comparable percentiles measured"
        bad = [c for c in comparisons if not c["within"]]
        assert not bad, \
            f"sim-vs-real fidelity breach (tolerance {tolerance}): {bad}"
    return row


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--speculate", action="store_true",
                    help="run the speculative-decoding serving rows "
                         "(mixed-splitfuse-dynamic Poisson schedule: "
                         "acceptance rate, tokens/target-forward, and the "
                         "frame-vs-host-step speedup side by side)")
    ap.add_argument("--gamma", type=int, default=2,
                    help="draft tokens per target verify (default 2)")
    ap.add_argument("--scheduler", action="store_true",
                    help="run only the scheduler-slo row (FIFO vs SLO-aware "
                         "admission under a deterministic 2-tenant overload "
                         "schedule: per-class TTFT p90, shed rate, goodput)")
    ap.add_argument("--tp", type=int, default=0,
                    help="run only the tensor-parallel serving row at this "
                         "degree (tokens/s/chip scaling vs the single-chip "
                         "baseline, with inline byte-identity and token-"
                         "parity asserts). With JAX_PLATFORMS=cpu set "
                         "explicitly, widens the CPU platform to a virtual "
                         "N-device mesh (parity/overhead run); otherwise "
                         "benches the real devices and errors loudly if "
                         "fewer than N exist.")
    ap.add_argument("--quant", action="store_true",
                    help="run only the quantized-serving row (f32 vs int8 "
                         "KV pages vs int8 KV + int8 weights at one fixed "
                         "KV HBM byte budget: tokens/s, blocks afforded, "
                         "and slots-until-first-deferral per leg, with "
                         "inline int8-KV token-identity asserts)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="run only the prefix-cache row (hit-rate sweep on "
                         "a deterministic shared-prefix arrival schedule: "
                         "TTFT p50/p90 and goodput vs the cold baseline, "
                         "with inline token-identity asserts and the >=2x "
                         "TTFT-p90-at->=50%%-hit-rate acceptance contract)")
    ap.add_argument("--disagg", action="store_true",
                    help="run only the disaggregated prefill/decode row "
                         "(1 prefill + 1 decode replica over the shared "
                         "KV tier vs a 2-replica monolithic fleet on a "
                         "long-prompt/short-decode mix: TTFT p90 + decode "
                         "ITL p90 per leg, with inline token-identity and "
                         "both-percentiles-improve asserts)")
    ap.add_argument("--service", action="store_true",
                    help="run only the service-edge row (serial vs "
                         "threaded fleet-driver routing overhead, "
                         "closed-loop HTTP/SSE load with inline "
                         "token-parity asserts, edge-admission-sheds-"
                         "before-local-sheds leg, and the autoscale "
                         "drain/rejoin/role-flip round trip)")
    ap.add_argument("--sessions", type=int, default=200,
                    help="closed-loop sessions for the --service load "
                         "leg (default 200, the acceptance bar)")
    ap.add_argument("--tracing", action="store_true",
                    help="run only the tracing-overhead row (distributed-"
                         "tracing on vs off on an identical deterministic "
                         "schedule, paired rounds, <2%% budget asserted "
                         "like the telemetry row)")
    ap.add_argument("--router", action="store_true",
                    help="run only the router-failover row (single engine "
                         "vs a 2-replica EngineRouter fleet, fault-free "
                         "and under a deterministic engine-kill schedule: "
                         "goodput ratios + failover recovery_ms, with "
                         "inline token-identity asserts)")
    ap.add_argument("--sim-fidelity", action="store_true",
                    help="run only the sim-vs-real fidelity gate (one "
                         "recorded Poisson schedule replayed through the "
                         "live engine and the trace-driven fleet "
                         "simulator; predicted TTFT/ITL p50/p90 must land "
                         "within the committed relative tolerance — "
                         "SERVING_r15.json is this mode's output)")
    ap.add_argument("--chaos", action="store_true",
                    help="run only the chaos-serving row (fault-free "
                         "baseline vs a fixed fault schedule — transient "
                         "dispatch failures, a poisoned row, a KV-alloc "
                         "outage — plus a kill-and-resume leg reporting "
                         "recovery time and goodput; survivor outputs are "
                         "asserted token-identical)")
    args = ap.parse_args()
    if args.tp and args.tp > 1 and os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU was EXPLICITLY requested (this container's dev-smoke config /
        # tests/conftest.py): widen it to the virtual args.tp-device mesh.
        # The flag must land before the first jax.devices() call — once a
        # backend is initialized, platform updates no longer re-select it.
        # With JAX_PLATFORMS unset or an accelerator named, nothing is
        # forced: a real slice benches its real devices, and too few
        # devices is a loud error below, never a silent CPU hijack.
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.tp}")
    import jax
    if args.tp and args.tp > 1:
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")   # sitecustomize latch
        if len(jax.devices()) < args.tp:
            raise SystemExit(
                f"--tp {args.tp}: only {len(jax.devices())} devices visible "
                f"on platform {jax.default_backend()!r}; for a virtual CPU "
                "parity run set JAX_PLATFORMS=cpu explicitly")
    _logs_to_stderr()
    platform = jax.default_backend()
    if platform == "tpu":
        model, long_prompt = "gpt2-small", 768
        decode_cfgs = [(8, 128, 128), (32, 128, 128), (64, 128, 128)]
        prefill_cfgs = [(8, long_prompt)]
        mixed = (16, 256, 64)
        mixed_compiled = (16, (256, 64), 64)
        mixed_dynamic = (16, 256, 64, 32)      # last field: n_arrivals
        delta = (32, 512, 128)
        # near-full contexts (832 + 128 + 1 lookahead slot = 961 <= 1024,
        # exactly 8 pages/seq; 896 would need a 9th page past max_seq_len)
        delta_long = (16, 832, 128)
        medium_decode = ("gpt2-medium", 8, 128, 128)
        collapse = (128, 64)
    else:   # dev smoke
        model, long_prompt = "tiny", 64
        decode_cfgs = [(4, 16, 16)]
        prefill_cfgs = [(4, long_prompt)]
        mixed = (4, 32, 8)
        mixed_compiled = (4, (32, 16), 8)
        mixed_dynamic = (4, 32, 8, 8)
        delta = (4, 32, 16)
        delta_long = None
        medium_decode = None
        collapse = None

    rows = []

    def add(row):
        rows.append(row)
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)

    def guarded(tag, fn, *a, **kw):
        # a failed config is a structured row, never a raw traceback
        try:
            add(fn(*a, **kw))
        except Exception as e:
            add({"workload": tag, "status": "failed",
                 "error_type": type(e).__name__, "error": str(e)[:300]})

    if args.tp:
        # focused mode: the tensor-parallel scaling row only
        b, p, n, arr = mixed_dynamic
        guarded("tp-serving", bench_tp, model, b, p, n, tp=args.tp,
                n_arrivals=arr)
        row = next((r for r in rows if r.get("workload") == "tp-serving"),
                   {})
        print(json.dumps({
            "metric": "fastgen_serving_tp",
            "model": model, "platform": jax.default_backend(),
            "value": row.get("scaling_tok_per_sec_per_chip_vs_tp1"),
            "unit": f"tp={args.tp} tokens/s/chip vs single-chip baseline",
            "rows": rows,
        }))
        # the inline byte-identity / token-parity asserts are a hard
        # contract, exactly like the telemetry budget
        if any(r.get("workload") == "tp-serving"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.quant:
        # focused mode: the quantized-serving capacity/tolerance row only
        b, p, n, arr = mixed_dynamic
        # the slot table must outsize the burst so the ONLY admission
        # constraint is KV-pool pressure — the quantity under test
        guarded("quant-serving", bench_quant, model, max(b, 8), max(p, 32),
                n, n_arrivals=8)
        row = next((r for r in rows if r.get("workload") == "quant-serving"),
                   {})
        print(json.dumps({
            "metric": "fastgen_serving_quant",
            "model": model, "platform": platform,
            "value": row.get("slots_ratio_int8_over_f32"),
            "unit": "slots-until-first-deferral ratio int8-KV/f32 at one "
                    "KV HBM byte budget (block-bytes ratio "
                    f"{row.get('kv_block_bytes_ratio_f32_over_int8')})",
            "rows": rows,
        }))
        # the inline int8-KV token-identity asserts are a hard contract,
        # exactly like the telemetry budget
        if any(r.get("workload") == "quant-serving"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.prefix_cache:
        # focused mode: the KV-memory-hierarchy row only
        b, p, n, arr = mixed_dynamic
        guarded("prefix-cache", bench_prefix_cache, model, b,
                max(p, 2 * long_prompt), n, n_arrivals=max(arr, 12),
                assert_contract=(platform != "tpu"))
        row = next((r for r in rows if r.get("workload") == "prefix-cache"),
                   {})
        full = (row.get("sweep") or [{}])[-1]
        print(json.dumps({
            "metric": "fastgen_serving_prefix_cache",
            "model": model, "platform": platform,
            "value": full.get("ttft_p90_speedup"),
            "unit": "TTFT p90 speedup vs cold at full-share "
                    f"(hit rate {full.get('hit_rate')})",
            "rows": rows,
        }))
        # the inline token-identity + >=2x-TTFT asserts are a hard
        # contract, exactly like the telemetry budget
        if any(r.get("workload") == "prefix-cache"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.disagg:
        # focused mode: the disaggregated prefill/decode fleet row only
        if platform == "tpu":
            b = 32
            cfgs = dict(long_prompt=1024, short_prompt=64,
                        long_new=8, short_new=64, n_long=4, n_short=48)
        else:
            # chunk=8: a long prompt spans 32 chunk steps (16 two-step
            # frames), so a monolithic replica's stream is chunk-wide for
            # most of a long's prefill while a burst of 8-token shorts
            # admits in ONE cheap wide frame — the widest differential
            # wide-frame count the overhead-bound tiny model can show
            b = 16
            cfgs = dict(long_prompt=256, short_prompt=8,
                        long_new=4, short_new=24, n_long=4, n_short=45,
                        chunk=8)
        guarded("disagg-serving", bench_disagg, model, b,
                assert_contract=(platform != "tpu"), **cfgs)
        row = next((r for r in rows
                    if r.get("workload") == "disagg-serving"), {})
        print(json.dumps({
            "metric": "fastgen_serving_disagg",
            "model": model, "platform": platform,
            "value": row.get("ttft_p90_speedup"),
            "unit": "disagg/monolithic fleet TTFT p90 speedup "
                    f"(ITL p90 speedup {row.get('itl_p90_speedup')}) on a "
                    "long-prompt/short-decode mix at equal replica count",
            "rows": rows,
        }))
        # the inline token-identity + both-percentiles-improve asserts
        # are a hard contract, exactly like the telemetry budget
        if any(r.get("workload") == "disagg-serving"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.service:
        # focused mode: the service-edge row only
        b, p, n, arr = mixed_dynamic
        guarded("service-edge", bench_service, model, max(b, 8), p, n,
                n_arrivals=max(arr, 12), sessions=args.sessions,
                assert_contract=(platform != "tpu"))
        row = next((r for r in rows
                    if r.get("workload") == "service-edge"), {})
        print(json.dumps({
            "metric": "fastgen_serving_service",
            "model": model, "platform": platform,
            "value": (row.get("routing_overhead") or {}).get(
                "threaded_over_serial_tok_per_sec"),
            "unit": "threaded/serial fleet-driver tok/s ratio "
                    f"({(row.get('loadgen') or {}).get('sessions')} "
                    "closed-loop SSE sessions, zero parity violations "
                    "asserted)",
            "rows": rows,
        }))
        # the inline parity / shed-ordering / autoscale asserts are a
        # hard contract, exactly like the telemetry budget
        if any(r.get("workload") == "service-edge"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.tracing:
        # focused mode: the distributed-tracing overhead row only
        b, p, n, arr = mixed_dynamic
        guarded("tracing-overhead", bench_tracing_overhead, model, b, p, n,
                n_arrivals=arr, assert_budget=(platform != "tpu"))
        row = next((r for r in rows
                    if r.get("workload") == "tracing-overhead"), {})
        print(json.dumps({
            "metric": "fastgen_serving_tracing",
            "model": model, "platform": platform,
            "value": row.get("overhead_pct"),
            "unit": "distributed-tracing overhead % (paired on/off "
                    "rounds, <2% budget asserted in smoke)",
            "rows": rows,
        }))
        # the <2% tracing budget is a hard contract, exactly like the
        # telemetry budget
        if any(r.get("workload") == "tracing-overhead"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.router:
        # focused mode: the multi-engine failover row only
        b, p, n, arr = mixed_dynamic
        guarded("router-failover", bench_router, model, b, p, n,
                n_arrivals=max(arr, 8))
        row = next((r for r in rows
                    if r.get("workload") == "router-failover"), {})
        print(json.dumps({
            "metric": "fastgen_serving_router",
            "model": model, "platform": platform,
            "value": row.get("kill_goodput_ratio"),
            "unit": "kill+failover/single-engine goodput ratio "
                    "(deterministic engine-kill schedule)",
            "rows": rows,
        }))
        # the inline token-identity / completion asserts are a hard
        # contract, exactly like the telemetry budget
        if any(r.get("workload") == "router-failover"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.sim_fidelity:
        # focused mode: the sim-vs-real fidelity gate only
        b = mixed_dynamic[0]
        guarded("sim-fidelity", bench_sim_fidelity, model, batch=max(b, 8),
                assert_contract=(platform != "tpu"))
        guarded("sim-check", bench_sim_check)
        row = next((r for r in rows
                    if r.get("workload") == "sim-fidelity"), {})
        worst = max((c["rel_err"] for c in row.get("comparisons", [])),
                    default=None)
        print(json.dumps({
            "metric": "fastgen_serving_sim_fidelity",
            "model": model, "platform": platform,
            "value": worst,
            "unit": "worst sim-vs-live relative error over TTFT/ITL "
                    f"p50/p90 (tolerance {row.get('tolerance_rel')})",
            "rows": rows,
        }))
        # the fidelity tolerance and the sim's own --check gate are hard
        # contracts, exactly like the telemetry budget
        if any(r.get("workload") in ("sim-fidelity", "sim-check")
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.chaos:
        # focused mode: fault tolerance vs the fault-free baseline only
        b, p, n, arr = mixed_dynamic
        guarded("chaos-serving", bench_chaos, model, b, p, n,
                n_arrivals=max(arr, 12))
        row = next((r for r in rows if r.get("workload") == "chaos-serving"),
                   {})
        print(json.dumps({
            "metric": "fastgen_serving_chaos",
            "model": model, "platform": platform,
            "value": row.get("chaos_goodput_ratio"),
            "unit": "chaos/baseline goodput ratio (fixed fault schedule)",
            "rows": rows,
        }))
        # the chaos row's inline token-identity/leak asserts are a hard
        # contract, exactly like the telemetry budget
        if any(r.get("workload") == "chaos-serving"
               and r.get("error_type") == "AssertionError" for r in rows):
            sys.exit(1)
        return

    if args.scheduler:
        # focused mode: the FIFO-vs-SLO-aware overload row only
        b, p, n, _arr = mixed_dynamic
        guarded("scheduler-slo", bench_scheduler, model, b, p, n)
        row = next((r for r in rows if r.get("workload") == "scheduler-slo"),
                   {})
        print(json.dumps({
            "metric": "fastgen_serving_scheduler",
            "model": model, "platform": platform,
            "value": (row.get("slo_aware") or {}).get("interactive_ttft_p90_ms"),
            "unit": "SLO-aware interactive TTFT p90 (ms)",
            "rows": rows,
        }))
        return

    if args.speculate:
        # focused mode: the speculative serving rows only (the spec bench
        # internally re-runs the non-spec frame + host-step contenders on
        # the same Poisson schedule for the side-by-side columns)
        b, p, n, arr = mixed_dynamic
        # speculation only engages on pure-decode (width-1) frames: give the
        # schedule enough decode budget that rows outlive the prefill frames
        spec_frame_steps = 8
        n = max(n, 3 * spec_frame_steps)
        guarded("mixed-splitfuse-dynamic-spec", bench_mixed_dynamic_spec,
                model, b, p, n, n_arrivals=arr, gamma=args.gamma,
                frame_steps=spec_frame_steps)
        spec_rows = [r for r in rows
                     if r.get("workload") == "mixed-splitfuse-dynamic-spec"]
        best = max((r.get("spec_frame_tok_per_sec", 0) or 0
                    for r in spec_rows), default=0)
        print(json.dumps({
            "metric": "fastgen_serving_speculative",
            "model": model, "platform": platform,
            "value": best, "unit": "speculative serve tokens/s",
            "rows": rows,
        }))
        return

    for b, p, n in decode_cfgs:
        guarded("decode-heavy", bench_decode, model, b, p, n)
    for b, p in prefill_cfgs:
        guarded("prefill-heavy", bench_prefill, model, b, p)
    guarded("mixed-splitfuse", bench_mixed, model, *mixed)
    guarded("mixed-splitfuse-compiled", bench_mixed_compiled, model,
            *mixed_compiled)
    b, p, n, arr = mixed_dynamic
    guarded("mixed-splitfuse-dynamic", bench_mixed_dynamic, model, b, p, n,
            n_arrivals=arr)
    # telemetry budget: the <2% overhead contract is ASSERTED in the smoke
    # configuration (deterministic schedule, CPU) and reported on TPU
    guarded("telemetry-overhead", bench_telemetry_overhead, model, b, p, n,
            n_arrivals=arr, assert_budget=(platform != "tpu"))
    # distributed-tracing budget: same <2% contract, spans-on vs spans-off
    guarded("tracing-overhead", bench_tracing_overhead, model, b, p, n,
            n_arrivals=arr, assert_budget=(platform != "tpu"))
    # SLO-aware scheduling vs FIFO on a deterministic 2-tenant overload
    guarded("scheduler-slo", bench_scheduler, model, b, p, n)
    # the fleet simulator's own CI smoke (determinism, snapshot/resume,
    # real-policy execution) rides in the default row set: a sim that
    # stops being deterministic must fail THIS artifact, not wait for
    # someone to run the focused mode
    guarded("sim-check", bench_sim_check)
    guarded("kernel-delta", bench_kernel_delta, model, *delta)
    if delta_long is not None:
        guarded("kernel-delta", bench_kernel_delta, model, *delta_long)
    if medium_decode is not None:
        guarded("decode-heavy", bench_decode, *medium_decode)
    if collapse is not None:
        guarded("decode-collapse-probe", bench_decode_collapse_probe, model,
                *collapse)
    if platform == "tpu":
        guarded("woq-kernel-delta", bench_woq_delta)
        guarded("platform-floor", bench_platform_floor)

    best_decode = max((r.get("decode_tok_per_sec", 0) for r in rows), default=0)
    print(json.dumps({
        "metric": "fastgen_serving",
        "model": model, "platform": platform,
        "value": best_decode, "unit": "decode tokens/s",
        "rows": rows,
    }))
    # the telemetry/tracing <2% overhead budgets are hard contracts in the
    # smoke configuration: guarded() keeps the JSON complete, but a budget
    # breach must still fail the run (a swallowed assert is not an assert)
    if any(r.get("workload") in ("telemetry-overhead", "tracing-overhead",
                                 "sim-check")
           and r.get("error_type") == "AssertionError" for r in rows):
        sys.exit(1)


if __name__ == "__main__":
    main()
