#!/usr/bin/env python
"""Serving micro-benchmark: FastGen-analog decode throughput.

Measures tokens/sec of the compiled multi-token decode loop (Pallas paged
attention over in-place KV pages) at several batch sizes — the serving-side
counterpart of bench.py's training number. Reference bar: FastGen's
throughput claims (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(batch, model_name="gpt2-small", prompt_len=128, new_tokens=64):
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model

    platform = jax.default_backend()
    if platform != "tpu":
        model_name, prompt_len, new_tokens = "tiny", 16, 8
    cfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=max(batch, 16),
        max_tokens_per_step=max(batch * 2, 768),
    )
    model = build_model(model_name)
    eng = InferenceEngineV2(model, cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(batch)]
    # warmup (compiles prefill chunks + decode loop at both step counts)
    eng.generate(prompts, max_new_tokens=4)
    eng.generate(prompts, max_new_tokens=new_tokens)
    # decode throughput = marginal cost of (new_tokens - 4) extra tokens,
    # cancelling the prefill both runs share
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=4)
    t1 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=new_tokens)
    t2 = time.perf_counter()
    dt = (t2 - t1) - (t1 - t0)
    toks = batch * (new_tokens - 4)
    return {"batch": batch, "decode_tok_per_sec": round(toks / dt, 1),
            "e2e_tok_per_sec": round(batch * new_tokens / (t2 - t1), 1),
            "prompt_len": prompt_len, "new_tokens": new_tokens,
            "platform": platform}


def main():
    results = [bench(b) for b in (16, 64)]
    print(json.dumps({"metric": "fastgen_decode_throughput", "results": results}))


if __name__ == "__main__":
    main()
