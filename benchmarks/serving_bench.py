#!/usr/bin/env python
"""Serving benchmark: FastGen-analog measured end to end.

Produces the recorded artifact the round-2 review demanded (SERVING_rNN.json
via `python benchmarks/serving_bench.py > SERVING_rNN.json`): one JSON object
with a row per workload — decode-heavy, prefill-heavy, and mixed Dynamic-
SplitFuse — each carrying tokens/sec, per-step latency p50/p95, KV-pool
utilization, and host-scheduler overhead, plus the paged-Pallas vs XLA-gather
decode delta. Reference bar shape: ``blogs/deepspeed-fastgen/README.md:28,139``
(FastGen reports effective throughput and p50/p95 latency trade-offs; the
absolute rows here are gpt2-small-class on one v5e chip).

Methodology (tunneled single-chip platform, see bench.py):
- decode throughput uses the COMPILED multi-token loop (one dispatch for N
  tokens) — per-dispatch tunnel latency would otherwise dominate;
- the mixed workload intentionally uses host-driven ``step()`` so the number
  includes the real SplitFuse scheduler cost, which is reported separately
  as ``sched_overhead_pct`` (host wall-time share of the step loop);
- timings sync via device_get of values data-dependent on the step.
"""

import json
import logging
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _logs_to_stderr():
    """The package logger streams to stdout (reference behavior); the bench
    must keep stdout pure JSON so `> SERVING_rNN.json` works as documented."""
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if hasattr(h, "stream"):
            h.stream = sys.stderr


def _mk_engine(model_name, batch, max_seq_len=None):
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import build_model
    cfg = RaggedInferenceEngineConfig(
        max_ragged_batch_size=max(batch, 16),
        max_tokens_per_step=max(batch * 2, 768),
    )
    model = build_model(model_name)
    return InferenceEngineV2(model, cfg, max_seq_len=max_seq_len)


def bench_platform_floor():
    """Measured per-op floor of the tunneled chip — the context for every
    absolute number in this artifact: streamed-HBM ops cost ~2 ms regardless
    of size (~15 GB/s effective vs the 819 GB/s v5e spec), so decode steps
    are op-floor-bound here, not a property of the engine design."""
    import time
    import jax
    import jax.numpy as jnp
    from jax import lax
    n = 32 * 1024 * 1024 // 2
    xs = jnp.ones((8, n), jnp.bfloat16)

    @jax.jit
    def run(xs, c):
        def body(c, x):
            return c + jnp.sum(x.astype(jnp.float32)), ()
        def rep(c, _):
            c, _n = lax.scan(body, c, xs)
            return c, ()
        c, _ = lax.scan(rep, c, None, length=6)
        return c

    c0 = jnp.zeros((), jnp.float32)
    run(xs, c0)
    jax.device_get(run(xs, c0))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(run(xs, c0))
        best = min(best, time.perf_counter() - t0)
    per = best / 48
    return {"workload": "platform-floor",
            "stream_32mb_op_ms": round(per * 1e3, 3),
            "effective_hbm_gbps": round(32 / 1024 / per, 1)}


def _kv_util(eng):
    total = eng.kv.num_blocks
    return round(1.0 - eng.kv.free_blocks / total, 4)


def bench_decode(model_name, batch, prompt_len, new_tokens):
    """Decode-heavy: steady-state generation throughput (compiled loop)."""
    eng = _mk_engine(model_name, batch)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, eng.model.cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(batch)]
    eng.generate(prompts, max_new_tokens=4)          # compile both step counts
    eng.generate(prompts, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=4)
    t1 = time.perf_counter()
    # KV utilization at the deepest point of the long run
    eng.put(list(range(batch)), prompts)
    while any(eng.state.seqs[u].in_prefill for u in range(batch)):
        eng.step()
    util = _kv_util(eng)
    eng.flush(list(range(batch)))
    t1b = time.perf_counter()
    eng.generate(prompts, max_new_tokens=new_tokens)
    t2 = time.perf_counter()
    decode_dt = (t2 - t1b) - (t1 - t0)               # marginal decode cost
    toks = batch * (new_tokens - 4)
    return {
        "workload": "decode-heavy", "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "decode_tok_per_sec": round(toks / decode_dt, 1),
        "decode_ms_per_token_per_seq": round(decode_dt / (new_tokens - 4) * 1e3, 2),
        "e2e_tok_per_sec": round(batch * new_tokens / (t2 - t1b), 1),
        "kv_util_after_prefill": util,
    }


def bench_prefill(model_name, batch, prompt_len):
    """Prefill-heavy: prompt-token ingestion throughput via SplitFuse chunks."""
    eng = _mk_engine(model_name, batch)
    rng = np.random.default_rng(1)

    def run():
        prompts = [rng.integers(0, eng.model.cfg.vocab_size,
                                (prompt_len,)).astype(np.int32)
                   for _ in range(batch)]
        uids = list(range(batch))
        eng.put(uids, prompts)
        lat = []
        t0 = time.perf_counter()
        while any(eng.state.seqs[u].in_prefill for u in uids):
            s = time.perf_counter()
            eng.step()
            lat.append(time.perf_counter() - s)
        dt = time.perf_counter() - t0
        util = _kv_util(eng)
        eng.flush(uids)
        return dt, lat, util

    run()                                             # compile
    dt, lat, util = run()
    total = batch * prompt_len
    return {
        "workload": "prefill-heavy", "batch": batch, "prompt_len": prompt_len,
        "prefill_tok_per_sec": round(total / dt, 1),
        "step_ms_p50": round(statistics.median(lat) * 1e3, 2),
        "step_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 2),
        "kv_util_peak": util,
    }


def bench_mixed(model_name, batch, prompt_len, new_tokens):
    """Mixed SplitFuse: half the fleet decodes while half prefills — the
    host-driven step() loop, so the scheduler cost is IN the number."""
    eng = _mk_engine(model_name, batch)
    rng = np.random.default_rng(2)
    vocab = eng.model.cfg.vocab_size

    def run():
        uids_a = list(range(0, batch // 2))
        uids_b = list(range(batch // 2, batch))
        eng.put(uids_a, [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
                         for _ in uids_a])
        # drive group A into decode
        while any(eng.state.seqs[u].in_prefill for u in uids_a):
            eng.step()
        # group B arrives: steps now fuse B's prefill chunks with A's decodes
        eng.put(uids_b, [rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
                         for _ in uids_b])
        lat, produced = [], 0
        # time the scheduler from INSIDE step() (wrapping the bound method)
        # so each iteration schedules exactly once
        sched_box = [0.0]
        orig_schedule = eng._schedule

        def timed_schedule():
            s = time.perf_counter()
            out = orig_schedule()
            sched_box[0] += time.perf_counter() - s
            return out

        eng._schedule = timed_schedule
        t0 = time.perf_counter()
        while (any(eng.state.seqs[u].in_prefill for u in uids_b)
               or min(len(eng.state.seqs[u].generated) for u in uids_a + uids_b)
               < new_tokens):
            s = time.perf_counter()
            out = eng.step()
            produced += len(out)
            lat.append(time.perf_counter() - s)
        dt = time.perf_counter() - t0
        eng._schedule = orig_schedule
        sched_t = sched_box[0]
        util = _kv_util(eng)
        eng.flush(uids_a + uids_b)
        return dt, lat, sched_t, produced, util

    run()                                             # compile
    dt, lat, sched_t, produced, util = run()
    return {
        "workload": "mixed-splitfuse", "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "generated_tok_per_sec": round(produced / dt, 1),
        "step_ms_p50": round(statistics.median(lat) * 1e3, 2),
        "step_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 2),
        "sched_overhead_pct": round(100 * sched_t / dt, 2),
        "steps": len(lat), "kv_util_peak": util,
    }


def bench_kernel_delta(model_name, batch, prompt_len, new_tokens):
    """Paged-Pallas vs XLA-gather decode delta (same workload, kernel off)."""
    rows = {}
    for mode, env in (("paged_pallas", "0"), ("xla_gather", "1")):
        os.environ["DS_TPU_DISABLE_PALLAS"] = env
        try:
            r = bench_decode(model_name, batch, prompt_len, new_tokens)
            rows[mode] = r["decode_tok_per_sec"]
        finally:
            os.environ.pop("DS_TPU_DISABLE_PALLAS", None)
    if rows.get("xla_gather"):
        rows["pallas_speedup"] = round(rows["paged_pallas"] / rows["xla_gather"], 3)
    return {"workload": "kernel-delta", "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, **rows}


def main():
    import jax
    _logs_to_stderr()
    platform = jax.default_backend()
    if platform == "tpu":
        model, long_prompt = "gpt2-small", 768
        decode_cfgs = [(8, 128, 128), (32, 128, 128), (64, 128, 128)]
        prefill_cfgs = [(8, long_prompt)]
        mixed = (16, 256, 64)
        delta = (32, 512, 128)
    else:   # dev smoke
        model, long_prompt = "tiny", 64
        decode_cfgs = [(4, 16, 16)]
        prefill_cfgs = [(4, long_prompt)]
        mixed = (4, 32, 8)
        delta = (4, 32, 16)

    rows = []
    for b, p, n in decode_cfgs:
        rows.append(bench_decode(model, b, p, n))
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    for b, p in prefill_cfgs:
        rows.append(bench_prefill(model, b, p))
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    rows.append(bench_mixed(model, *mixed))
    print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    rows.append(bench_kernel_delta(model, *delta))
    print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    if platform == "tpu":
        rows.append(bench_platform_floor())
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)

    best_decode = max((r.get("decode_tok_per_sec", 0) for r in rows), default=0)
    print(json.dumps({
        "metric": "fastgen_serving",
        "model": model, "platform": platform,
        "value": best_decode, "unit": "decode tokens/s",
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
