#!/usr/bin/env python
"""MoE dispatch-path throughput: capacity einsum vs dropless grouped.

The round-4 review asked for a recorded throughput row next to the
dropless-under-EP equivalence tests (``tests/test_models.py::
test_moe_grouped_ep_*``). On this 1-chip platform the expert axis cannot be
really sharded, so the measured rows compare the two dispatch paths at
ep=1 (where "grouped" is the sort+ragged_dot megablox path the EP ring
reuses per shard); the EP ring itself is validated for equivalence on the
virtual 8-device mesh and its throughput character is the local ragged_dot
plus two all-to-alls over ICI.

Prints one JSON line; run with the repo root on sys.path.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_path(moe_impl, tokens, hidden, ffn, experts, k, iters=20):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import layers as L
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=hidden, num_layers=1, num_heads=8,
        intermediate_size=ffn, moe_intermediate_size=ffn, num_experts=experts,
        num_experts_per_tok=k, moe_impl=moe_impl, moe_capacity_factor=1.25,
        max_seq_len=4096, dtype="bfloat16")
    params, _ = L.init_moe_mlp(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, tokens, hidden)),
                    jnp.bfloat16)

    @jax.jit
    def run(params, x):
        def body(c, _):
            y, aux = L.apply_moe_mlp(params, c, cfg)
            return (y * 0.5 + c * 0.5).astype(c.dtype), aux
        y, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.sum(y.astype(jnp.float32))

    jax.device_get(run(params, x))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(run(params, x))
        best = min(best, time.perf_counter() - t0)
    return tokens * iters / best


def main():
    import jax
    platform = jax.default_backend()
    if platform == "tpu":
        shape = dict(tokens=4096, hidden=1024, ffn=2816, experts=8, k=2)
    else:
        shape = dict(tokens=256, hidden=64, ffn=128, experts=4, k=2,
                     iters=3)
    rows = {}
    for impl in ("einsum", "grouped"):
        rows[impl] = round(bench_path(impl, **shape), 1)
    out = {
        "metric": "moe_dispatch_tokens_per_sec", "platform": platform,
        "shape": shape, "einsum_tok_per_sec": rows["einsum"],
        "grouped_tok_per_sec": rows["grouped"],
        "grouped_speedup": round(rows["grouped"] / rows["einsum"], 3),
        "note": "dropless grouped (sort + ragged_dot) vs capacity einsum "
                "dispatch at ep=1; the EP ring variant (explicit all-to-all "
                "+ per-shard ragged_dot) is equivalence-tested on the "
                "virtual 8-device mesh — 1 real chip cannot shard the "
                "expert axis",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
