#!/usr/bin/env python
"""MoE dispatch-path throughput: capacity einsum vs dropless grouped.

The round-4 review asked for a recorded throughput row next to the
dropless-under-EP equivalence tests (``tests/test_models.py::
test_moe_grouped_ep_*``). On this 1-chip platform the expert axis cannot be
really sharded, so the measured rows compare the two dispatch paths at
ep=1 (where "grouped" is the sort+ragged_dot megablox path the EP ring
reuses per shard); the EP ring itself is validated for equivalence on the
virtual 8-device mesh and its throughput character is the local ragged_dot
plus two all-to-alls over ICI.

Prints one JSON line; run with the repo root on sys.path.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_path(moe_impl, tokens, hidden, ffn, experts, k, iters=20):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import layers as L
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.utils import groups

    groups.reset_mesh()
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=hidden, num_layers=1, num_heads=8,
        intermediate_size=ffn, moe_intermediate_size=ffn, num_experts=experts,
        num_experts_per_tok=k, moe_impl=moe_impl, moe_capacity_factor=1.25,
        max_seq_len=4096, dtype="bfloat16")
    params, _ = L.init_moe_mlp(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, tokens, hidden)),
                    jnp.bfloat16)

    @jax.jit
    def run(params, x):
        def body(c, _):
            y, aux = L.apply_moe_mlp(params, c, cfg)
            return (y * 0.5 + c * 0.5).astype(c.dtype), aux
        y, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.sum(y.astype(jnp.float32))

    jax.device_get(run(params, x))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(run(params, x))
        best = min(best, time.perf_counter() - t0)
    return tokens * iters / best


def bench_ep_virtual(tokens, hidden, ffn, experts, k, iters=5):
    """EP-ring comm-pattern row on the virtual 8-device CPU mesh (r4 review:
    the sharded-EP variant had equivalence tests only, no recorded perf
    character). CPU wall time is NOT a TPU number — the row records the
    RELATIVE cost of the a2a ring vs the local grouped path on the same
    mesh, i.e. the dispatch/comm overhead structure."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import layers as L
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.utils import groups

    out = {}
    for ep in (1, 4):
        groups.reset_mesh()
        groups.set_mesh(groups.build_mesh(expert=ep, data=8 // ep))
        cfg = TransformerConfig(
            vocab_size=256, hidden_size=hidden, num_layers=1, num_heads=8,
            intermediate_size=ffn, moe_intermediate_size=ffn,
            num_experts=experts, num_experts_per_tok=k, moe_impl="grouped",
            max_seq_len=4096, dtype="float32")
        params, _ = L.init_moe_mlp(jax.random.PRNGKey(0), cfg)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(8, tokens // 8, hidden)), jnp.float32)

        @jax.jit
        def run(params, x):
            def body(c, _):
                y, aux = L.apply_moe_mlp(params, c, cfg)
                return (y * 0.5 + c * 0.5).astype(c.dtype), aux
            y, _ = jax.lax.scan(body, x, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))

        jax.device_get(run(params, x))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_get(run(params, x))
            best = min(best, time.perf_counter() - t0)
        out[f"ep{ep}_tok_per_sec"] = round(tokens * iters / best, 1)
    out["ep_ring_relative"] = round(out["ep4_tok_per_sec"] /
                                    out["ep1_tok_per_sec"], 3)
    return out


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--ep-virtual", action="store_true",
                    help="run the EP-ring row on a forced CPU mesh")
    args = ap.parse_args()
    if args.ep_virtual:
        print(json.dumps(bench_ep_virtual(tokens=2048, hidden=256, ffn=512,
                                          experts=8, k=2)))
        return

    import jax
    platform = jax.default_backend()
    if platform == "tpu":
        shape = dict(tokens=4096, hidden=1024, ffn=2816, experts=8, k=2)
    else:
        shape = dict(tokens=256, hidden=64, ffn=128, experts=4, k=2,
                     iters=3)
    rows = {}
    for impl in ("einsum", "grouped"):
        rows[impl] = round(bench_path(impl, **shape), 1)
    # EP ring on the virtual mesh: separate process (the backend must be
    # forced to CPU before jax initializes)
    import subprocess
    ep_row = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                              " --xla_force_host_platform_device_count=8").strip())
        res = subprocess.run([sys.executable, os.path.abspath(__file__),
                              "--ep-virtual"], env=env, capture_output=True,
                             text=True, timeout=900)
        for ln in reversed(res.stdout.splitlines()):
            if ln.startswith("{"):
                ep_row = json.loads(ln)
                break
        if ep_row is None:
            # a null row is indistinguishable from "not run": record the
            # child's failure instead
            ep_row = {"error": f"rc={res.returncode}: "
                               f"{res.stderr.strip()[-200:]}"}
    except Exception as e:
        ep_row = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
    out = {
        "metric": "moe_dispatch_tokens_per_sec", "platform": platform,
        "shape": shape, "einsum_tok_per_sec": rows["einsum"],
        "grouped_tok_per_sec": rows["grouped"],
        "grouped_speedup": round(rows["grouped"] / rows["einsum"], 3),
        "ep_virtual_mesh": ep_row,
        "note": "dropless grouped (sort + ragged_dot) vs capacity einsum "
                "dispatch at ep=1 on the real chip; ep_virtual_mesh records "
                "the EP a2a-ring's relative cost on the virtual 8-device "
                "CPU mesh (comm-pattern sanity — 1 real chip cannot shard "
                "the expert axis)",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
