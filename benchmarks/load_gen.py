"""Closed-loop load generator for the HTTP/SSE service edge.

The first benchmark that measures the system as TRAFFIC experiences it:
N concurrent closed-loop sessions (each a thread holding a persistent
conversation: submit -> stream tokens -> think -> submit the next turn)
against a real network endpoint (``service.edge.ServiceEdge``), not
against an in-process arrival iterator. Closed-loop means each session
waits for its own completion before its next turn — the offered load
self-regulates like real users, and a 429 (edge shed) is honored by
sleeping the server's ``Retry-After`` before retrying, so the measured
latency includes honest back-pressure.

Determinism: every session's prompts, budgets, and think times derive
from ``--seed``; the TOKEN-PARITY check replays every request through a
direct single-engine ``serve()`` (the repo's greedy token-identity
invariant makes batching/placement irrelevant) and asserts the STREAMED
bytes match exactly. Zero parity violations across >= 200 concurrent
sessions is the acceptance bar (ISSUE 14).

Run self-hosted (builds a tiny fleet + edge in-process, CPU smoke):

    python benchmarks/load_gen.py --self-host --sessions 200 --turns 2

or against an external endpoint (no parity check unless --reference):

    python benchmarks/load_gen.py --url http://127.0.0.1:8100
"""

import argparse
import http.client
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB = 200          # tiny-model-safe token id range


# ----------------------------------------------------------------------
# deterministic workload
# ----------------------------------------------------------------------

def build_schedule(sessions: int, turns: int, prompt_len: int,
                   max_new: int, think_ms: float, seed: int
                   ) -> Dict[Tuple[int, int], Dict]:
    """(session, turn) -> {prompt, max_new_tokens, think_s, tenant,
    priority}. Pure function of the arguments — the parity reference
    replays exactly this."""
    rng = np.random.default_rng(seed)
    sched = {}
    for s in range(sessions):
        for t in range(turns):
            plen = int(rng.integers(max(4, prompt_len // 2),
                                    prompt_len + 1))
            sched[(s, t)] = {
                "prompt": [int(x) for x in rng.integers(0, VOCAB, (plen,))],
                "max_new_tokens": int(rng.integers(max(1, max_new // 2),
                                                   max_new + 1)),
                "think_s": float(rng.uniform(0.2, 1.0)) * think_ms * 1e-3,
                "tenant": f"t{s % 4}",
                "priority": "interactive" if s % 3 else "batch",
            }
    return sched


# ----------------------------------------------------------------------
# SSE client (stdlib only)
# ----------------------------------------------------------------------

def sse_generate(host: str, port: int, body: Dict, timeout: float = 120.0):
    """POST /v1/generate and consume the SSE stream. Returns
    ``(status, result)``: status 200 -> result = {"streamed": [...],
    "done": [...], "ttft_s": ...}; status 429 -> result = retry-after
    seconds; else result = error text."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = time.monotonic()
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status == 429:
            retry = float(resp.getheader("Retry-After") or 1.0)
            resp.read()
            return 429, retry
        if resp.status != 200:
            return resp.status, resp.read().decode(errors="replace")
        streamed: List[int] = []
        done: Optional[List[int]] = None
        error = None
        ttft = None
        buf = b""
        while True:
            line = resp.readline()
            if not line:
                break
            buf += line
            if line != b"\n":
                continue
            ev, data = None, None
            for ln in buf.decode().strip().splitlines():
                if ln.startswith("event: "):
                    ev = ln[7:]
                elif ln.startswith("data: "):
                    data = json.loads(ln[6:])
            buf = b""
            if ev == "token":
                if ttft is None:
                    ttft = time.monotonic() - t0
                streamed.extend(data["tokens"])
            elif ev == "done":
                done = data["tokens"]
                break
            elif ev == "error":
                error = data
                break
        if error is not None:
            return -1, error
        return 200, {"streamed": streamed, "done": done,
                     "ttft_s": ttft if ttft is not None
                     else time.monotonic() - t0,
                     "e2e_s": time.monotonic() - t0}
    finally:
        conn.close()


# ----------------------------------------------------------------------
# closed-loop sessions
# ----------------------------------------------------------------------

def _aggregate(results: Dict, failures: List[str], sheds: Dict,
               elapsed: float, **mode_fields) -> Dict:
    """Shared report tail for ``run_load``/``run_open_loop`` — one
    definition of the mismatch check, percentile summaries, and report
    keys, so closed- and open-loop runs can never drift apart. Callers
    pass SNAPSHOTS (taken under their lock — a straggler thread past the
    join timeout may still be writing)."""
    stream_mismatch = [
        k for k, v in results.items()
        if v["done"] is None or v["streamed"] != v["done"]]
    ttfts = sorted(v["ttft_s"] for v in results.values())
    e2es = sorted(v["e2e_s"] for v in results.values())
    toks = sum(len(v["done"] or ()) for v in results.values())

    def pct(xs, p):
        return round(float(np.percentile(xs, p)) * 1e3, 2) if xs else None

    return {
        **mode_fields,
        "completed": len(results),
        "failures": failures[:20], "n_failures": len(failures),
        "edge_sheds_seen": sheds["count"],
        "retry_wait_s": round(sheds["retry_wait_s"], 2),
        "stream_vs_done_mismatches": len(stream_mismatch),
        "elapsed_s": round(elapsed, 3),
        "tokens": toks,
        "tok_per_sec": round(toks / max(elapsed, 1e-9), 1),
        "ttft_ms": {"p50": pct(ttfts, 50), "p90": pct(ttfts, 90),
                    "p99": pct(ttfts, 99)},
        "e2e_ms": {"p50": pct(e2es, 50), "p90": pct(e2es, 90)},
        "_results": results,       # stripped before JSON dump
    }


def run_load(host: str, port: int, sched: Dict, sessions: int, turns: int,
             max_shed_retries: int = 20) -> Dict:
    """Drive the schedule with one thread per session; returns the
    aggregate report (latencies, sheds, failures, and every request's
    streamed/done tokens for the parity check)."""
    results: Dict[Tuple[int, int], Dict] = {}
    lock = threading.Lock()
    failures: List[str] = []
    sheds = {"count": 0, "retry_wait_s": 0.0}

    def session(s: int) -> None:
        for t in range(turns):
            req = sched[(s, t)]
            time.sleep(req["think_s"])
            body = {k: req[k] for k in ("prompt", "max_new_tokens",
                                        "tenant", "priority")}
            body["session"] = f"s{s}"
            tries = 0
            while True:
                status, out = sse_generate(host, port, body)
                if status == 200:
                    with lock:
                        results[(s, t)] = out
                    break
                if status == 429 and tries < max_shed_retries:
                    tries += 1
                    with lock:
                        sheds["count"] += 1
                        sheds["retry_wait_s"] += out
                    time.sleep(min(float(out), 5.0))
                    continue
                with lock:
                    failures.append(f"({s},{t}): status={status} {out}")
                return

    threads = [threading.Thread(target=session, args=(s,), daemon=True)
               for s in range(sessions)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=600)
    elapsed = time.monotonic() - t0
    with lock:
        snap, fails, shed_snap = dict(results), list(failures), dict(sheds)
    return _aggregate(snap, fails, shed_snap, elapsed,
                      sessions=sessions, turns=turns,
                      requests=sessions * turns)


# ----------------------------------------------------------------------
# open-loop (arrival-rate) sessions — the PR-12 ROADMAP follow-up
# ----------------------------------------------------------------------

def run_open_loop(host: str, port: int, sched: Dict, rate: float) -> Dict:
    """OPEN-loop load: requests fire at a fixed arrival RATE on their own
    threads — nobody waits for a previous completion, so offered load
    does NOT self-regulate and overload actually lands on the edge
    (closed-loop sessions slow down with the system and can never
    overdrive it). Each scheduled request (session, turn) launches at a
    deterministic offset ``i / rate`` seconds; an edge shed (429) is
    counted and DROPPED — in an open-loop world the arrival is lost, not
    retried, which is exactly the regime tracing overhead must be
    measured under. Returns the same report shape as ``run_load`` (shed
    requests are not failures; ``completed + edge_sheds_seen`` accounts
    for every arrival)."""
    order = sorted(sched)
    results: Dict[Tuple[int, int], Dict] = {}
    lock = threading.Lock()
    failures: List[str] = []
    sheds = {"count": 0, "retry_wait_s": 0.0}
    start = time.monotonic() + 0.05        # common launch epoch

    def fire(i: int, key) -> None:
        req = sched[key]
        sched_t = start + i / max(rate, 1e-6)   # INTENDED arrival
        delay = sched_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        body = {k: req[k] for k in ("prompt", "max_new_tokens",
                                    "tenant", "priority")}
        body["session"] = f"s{key[0]}"
        send_t = time.monotonic()               # ACTUAL send
        status, out = sse_generate(host, port, body)
        with lock:
            if status == 200:
                # stamp both times: schedule-relative latency charges the
                # request from when it was SUPPOSED to arrive, so a lagging
                # generator (thread wakeup under load) can't flatter the
                # system by silently closing the loop
                out["sched_t"] = sched_t
                out["send_t"] = send_t
                results[key] = out
            elif status == 429:
                sheds["count"] += 1
                sheds["retry_wait_s"] += out
            else:
                failures.append(f"{key}: status={status} {out}")

    threads = [threading.Thread(target=fire, args=(i, key), daemon=True)
               for i, key in enumerate(order)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=600)
    elapsed = time.monotonic() - t0
    with lock:
        snap, fails, shed_snap = dict(results), list(failures), dict(sheds)
    report = _aggregate(snap, fails, shed_snap, elapsed,
                        mode="open-loop", arrival_rate_per_s=rate,
                        requests=len(order))
    # schedule-relative view: TTFT measured from the INTENDED arrival
    # (sched_t), plus the generator's own lag (send_t - sched_t). If lag
    # is material relative to the latencies reported, the run was
    # generator-bound, not system-bound — sched_ttft_ms is the honest
    # number either way, and the one the fleet simulator predicts.
    lags = sorted(v["send_t"] - v["sched_t"] for v in snap.values())
    sched_ttfts = sorted(v["send_t"] - v["sched_t"] + v["ttft_s"]
                         for v in snap.values())

    def pct(xs, p):
        return round(float(np.percentile(xs, p)) * 1e3, 2) if xs else None

    report["gen_lag_ms"] = {"p50": pct(lags, 50), "p90": pct(lags, 90),
                            "max": pct(lags, 100)}
    report["sched_ttft_ms"] = {"p50": pct(sched_ttfts, 50),
                               "p90": pct(sched_ttfts, 90),
                               "p99": pct(sched_ttfts, 99)}
    return report


# ----------------------------------------------------------------------
# parity reference: direct serve() of the same schedule
# ----------------------------------------------------------------------

def direct_reference(mk_engine, sched: Dict) -> Dict[Tuple[int, int], List]:
    """Every scheduled request through ONE fresh engine's serve() —
    greedy outputs are placement/batching-independent, so this is THE
    token-identity reference for whatever the fleet streamed."""
    eng = mk_engine()
    uids = {}
    items = []
    for i, (key, req) in enumerate(sorted(sched.items())):
        uids[i] = key
        items.append({"uid": i, "tokens": req["prompt"],
                      "max_new_tokens": req["max_new_tokens"]})
    out = {}
    CHUNK = 16      # keep the queue bounded; admission defers overflow
    def arrivals():
        for i in range(0, len(items), CHUNK):
            yield items[i:i + CHUNK]
    for uid, toks in eng.serve(arrivals(), max_new_tokens=8):
        out[uids[uid]] = [int(t) for t in toks]
    return out


def check_parity(report: Dict, ref: Dict) -> int:
    """Count parity violations: streamed tokens must be byte-identical
    to the direct reference for every completed request."""
    bad = report["stream_vs_done_mismatches"]
    for key, v in report["_results"].items():
        if v["done"] != ref.get(key):
            bad += 1
    return bad


# ----------------------------------------------------------------------
# self-hosted harness (CPU smoke fleet)
# ----------------------------------------------------------------------

def build_fleet(replicas: int, batch: int, max_seq_len: int,
                scheduler: bool, edge_cfg=None, autoscale: bool = False):
    """Tiny fleet + threaded driver + edge, for self-hosted runs and the
    serving bench. Returns (router, driver, edge, mk_engine)."""
    import jax
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.router import EngineRouter
    from deepspeed_tpu.inference.v2.scheduler import (RequestScheduler,
                                                      SchedulerConfig)
    from deepspeed_tpu.inference.v2.service import (AutoscaleController,
                                                    EdgeConfig, FleetDriver,
                                                    ServiceEdge)
    from deepspeed_tpu.models import build_model

    model = build_model("tiny", num_heads=8)
    params = model.init(jax.random.PRNGKey(0))

    def mk_engine():
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(
            kv_block_size=16, prefill_chunk_size=8,
            max_tokens_per_step=1024, dtype="float32",
            max_ragged_batch_size=batch, frame_steps=2,
            frame_retry_backoff_s=0.0), params=params,
            max_seq_len=max_seq_len)

    router = EngineRouter({f"replica{i}": mk_engine()
                           for i in range(replicas)})
    sched_factory = None
    if scheduler:
        sched_factory = lambda: RequestScheduler(SchedulerConfig(  # noqa
            lookahead_reserve=True))
    driver = FleetDriver(
        router,
        autoscaler=AutoscaleController() if autoscale else None)
    driver.start(max_new_tokens=8, scheduler_factory=sched_factory)
    edge = ServiceEdge(driver, edge_cfg or EdgeConfig()).start()
    return router, driver, edge, mk_engine


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default=None,
                    help="existing endpoint (http://host:port); default "
                         "is --self-host")
    ap.add_argument("--self-host", action="store_true",
                    help="build a tiny in-process fleet + edge and drive "
                         "it (CPU smoke; enables the parity check)")
    ap.add_argument("--sessions", type=int, default=200)
    ap.add_argument("--turns", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--think-ms", type=float, default=200.0)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--open-loop", action="store_true",
                    help="arrival-RATE mode: requests fire at --rate/s "
                         "regardless of completions (offered load does "
                         "not self-regulate; 429s are dropped, not "
                         "retried)")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="open-loop arrival rate, requests/s (default 20)")
    ap.add_argument("--scheduler", action="store_true",
                    help="self-host with the SLO-aware RequestScheduler "
                         "(+ admission lookahead) per replica")
    ap.add_argument("--out", default=None, help="write the JSON report "
                                                "here as well as stdout")
    args = ap.parse_args()

    sched = build_schedule(args.sessions, args.turns, args.prompt_len,
                           args.max_new, args.think_ms, args.seed)
    ref = None
    if args.url and not args.self_host:
        host, port = args.url.split("//")[-1].split(":")
        port = int(port)
        edge = driver = None
    else:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        router, driver, edge, mk_engine = build_fleet(
            args.replicas, args.batch,
            max_seq_len=2 * (args.prompt_len + args.max_new) + 32,
            scheduler=args.scheduler)
        host, port = "127.0.0.1", edge.edge_port
        ref = direct_reference(mk_engine, sched)

    if args.open_loop:
        report = run_open_loop(host, port, sched, args.rate)
    else:
        report = run_load(host, port, sched, args.sessions, args.turns)
    if ref is not None:
        report["parity_violations"] = check_parity(report, ref)
    report.pop("_results")
    if edge is not None:
        report["edge_counters"] = dict(edge.counters)
        report["driver"] = driver.stats()["driver"]
        edge.shutdown()
        driver.stop()
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    # open-loop: a shed arrival is lost by design, not a failure — every
    # arrival must still be ACCOUNTED for (completed or shed)
    accounted = report["completed"] + (report["edge_sheds_seen"]
                                       if args.open_loop else 0)
    ok = (accounted == report["requests"]
          and report["n_failures"] == 0
          and report["stream_vs_done_mismatches"] == 0
          and report.get("parity_violations", 0) == 0)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
