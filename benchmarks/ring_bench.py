"""Ring attention: Pallas flash kernel vs einsum ring at long context.

Runs on the virtual 8-device CPU mesh (multi-chip CP is exactly what the one
real chip cannot host), 32k tokens over 8 ring ranks. Two metrics per path:

- XLA ``temp_size`` from the compiled memory analysis — the scratch the ring
  body actually allocates. The einsum ring's fp32 (B, H, 512, S/n) score
  chunks live here; the flash ring keeps scores in (block_q, block_k) VMEM
  tiles (interpret-mode on CPU, but the allocation shape is the design).
- wall time per forward (CPU throughput is NOT the TPU number — the row is
  a relative sanity check, the memory column is the load-bearing one).

Writes one JSON line; the round artifact captures it as RING_r{N}.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def measure(use_flash: bool, b, s, h, kvh, d):
    os.environ["DS_TPU_RING_FLASH"] = "1" if use_flash else "0"
    from deepspeed_tpu.sequence import ring_attention as ra
    from deepspeed_tpu.utils import groups
    groups.reset_mesh()                       # also clears the ring cache
    groups.set_mesh(groups.build_mesh(seq=8))
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, kvh, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, kvh, d)), jnp.float32)

    fn = jax.jit(lambda q, k, v: ra.ring_attention(q, k, v))
    lowered = fn.lower(q, k, v)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    out = jax.block_until_ready(compiled(q, k, v))
    t0 = time.time()
    out = jax.block_until_ready(compiled(q, k, v))
    dt = time.time() - t0
    return {
        "path": "pallas_flash" if use_flash else "einsum",
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", -1)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", -1)),
        "wall_s": round(dt, 3),
        "out_norm": float(jnp.linalg.norm(out.astype(jnp.float32))),
    }


def main():
    b, s, h, kvh, d = 1, 32768, 4, 4, 64
    rows = [measure(False, b, s, h, kvh, d), measure(True, b, s, h, kvh, d)]
    flash = next(r for r in rows if r["path"] == "pallas_flash")
    einsum = next(r for r in rows if r["path"] == "einsum")
    # identical math, two implementations
    rel = abs(flash["out_norm"] - einsum["out_norm"]) / max(einsum["out_norm"], 1e-9)
    print(json.dumps({
        "metric": "ring_attention_32k",
        "tokens": s, "ranks": 8, "heads": h, "head_dim": d,
        "rows": rows,
        "temp_ratio_einsum_over_flash": round(
            einsum["temp_bytes"] / max(flash["temp_bytes"], 1), 2),
        "out_norm_rel_delta": rel,
        "note": "virtual CPU mesh (interpret-mode kernel): temp_bytes is "
                "the design metric — fp32 score chunks vs VMEM-tile scores; "
                "on-chip kernel compile+parity is covered by the real-TPU "
                "drive (single-rank ring, fwd+bwd through Mosaic)",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
