from setuptools import find_packages, setup

setup(
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native training & inference framework (DeepSpeed capability set on JAX/XLA/Pallas)",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax", "numpy", "pydantic"],
    entry_points={"console_scripts": [
        "dstpu=deepspeed_tpu.launcher.runner:main",
        "dstpu_report=deepspeed_tpu.env_report:cli_main",
    ]},
)
