#!/usr/bin/env python
"""Headline benchmark: ZeRO training throughput on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: model-FLOPs utilization (MFU)-derived tokens/sec/chip for a
GPT-2-style causal LM trained with deepspeed_tpu (ZeRO + fused step),
scaled against the reference's A100 per-device baseline.

vs_baseline: measured MFU / 0.40 — DeepSpeed's published large-model
training runs sustain roughly 40% MFU on A100 (e.g. Ulysses blog: >54% of
peak on its best config, typical ZeRO-3 runs lower); beating 1.0 means the
TPU step loop is better at feeding its matrix units than the reference's.

The `extra` payload carries the evidence for the MFU story the headline
number rests on:
  - `matmul_ceiling_mfu`: raw bf16 matmul efficiency at the model's own
    matrix widths (the practical chip ceiling for this workload — if model
    MFU ~= this, the step loop is compute-bound, not framework-bound).
  - `matmul_peak_mfu`: the same measurement at large square shapes (what
    the chip can do when shapes are ideal).
  - `rows`: the gpt2-small batch sweep (8/16/32) and a gpt2-medium row,
    including failed configs recorded with their error instead of hidden.

Methodology notes (hard-won on the tunneled single-chip platform):
- `jax.block_until_ready` is NOT a reliable sync there; every timing syncs
  by `jax.device_get` of a value data-dependent on the step.
- The first few executions of a fresh executable pay tunnel/load overhead,
  so warmup runs several steps before the timed window.
- Batches are staged on device before the timed loop (input pipeline is
  benchmarked by the data-pipeline suite, not here).
- Per-dispatch tunnel latency is ~3-6 ms: matmul timing loops live inside
  one `lax.scan` dispatch, never chained small jit calls.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PEAK_TFLOPS = {"tpu": 197.0}  # v5e bf16


def _timed_matmul_chain(m, widths, iters=10, unroll=10):
    """Sustained bf16 TFLOP/s for a DEPENDENT matmul chain, one dispatch.

    ``widths`` is a cycle of inner dims (first == last): each step runs
    x @ W_0 @ W_1 ... with x genuinely carried between steps, so XLA can
    neither hoist the matmuls out of the loop nor overlap iterations —
    this measures back-to-back dependent GEMM throughput. ``unroll`` chains
    repeat inside the scan body (measured: scan-per-iteration overhead on
    the tunneled chip dwarfs sub-ms matmuls; 10x10 beats 100x1 by 5x at
    768-wide shapes). A down-scale between steps keeps values finite
    (elementwise, fused, negligible next to the GEMMs).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    ws = [jnp.full((widths[i], widths[i + 1]), 0.01, jnp.bfloat16)
          for i in range(len(widths) - 1)]
    x0 = jnp.ones((m, widths[0]), jnp.bfloat16)

    @jax.jit
    def run(x, ws):
        def body(x, _):
            for _ in range(unroll):
                for w in ws:
                    x = x @ w
                x = (x * 1e-2).astype(jnp.bfloat16)
            return x, ()

        x, _ = lax.scan(body, x, None, length=iters)
        # scalar sync value: device_get of the full matrix would time the
        # host transfer (hundreds of ms through the tunnel), not the MXU
        return jnp.sum(x.astype(jnp.float32))

    run(x0, ws)  # compile+warm
    _ = jax.device_get(run(x0, ws))
    # tunnel timing noise is +/-40% at ms scale: best-of-3 windows
    dt = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        _ = jax.device_get(run(x0, ws))
        dt = min(dt, time.perf_counter() - t0)
    flops = 2 * m * sum(widths[i] * widths[i + 1]
                        for i in range(len(widths) - 1)) * iters * unroll
    return flops / dt / 1e12


def measure_matmul_ceiling(platform):
    """Raw bf16 matmul efficiency: at model-relevant widths and at ideal shapes.

    gpt2-small's biggest GEMMs are 768-wide (QKV/proj: 768x768; MLP:
    768x3072x768); gpt2-medium's are 1024/4096. The ceiling that bounds the
    model is dependent-GEMM efficiency at THOSE widths, not at 8192^2.
    """
    peak = PEAK_TFLOPS.get(platform)
    if peak is None:
        return None  # CPU dev run: not meaningful
    # 8192 rows = the bench's batch*seq token count. The MLP chain
    # (768x3072x768) is the model's dominant GEMM pattern: its efficiency
    # is the practical per-matmul ceiling at gpt2-small's widths. (The
    # model itself can exceed it via intra-layer independent matmuls —
    # q/k/v — overlapping; model MFU >= this chain means the step loop
    # adds no framework overhead on top of the chip's shape limits.)
    # MEDIAN of 3 full measurements: single windows through the tunnel
    # spread +/-25% even with best-of-3 timing inside (round 3 recorded a
    # noise-deflated 0.351 ceiling that a healthy chip re-measures at
    # ~0.39-0.44), and the ceiling anchors the headline's framing.
    import statistics
    mlp_tf = statistics.median(
        _timed_matmul_chain(8192, (768, 3072, 768)) for _ in range(3))
    proj_tf = _timed_matmul_chain(8192, (768, 768))
    ideal_tf = _timed_matmul_chain(8192, (8192, 8192), iters=2, unroll=5)
    return {
        "matmul_ceiling_mfu": round(mlp_tf / peak, 4),
        "matmul_proj_mfu": round(proj_tf / peak, 4),
        "matmul_peak_mfu": round(ideal_tf / peak, 4),
    }


def run_train_config(name, batch, seq, dtype, zero_stage, warmup, steps, gas=1):
    """Train one config; return a result row. Failures become rows too.
    ``batch`` is the GLOBAL per-chip batch; ``gas`` splits it into
    microbatches (batch must divide by gas)."""
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, get_config

    n_chips = len(jax.devices())
    platform = jax.default_backend()
    row = {"model": name, "batch": batch, "seq": seq}
    if gas > 1:
        row["gas"] = gas
    try:
        cfg = get_config(name, max_seq_len=seq) if platform == "tpu" \
            else get_config(name)
        # remat="dots" (save matmul outputs, recompute elementwise) is a
        # measured ~7% throughput WIN on this chip even where memory fits:
        # the saved-activation traffic between forward and backward is the
        # bottleneck, not the recompute FLOPs (round-5 sweep: 92.0 vs
        # 101.5 ms at micro-8; it also recovers most of the batch-16 dip —
        # 188.9 vs 207.3 ms — pinning that dip on activation memory
        # pressure, and lets batch-32 gas=1 compile at all)
        model = build_model(cfg.replace(dtype=dtype, remat="dots"))
        config = {
            "train_batch_size": batch * max(1, n_chips),
            "train_micro_batch_size_per_gpu": batch // gas,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-4, "weight_decay": 0.01}},
            "zero_optimization": {"stage": zero_stage},
            "bf16": {"enabled": dtype == "bfloat16"},
            "steps_per_print": 10 ** 9,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config)
        rng = np.random.default_rng(0)

        def make_batch():
            ids = rng.integers(0, cfg.vocab_size,
                               (config["train_batch_size"], seq), dtype=np.int32)
            return {"input_ids": ids, "labels": ids}

        batches = [engine.stage_batch(make_batch()) for _ in range(4)]
        for i in range(warmup):
            loss = engine.train_batch(batches[i % len(batches)])
        _ = jax.device_get(loss)

        t0 = time.perf_counter()
        for i in range(steps):
            loss = engine.train_batch(batches[i % len(batches)])
        final_loss = float(jax.device_get(loss))
        dt = time.perf_counter() - t0

        tokens = steps * config["train_batch_size"] * seq
        tps_chip = tokens / dt / max(1, n_chips)
        n_params = model.param_count()
        achieved_tflops = tps_chip * 6 * n_params / 1e12
        peak = PEAK_TFLOPS.get(platform, 0.1)
        row.update({
            "tokens_per_sec_chip": round(tps_chip, 1),
            "params_m": round(n_params / 1e6, 1),
            "achieved_tflops_per_chip": round(achieved_tflops, 2),
            "mfu": round(achieved_tflops / peak, 4),
            "step_ms": round(dt / steps * 1e3, 1),
            "final_loss": round(final_loss, 4),
            "zero_stage": zero_stage,
        })
    except Exception as e:  # OOM / compile failure is a result, not a crash
        msg = str(e)
        row["status"] = "failed"
        row["error_type"] = type(e).__name__
        # classify the known platform walls instead of dumping tracebacks
        if "remote_compile" in msg and "500" in msg:
            row["skip_reason"] = (
                "tunnel compile-helper exhausts its memory on this config "
                "(HTTP 500) — a platform wall, not a framework limit; the "
                "same model compiles at smaller batch (see adjacent rows)")
        elif "RESOURCE_EXHAUSTED" in msg or "OOM" in msg.upper():
            row["skip_reason"] = "out of device memory at this batch"
        else:
            row["error"] = msg[:200]
    return row


def main():
    import jax

    n_chips = len(jax.devices())
    platform = jax.default_backend()

    if platform == "tpu":
        # micro-batch 8 is this chip's throughput sweet spot; with
        # remat="dots" (see run_train_config) the headline rides gas to a
        # 128 global batch of micro-8 steps (round-5 sweep: gas-16 edges
        # gas-8, 99.4k vs 98.6k tok/s). The batch-16 single-step dip is
        # EXPLAINED and mostly recovered by remat (activation memory
        # pressure: 16x1024 saved activations thrash HBM; dots-remat cuts
        # the traffic — 86.7k vs 79.1k tok/s — micro-8 still wins), and
        # batch-32 gas=1 now compiles under remat instead of hitting the
        # compile-helper wall.
        headline_cfg = ("gpt2-small", 128, 1024, "bfloat16", 1, 3, 10, 16)
        sweep = [("gpt2-small", 8, 1024, "bfloat16", 1, 3, 10),
                 ("gpt2-small", 16, 1024, "bfloat16", 1, 3, 10),
                 ("gpt2-small", 16, 1024, "bfloat16", 1, 3, 10, 2),
                 ("gpt2-small", 32, 1024, "bfloat16", 1, 3, 10),
                 ("gpt2-small", 32, 1024, "bfloat16", 1, 3, 10, 4),
                 ("gpt2-medium", 4, 1024, "bfloat16", 1, 3, 10)]
    else:
        headline_cfg = ("tiny-gpt2", 8, 128, "float32", 1, 2, 5)
        sweep = []

    try:
        ceiling = measure_matmul_ceiling(platform)
    except Exception as e:  # a ceiling failure must not kill the bench
        ceiling = {"matmul_ceiling_error": f"{type(e).__name__}: {str(e)[:200]}"}
    headline = run_train_config(*headline_cfg)

    if "error" in headline or headline.get("status") == "failed":
        # don't burn chip time on the sweep when the headline config failed
        print(json.dumps({"metric": "bench-error", "value": 0, "unit": "",
                          "vs_baseline": 0,
                          "extra": {**headline, **(ceiling or {})}}))
        return
    rows = [run_train_config(*s) for s in sweep]

    mfu = headline["mfu"]
    extra = {
        "platform": platform,
        "chips": n_chips,
        **{k: headline[k] for k in ("params_m", "achieved_tflops_per_chip",
                                    "mfu", "step_ms", "final_loss")},
    }
    if ceiling:
        extra.update(ceiling)
        if ceiling.get("matmul_ceiling_mfu"):
            # How much of the chip's practical (model-width) matmul ceiling
            # the full training step achieves — framework efficiency.
            extra["mfu_vs_matmul_ceiling"] = round(
                mfu / ceiling["matmul_ceiling_mfu"], 3)
            extra["residual_accounting"] = (
                "the gap to the pure-matmul ceiling is the non-MXU work a "
                "transformer step cannot avoid on this part: flash "
                "attention's VPU softmax at seq 1024, layernorms/residuals, "
                "the chunked vocab cross-entropy, and the fused-Adam "
                "update. Round-5 sweep results per lever: remat=dots +7% "
                "(adopted; saved-activation HBM traffic was the binding "
                "constraint), flash blocks 512x512 already optimal (256/"
                "1024 variants within noise), CE chunking flat across "
                "4/8/16/off, gas plateau at 16-32, micro-batch 8 optimal "
                "(16 is activation-pressure-bound even under remat). No "
                "remaining measured lever exceeds the +-2% run noise.")
    if rows:
        extra["rows"] = rows

    result = {
        "metric": "gpt2s-zero1-train-tokens-per-sec-per-chip",
        "value": headline["tokens_per_sec_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "extra": extra,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
