#!/usr/bin/env python
"""Headline benchmark: ZeRO training throughput on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: model-FLOPs utilization (MFU)-derived tokens/sec/chip for a
GPT-2-style causal LM trained with deepspeed_tpu (ZeRO + fused step),
scaled against the reference's A100 per-device baseline.

vs_baseline: measured MFU / 0.40 — DeepSpeed's published large-model
training runs sustain roughly 40% MFU on A100 (e.g. Ulysses blog: >54% of
peak on its best config, typical ZeRO-3 runs lower); beating 1.0 means the
TPU step loop is better at feeding its matrix units than the reference's.

Methodology notes (hard-won on the tunneled single-chip platform):
- `jax.block_until_ready` is NOT a reliable sync there; every timing syncs
  by `jax.device_get` of a value data-dependent on the step.
- The first few executions of a fresh executable pay tunnel/load overhead,
  so warmup runs several steps before the timed window.
- Batches are staged on device before the timed loop (input pipeline is
  benchmarked by the data-pipeline suite, not here).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, get_config

    n_chips = len(jax.devices())
    platform = jax.default_backend()

    # Size the model to the platform: a real GPT-2-small-class model on TPU,
    # a tiny one on CPU fallback so the bench always completes.
    if platform == "tpu":
        cfg = get_config("gpt2-small", max_seq_len=1024)
        batch, seq, warmup, steps = 8, 1024, 5, 30
        dtype = "bfloat16"
    else:
        cfg = get_config("tiny-gpt2")
        batch, seq, warmup, steps = 8, 128, 2, 5
        dtype = "float32"

    model = build_model(cfg.replace(dtype=dtype))
    config = {
        "train_batch_size": batch * max(1, n_chips),
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "zero_optimization": {"stage": 2 if n_chips > 1 else 1},
        "bf16": {"enabled": dtype == "bfloat16"},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rng = np.random.default_rng(0)

    def make_batch():
        ids = rng.integers(0, cfg.vocab_size, (config["train_batch_size"], seq),
                           dtype=np.int32)
        return {"input_ids": ids, "labels": ids}

    # Pre-stage a few distinct batches on device (sharded the way train_batch
    # expects them); the timed loop cycles through them.
    batches = [engine.stage_batch(make_batch()) for _ in range(4)]

    for i in range(warmup):
        loss = engine.train_batch(batches[i % len(batches)])
    _ = jax.device_get(loss)  # full sync: loss depends on the whole step chain

    t0 = time.perf_counter()
    for i in range(steps):
        loss = engine.train_batch(batches[i % len(batches)])
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    tokens = steps * config["train_batch_size"] * seq
    tokens_per_sec = tokens / dt
    tokens_per_sec_chip = tokens_per_sec / max(1, n_chips)

    # model FLOPs: 6 * params * tokens (fwd+bwd)
    n_params = model.param_count()
    flops_per_token = 6 * n_params
    achieved_tflops = tokens_per_sec_chip * flops_per_token / 1e12
    # v5e peak bf16: 197 TFLOP/s; CPU: report vs nominal 0.1 TF to keep the
    # line well-formed in dev environments.
    peak = 197.0 if platform == "tpu" else 0.1
    mfu = achieved_tflops / peak

    result = {
        "metric": f"gpt2s-zero{config['zero_optimization']['stage']}-train-tokens-per-sec-per-chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "extra": {
            "platform": platform,
            "chips": n_chips,
            "params_m": round(n_params / 1e6, 1),
            "achieved_tflops_per_chip": round(achieved_tflops, 2),
            "mfu": round(mfu, 4),
            "step_ms": round(dt / steps * 1e3, 1),
            "final_loss": round(final_loss, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
