"""ZeRO-Inference weight-only quantization.

Analog of ``deepspeed/inference/quantization/layers.py:47,75``
(QuantizedLinear / QuantizedEmbedding): weights stored INT8/INT4 with
per-group scales, dequantized on the fly inside the matmul — model memory
drops 4-8x so models larger than HBM can serve (with the NVMe/host tier
holding the quantized weights).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...ops.pallas.quantizer import (dequantize_int4, dequantize_int8,
                                     quantize_int4, quantize_int8)


class QuantizedParameter:
    """A weight held in quantized form; dequantizes at use."""

    def __init__(self, q, scales, orig_shape, bits: int, group_size: int,
                 dtype=jnp.bfloat16):
        self.q = q
        self.scales = scales
        self.orig_shape = orig_shape
        self.bits = bits
        self.group_size = group_size
        self.dtype = dtype

    @classmethod
    def quantize(cls, w, bits: int = 8, group_size: int = 256):
        pad = (-w.size) % group_size
        flat = w.reshape(-1)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), w.dtype)])
        if bits == 8:
            q, s = quantize_int8(flat, group_size)
            return cls(q, s, w.shape, 8, group_size, w.dtype)
        if bits == 4:
            q, s, _ = quantize_int4(flat, group_size)
            return cls(q, s, w.shape, 4, group_size, w.dtype)
        raise ValueError(f"bits must be 4 or 8, got {bits}")

    def dequantized(self):
        import math
        n = math.prod(self.orig_shape)
        if self.bits == 8:
            full = dequantize_int8(self.q, self.scales, self.dtype, self.group_size)
        else:
            padded = ((n + self.group_size - 1) // self.group_size) * self.group_size
            full = dequantize_int4(self.q, self.scales, (padded,), self.dtype,
                                   self.group_size).reshape(-1)
        return full.reshape(-1)[:n].reshape(self.orig_shape)

    @property
    def nbytes(self):
        return self.q.size * (1 if self.bits == 8 else 1) + self.scales.size * 4


class QuantizedLinear:
    """y = x @ dequant(Wq) (+ b). Reference ``layers.py:47``."""

    def __init__(self, weight, bias=None, bits: int = 8, group_size: int = 256):
        self.wq = QuantizedParameter.quantize(weight, bits, group_size)
        self.bias = bias

    def __call__(self, x):
        w = self.wq.dequantized().astype(x.dtype)
        y = x @ w
        if self.bias is not None:
            y = y + self.bias.astype(x.dtype)
        return y


class QuantizedEmbedding:
    """Embedding lookup over a quantized table. Reference ``layers.py:75``."""

    def __init__(self, table, bits: int = 8, group_size: int = 256):
        self.wq = QuantizedParameter.quantize(table, bits, group_size)

    def __call__(self, ids):
        return self.wq.dequantized()[ids]


def quantize_model_params(params, bits: int = 8, group_size: int = 256,
                          min_size: int = 4096):
    """Quantize every large weight in a param pytree → pytree of
    QuantizedParameter (small tensors stay dense)."""
    def one(x):
        if x.size >= min_size and x.ndim >= 2:
            return QuantizedParameter.quantize(x, bits, group_size)
        return x
    return jax.tree.map(one, params)


def dequantize_model_params(qparams):
    def one(x):
        return x.dequantized() if isinstance(x, QuantizedParameter) else x
    return jax.tree.map(one, qparams,
                        is_leaf=lambda x: isinstance(x, QuantizedParameter))
