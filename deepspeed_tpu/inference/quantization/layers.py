"""ZeRO-Inference weight-only quantization.

Analog of ``deepspeed/inference/quantization/layers.py:47,75``
(QuantizedLinear / QuantizedEmbedding): weights stored INT8/INT4 with
per-group scales, dequantized on the fly inside the matmul — model memory
drops 4-8x so models larger than HBM can serve (with the NVMe/host tier
holding the quantized weights).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...ops.pallas.quantizer import (dequantize_int4, dequantize_int8,
                                     quantize_int4, quantize_int8)


class QuantizedParameter:
    """A weight held in quantized form; dequantizes at use."""

    def __init__(self, q, scales, orig_shape, bits: int, group_size: int,
                 dtype=jnp.bfloat16):
        self.q = q
        self.scales = scales
        self.orig_shape = orig_shape
        self.bits = bits
        self.group_size = group_size
        self.dtype = dtype

    @classmethod
    def quantize(cls, w, bits: int = 8, group_size: int = 256):
        pad = (-w.size) % group_size
        flat = w.reshape(-1)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), w.dtype)])
        if bits == 8:
            q, s = quantize_int8(flat, group_size)
            return cls(q, s, w.shape, 8, group_size, w.dtype)
        if bits == 6:
            q, s = _quantize_fp6(flat, group_size)
            return cls(q, s, w.shape, 6, group_size, w.dtype)
        if bits == 4:
            q, s, _ = quantize_int4(flat, group_size)
            return cls(q, s, w.shape, 4, group_size, w.dtype)
        raise ValueError(f"bits must be 4, 6 or 8, got {bits}")

    def dequantized(self):
        import math
        n = math.prod(self.orig_shape)
        if self.bits == 8:
            full = dequantize_int8(self.q, self.scales, self.dtype, self.group_size)
        elif self.bits == 6:
            padded = ((n + self.group_size - 1) // self.group_size) * self.group_size
            full = _dequantize_fp6(self.q, self.scales, padded, self.dtype,
                                   self.group_size)
        else:
            padded = ((n + self.group_size - 1) // self.group_size) * self.group_size
            full = dequantize_int4(self.q, self.scales, (padded,), self.dtype,
                                   self.group_size).reshape(-1)
        return full.reshape(-1)[:n].reshape(self.orig_shape)

    @property
    def nbytes(self):
        return self.q.size + self.scales.size * 4


# ---- FP6 (e3m2) weight-only format ---------------------------------------
# Analog of the reference's FP6 mixed-input GEMM weights
# (inference/v2/kernels/core_ops/cuda_linear/linear_kernels_cuda.cu): sign +
# 3-bit exponent (bias 3) + 2-bit mantissa, per-group absmax scaling to the
# format's max magnitude (28.0); four 6-bit codes pack into three bytes.
# Encoding is nearest-neighbor over the 64-entry codebook (weights quantize
# once at load; decode is a vectorized table lookup).

def _fp6_codebook():
    vals = []
    for code in range(64):
        s = -1.0 if code & 0x20 else 1.0
        e = (code >> 2) & 0x7
        m = code & 0x3
        if e == 0:                       # subnormal: 2^-2 * m/4
            v = 0.25 * (m / 4.0)
        else:
            v = (2.0 ** (e - 3)) * (1.0 + m / 4.0)
        vals.append(s * v)
    return jnp.asarray(vals, jnp.float32)          # max magnitude 28.0


_FP6_MAX = 28.0


def _quantize_fp6(flat, group_size):
    # Codes 0..31 are the non-negative codebook values in ascending order
    # (monotone in (e, m)), so round-to-nearest is a searchsorted against
    # the midpoints — O(n log 32), no (n, 64) distance tensor (a 64x fp32
    # blow-up that would OOM on multi-GB weights at load time).
    book = _fp6_codebook()
    pos = book[:32]
    mids = (pos[:-1] + pos[1:]) * 0.5
    g = flat.reshape(-1, group_size).astype(jnp.float32)
    scales = jnp.max(jnp.abs(g), axis=1, keepdims=True) / _FP6_MAX
    scales = jnp.maximum(scales, 1e-12)
    x = (g / scales).reshape(-1)
    mag = jnp.searchsorted(mids, jnp.abs(x)).astype(jnp.uint8)
    codes = jnp.where(x < 0, mag | 0x20, mag).astype(jnp.uint8)
    pad4 = (-codes.size) % 4                               # pack needs 4 | n
    if pad4:
        codes = jnp.concatenate([codes, jnp.zeros((pad4,), codes.dtype)])
    c = codes.reshape(-1, 4).astype(jnp.uint32)            # pack 4 → 3 bytes
    word = (c[:, 0] | (c[:, 1] << 6) | (c[:, 2] << 12) | (c[:, 3] << 18))
    packed = jnp.stack([word & 0xFF, (word >> 8) & 0xFF, (word >> 16) & 0xFF],
                       axis=1).astype(jnp.uint8).reshape(-1)
    return packed, scales.reshape(-1)


def _dequantize_fp6(packed, scales, n_padded, dtype, group_size):
    book = _fp6_codebook()
    b = packed.reshape(-1, 3).astype(jnp.uint32)
    word = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
    codes = jnp.stack([word & 0x3F, (word >> 6) & 0x3F, (word >> 12) & 0x3F,
                       (word >> 18) & 0x3F], axis=1).reshape(-1)
    vals = book[codes[:n_padded]].reshape(-1, group_size)  # drop pack padding
    return (vals * scales[:, None]).astype(dtype).reshape(-1)[:n_padded]


class QuantizedLinear:
    """y = x @ dequant(Wq) (+ b). Reference ``layers.py:47``.

    2-D weights with plane-aligned K use the FUSED mixed-input Pallas GEMM
    (``ops/pallas/woq_matmul.py``): the packed weight dequantizes tile-by-
    tile in VMEM, never materializing the bf16 weight in HBM — the analog
    of the reference's FP6/INT4 ``cuda_linear`` kernels. Other shapes fall
    back to dequantize-then-matmul.
    """

    def __init__(self, weight, bias=None, bits: int = 8, group_size: int = 256):
        from ...ops.pallas.woq_matmul import quantize_woq
        self.bias = bias
        self.fused = None
        self.wq = None
        self._wdtype = weight.dtype
        planes = {8: 1, 6: 4, 4: 2}[bits]
        # honor the caller's group when the fused layout supports it (K
        # groups must tile the plane layout); otherwise try the kernel's
        # native 128 before falling back to the flat dequant path
        for fg in (group_size, 128):
            if weight.ndim == 2 and weight.shape[0] % (fg * planes) == 0:
                self.fused = quantize_woq(weight, bits, fg)
                break
        if self.fused is None:
            self.wq = QuantizedParameter.quantize(weight, bits, group_size)

    def __call__(self, x):
        if self.fused is not None:
            from ...ops.pallas.woq_matmul import woq_matmul
            lead = x.shape[:-1]
            y = woq_matmul(x.reshape(-1, x.shape[-1]), self.fused)
            y = y.reshape(*lead, y.shape[-1])
        else:
            w = self.wq.dequantized().astype(x.dtype)
            y = x @ w
        if self.bias is not None:
            y = y + self.bias.astype(x.dtype)
        return y

    def dequantized(self):
        if self.fused is not None:
            from ...ops.pallas.woq_matmul import woq_dequantize
            return woq_dequantize(self.fused, self._wdtype)
        return self.wq.dequantized()

    @property
    def nbytes(self):
        if self.fused is not None:
            return self.fused["q"].size + self.fused["scales"].size * 4
        return self.wq.nbytes


class QuantizedEmbedding:
    """Embedding lookup over a quantized table. Reference ``layers.py:75``."""

    def __init__(self, table, bits: int = 8, group_size: int = 256):
        self.wq = QuantizedParameter.quantize(table, bits, group_size)

    def __call__(self, ids):
        return self.wq.dequantized()[ids]


def quantize_model_params(params, bits: int = 8, group_size: int = 256,
                          min_size: int = 4096):
    """Quantize every large weight in a param pytree → pytree of
    QuantizedParameter (small tensors stay dense)."""
    def one(x):
        if x.size >= min_size and x.ndim >= 2:
            return QuantizedParameter.quantize(x, bits, group_size)
        return x
    return jax.tree.map(one, params)


def dequantize_model_params(qparams):
    def one(x):
        return x.dequantized() if isinstance(x, QuantizedParameter) else x
    return jax.tree.map(one, qparams,
                        is_leaf=lambda x: isinstance(x, QuantizedParameter))
