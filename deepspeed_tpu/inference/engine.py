"""Inference engine (v1 analog).

Analog of ``deepspeed/inference/engine.py:41`` (InferenceEngine). The
reference injects fused CUDA kernels into a torch module and slices weights
for TP (``_apply_injection_policy:411``). Here "injection" is conversion to
the native CausalLM (``module_inject``) whose params carry TP shardings over
the ``tensor`` mesh axis; the decode step is one compiled scan (the
CUDA-graph capture/replay knobs become XLA compilation, which is always on).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..models.transformer import CausalLM
from ..parallel import sharding as shd
from ..utils import groups
from ..utils.logging import log_dist, logger
from .config import DeepSpeedInferenceConfig
from .sampling import sample_logits


class InferenceEngine:
    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params=None):
        self._config = config or DeepSpeedInferenceConfig()
        if not dist.is_initialized():
            dist.init_distributed(verbose=False)
        self.mesh = groups.get_mesh()

        from ..module_inject import as_inference_model
        self.model, converted_params = as_inference_model(model, self._config)
        if params is not None:
            converted_params = params

        dt = self._config.dtype.replace("torch.", "").replace("half", "float16")
        if self.model.cfg.dtype != dt and dt in ("float16", "bfloat16", "float32"):
            self.model.cfg = self.model.cfg.replace(dtype=dt)

        # TP/ZeRO-inference shardings from the same logical-axis rules as training
        abstract = self.model.abstract_params()
        logical = self.model.logical_axes()
        self.param_shardings = shd.tree_shardings(abstract, logical, shd.BASE_RULES, self.mesh)

        if converted_params is None:
            with self.mesh:
                self.module_params = jax.jit(self.model.init,
                                             out_shardings=self.param_shardings)(
                    jax.random.PRNGKey(0))
        else:
            self.module_params = jax.device_put(converted_params, self.param_shardings)

        self._decode_fn = None
        self._cache = None
        self._cache_max = 0
        log_dist(f"InferenceEngine ready: params={self.model.param_count() / 1e6:.1f}M "
                 f"tp={self.mesh.shape['tensor']}", ranks=[0])

    # -- reference-parity surface --

    def forward(self, input_ids, *args, **kwargs):
        return jax.jit(self.model.apply)(self.module_params, jnp.asarray(input_ids))

    __call__ = forward

    def module_state_dict(self):
        return jax.device_get(self.module_params)

    def _get_decode_fn(self):
        if self._decode_fn is None:
            @jax.jit
            def decode(params, ids, cache, cache_len):
                return self.model.apply_decode(params, ids, cache, cache_len)
            self._decode_fn = decode
        return self._decode_fn

    def generate(self, input_ids, max_new_tokens: int = 32, *, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, eos_token_id: Optional[int] = None,
                 seed: int = 0, return_dict: bool = False, **kwargs):
        """Batch generation with a compiled prefill + compiled decode loop.

        input_ids: (B, S_prompt) — right-aligned prompts (no padding support
        in v1; use the ragged v2 engine for mixed lengths).
        """
        if not self.model.cfg.causal or self.model.cfg.mlm_head:
            raise NotImplementedError(
                "generate() is autoregressive; BERT-style encoders are "
                "served with forward() (fill-mask / embedding workloads)")
        ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        b, s_prompt = ids.shape
        max_len = s_prompt + max_new_tokens
        cache = self.model.init_cache(b, max_len)
        decode = self._get_decode_fn()

        # prefill
        cache_len = jnp.zeros((b,), jnp.int32)
        logits, cache = decode(self.module_params, ids, cache, cache_len)
        cache_len = cache_len + s_prompt
        rng = jax.random.PRNGKey(seed)
        rng, sub = jax.random.split(rng)
        next_tok = sample_logits(logits[:, -1].astype(jnp.float32), sub,
                                 temperature=temperature, top_k=top_k, top_p=top_p,
                                 greedy=temperature == 0.0)

        @jax.jit
        def step(carry, _):
            cache, cache_len, tok, rng, done = carry
            logits, cache = self.model.apply_decode(self.module_params, tok[:, None],
                                                    cache, cache_len)
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(logits[:, -1].astype(jnp.float32), sub,
                                temperature=temperature, top_k=top_k, top_p=top_p,
                                greedy=temperature == 0.0)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (cache, cache_len + 1, nxt, rng, done), tok

        done0 = jnp.zeros((b,), bool)
        (_, _, last, _, _), toks = jax.lax.scan(
            step, (cache, cache_len, next_tok, rng, done0), None, length=max_new_tokens - 1)
        out_new = jnp.concatenate([toks.T, last[:, None]], axis=1)  # (B, max_new)
        full = jnp.concatenate([ids, out_new], axis=1)
        if return_dict:
            return {"sequences": full, "new_tokens": out_new}
        return full

    @property
    def config(self):
        return self._config
