"""Inference configuration.

Analog of ``deepspeed/inference/config.py`` (DeepSpeedInferenceConfig).
Field names kept so reference-style ``init_inference(..., dtype=...,
tensor_parallel={"tp_size": N})`` calls parse unchanged.
"""

from typing import Any, Dict, Optional, Union

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    qkv: Optional[Any] = None
    bits: int = 8
    group_size: int = 64


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    replace_with_kernel_inject: bool = False
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = DeepSpeedTPConfig()
    enable_cuda_graph: bool = False      # parity knob; XLA always compiles
    zero: Dict[str, Any] = {}
    triangular_masking: bool = True
    moe: Union[bool, Dict[str, Any]] = False
    quant: QuantizationConfig = QuantizationConfig()
    checkpoint: Optional[Union[str, Dict]] = None
    base_dir: str = ""
    max_tokens: int = Field(4096, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_out_tokens")
    transposed_mode: bool = False
    mp_size: int = 1                     # legacy alias for tp_size
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = None
    injection_policy_tuple: Optional[tuple] = None
    config: Optional[Dict] = None
    save_mp_checkpoint_path: Optional[str] = None
    checkpoint_config: Dict[str, Any] = Field({}, alias="ds_config")

    @property
    def tp_size_effective(self):
        return max(self.tensor_parallel.tp_size, self.mp_size)
