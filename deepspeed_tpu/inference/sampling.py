"""Token sampling strategies for generation (greedy, temperature, top-k,
top-p). All pure functions usable inside jit/scan."""

import jax
import jax.numpy as jnp


def sample_logits_per_row(logits, rng, temps):
    """Row-wise sampling for the device-resident serving frame: ``temps``
    (B,) float32 rides in the frame carry, so rows with different sampling
    settings share one batch. Rows with temp <= 0 take argmax (bit-identical
    to the greedy host path); the rest sample at their own temperature.
    logits: (B, V) → token ids (B,) int32."""
    greedy_toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy_toks, sampled)


def speculative_verify_per_row(target_logits, draft_logits, draft_toks, temps,
                               rng=None):
    """Per-row draft verification for the speculative serving frame: decides
    how many drafted tokens survive and what the replacement/bonus token is,
    entirely in-graph (acceptance never syncs the host).

    target_logits: (B, G+1, V) the target model's logits at the G+1 verified
    positions (position 0 is the committed last token; positions 1..G are the
    drafted tokens). draft_logits: (B, G, V) the draft's proposal logits.
    draft_toks: (B, G) the proposed tokens. temps: (B,) per-row temperatures.

    Returns (n_accept (B,) int32 in [0, G], replacement (B,) int32): the
    count of leading accepted drafts and the token to emit right after them —
    the target's continuation on full acceptance, its correction at the first
    rejected position otherwise.

    Rows with temp <= 0 use exact greedy token-match (accept while the draft
    token equals the target argmax), which makes the speculative output
    bit-identical to non-speculative greedy decoding. Rows with temp > 0 use
    Leviathan-style rejection sampling: accept q_j with probability
    min(1, p_t(q_j) / p_d(q_j)); on the first rejection the replacement is
    drawn from the normalized residual max(p_t - p_d, 0), which preserves the
    target distribution exactly. ``rng=None`` means all rows are greedy and
    no randomness is consumed."""
    g = draft_toks.shape[1]
    tgt_greedy = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # (B, G+1)
    match = (draft_toks == tgt_greedy[:, :g]).astype(jnp.int32)
    # leading-ones count: cumprod zeroes everything after the first mismatch
    greedy_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1).astype(jnp.int32)
    greedy_repl = jnp.take_along_axis(tgt_greedy, greedy_acc[:, None],
                                      axis=1)[:, 0]
    if rng is None:
        return greedy_acc, greedy_repl
    r_u, r_res = jax.random.split(rng)
    t = jnp.maximum(temps, 1e-6)[:, None, None]
    p_t = jax.nn.softmax(target_logits.astype(jnp.float32) / t, axis=-1)
    p_d = jax.nn.softmax(draft_logits.astype(jnp.float32) / t, axis=-1)
    pt_q = jnp.take_along_axis(p_t[:, :g], draft_toks[..., None], -1)[..., 0]
    pd_q = jnp.take_along_axis(p_d, draft_toks[..., None], -1)[..., 0]
    u = jax.random.uniform(r_u, draft_toks.shape)
    accept = (u * pd_q <= pt_q).astype(jnp.int32)   # accept w.p. min(1, pt/pd)
    samp_acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1).astype(jnp.int32)
    n_acc = jnp.where(temps <= 0.0, greedy_acc, samp_acc)
    # residual at the first rejected position; the bonus position (n_acc == G)
    # has no draft distribution, so pad p_d with zeros there and the residual
    # degenerates to p_t itself
    pd_pad = jnp.concatenate([p_d, jnp.zeros_like(p_d[:, :1])], axis=1)
    idx = n_acc[:, None, None]
    p_t_at = jnp.take_along_axis(p_t, idx, axis=1)[:, 0]        # (B, V)
    p_d_at = jnp.take_along_axis(pd_pad, idx, axis=1)[:, 0]
    res = jnp.maximum(p_t_at - p_d_at, 0.0)
    # p_d == p_t exactly (self-draft) leaves a zero residual: fall back to p_t
    res = jnp.where(jnp.sum(res, axis=-1, keepdims=True) > 0.0, res, p_t_at)
    sampled_repl = jax.random.categorical(
        r_res, jnp.log(res + 1e-30), axis=-1).astype(jnp.int32)
    repl = jnp.where(temps <= 0.0, greedy_repl, sampled_repl)
    return n_acc, repl


def sample_logits(logits, rng, *, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0, greedy: bool = False):
    """logits: (B, V) → token ids (B,) int32."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
