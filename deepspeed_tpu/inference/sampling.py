"""Token sampling strategies for generation (greedy, temperature, top-k,
top-p). All pure functions usable inside jit/scan."""

import jax
import jax.numpy as jnp


def sample_logits_per_row(logits, rng, temps):
    """Row-wise sampling for the device-resident serving frame: ``temps``
    (B,) float32 rides in the frame carry, so rows with different sampling
    settings share one batch. Rows with temp <= 0 take argmax (bit-identical
    to the greedy host path); the rest sample at their own temperature.
    logits: (B, V) → token ids (B,) int32."""
    greedy_toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy_toks, sampled)


def sample_logits(logits, rng, *, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0, greedy: bool = False):
    """logits: (B, V) → token ids (B,) int32."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
