"""SimEngine: the virtual-time twin of ``InferenceEngineV2.serve()``.

Presents the exact engine surface the fleet layer consumes — ``serve()``
as a cooperatively-steppable generator yielding ``(uid, tokens)`` /
``HandoffEvent`` / ``ServeBoundary``, plus ``_config`` / ``telemetry`` /
``kv`` / ``_ledger`` / ``snapshot_serving_state`` / drain-and-role hooks
— while executing NO frames: a "frame" advances per-row token counters
deterministically and charges virtual seconds from the committed cost
baseline (``sim.cost.FrameCostModel``).

Everything that IS policy stays the production object: the
``RequestScheduler`` passed by the router's ``scheduler_factory`` runs
verbatim (submit quotas, SLO sheds, aging, fair share, preemption,
admission, frame-steps caps), the ``ServingTelemetry`` is the real class
on the virtual clock (so TTFT/ITL percentiles come out of the same
histograms the live fleet exports), and the per-boundary sequence below
mirrors ``engine_v2._serve_loop_sched`` stage for stage — arrival poll,
deadline expiry, ``on_boundary`` control pass, preemption, admission,
idle/exhausted handling, frame plan, emissions, retirement, handoffs,
boundary event. Arrival normalization reuses the real
``InferenceEngineV2._norm_arrival`` staticmethod.

Time: the engine keeps a replica-LOCAL timeline ``local_t`` and seeks
the shared :class:`~.clock.VirtualClock` to it whenever it runs, so
every timestamp the real policy objects read (ledger deadlines,
ShedReason.t, telemetry spans, ``ServeBoundary.t``) is replica-local
virtual time. The fleet driver (``sim.sim``) gates arrival delivery on
``min(local_t)`` across replicas and fast-forwards idle engines.
"""

import dataclasses
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

import numpy as np

from ..engine_v2 import (HandoffEvent, InferenceEngineV2,
                         RaggedInferenceEngineConfig, ServeBoundary)
from ..faults import FaultReason, LedgerEntry, snapshot_ledger
from ..telemetry import (N_STATS, STAT_ACCEPTED, STAT_ACTIVE_STEPS,
                         STAT_DRAFTED, STAT_EMITTED, STAT_EOS,
                         STAT_PREFILL_TOKS, STAT_TARGET_FWD,
                         ServingTelemetry)
from .clock import VirtualClock
from .cost import FrameCostModel

_VOCAB = 32000


def synth_token(uid: int, k: int) -> int:
    """Deterministic synthetic token value for generated token ``k`` of
    request ``uid`` (never 0/1 — those are common pad/eos ids)."""
    return ((uid * 1009 + k * 31 + 7) % (_VOCAB - 2)) + 2


class _SimSeq:
    """Host-side descriptor mirror (``state.seqs`` entry): just enough
    for ``faults.snapshot_ledger`` and the serve-loop bookkeeping."""
    __slots__ = ("uid", "generated", "done", "blocks", "seen_tokens")

    def __init__(self, uid: int):
        self.uid = uid
        self.generated: List[int] = []
        self.done = False
        self.blocks = 0          # reserved KV blocks (count, not ids)
        self.seen_tokens = 0

    def get(self, key, default=None):   # snapshot_ledger duck-typing aid
        return getattr(self, key, default)


class _SimState:
    """``engine.state`` twin: descriptor map + KV release on flush."""

    def __init__(self, kv: "_SimKV"):
        self.seqs: Dict[int, _SimSeq] = {}
        self._kv = kv

    def get_or_create_sequence(self, uid: int) -> _SimSeq:
        seq = self.seqs.get(uid)
        if seq is None:
            seq = self.seqs[uid] = _SimSeq(uid)
        return seq

    def flush_sequence(self, uid: int) -> None:
        seq = self.seqs.pop(uid, None)
        if seq is not None and seq.blocks:
            self._kv.release(seq.blocks)
            seq.blocks = 0


class _SimKV:
    """Paged-pool accounting twin (``engine.kv``): block arithmetic and
    a free-block counter — the numbers admission control runs on."""

    def __init__(self, num_blocks: int, block_size: int,
                 block_bytes: int = 0):
        self.num_blocks = int(num_blocks)
        self.free_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.block_bytes = int(block_bytes)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def reserve(self, n: int) -> bool:
        if n > self.free_blocks:
            return False
        self.free_blocks -= n
        return True

    def release(self, n: int) -> None:
        self.free_blocks = min(self.num_blocks, self.free_blocks + n)


class SimSwapTier:
    """Shared KV swap-tier twin for disaggregated sim fleets.

    Stores WATERMARKS, not pages: a handoff/preemption record maps uid ->
    committed token count, and re-admission turns it into a ``cached0``
    prefill skip. Satisfies the ``EngineRouter`` ctor's shared-tier
    validation (one instance, ``shared=True``) and the autoscaler's
    tier-identity checks."""

    shared = True

    def __init__(self):
        self.records: Dict[int, Dict] = {}
        self.stats: Dict[str, int] = {"requests": 0, "handoffs": 0}
        self.flight = None          # router.attach_tracing assigns this

    # -- engine-side surface -----------------------------------------
    def put_request(self, uid: int, watermark: int, kv=None, blocks=None,
                    **kw) -> None:
        self.records[uid] = {"watermark": int(watermark)}
        self.stats["requests"] += 1

    def stamp_request_handoff(self, uid: int, meta: Dict) -> bool:
        rec = self.records.setdefault(uid, {"watermark": 0})
        rec.update(meta)
        self.stats["handoffs"] += 1
        return True

    def request_record(self, uid: int) -> Optional[Dict]:
        return self.records.get(uid)

    def drop_request(self, uid: int) -> None:
        self.records.pop(uid, None)

    def prune_requests(self, keep) -> None:
        pass                        # shared tier: router owns lifecycle


@dataclasses.dataclass
class _SimRow:
    """One live slot: the per-row counters a virtual frame advances."""
    uid: int
    plen: int                  # folded prompt length (tokens to commit)
    limit: int                 # REMAINING generation budget
    temp: float
    eos: Optional[int]
    cached: int                # committed tokens (prefill watermark)
    gen_base: int              # seq.generated entries predating admission


class SimEngine:
    """See module docstring. One instance per simulated replica."""

    def __init__(self, *, config: Optional[RaggedInferenceEngineConfig]
                 = None, clock: Optional[VirtualClock] = None,
                 cost_model: Optional[FrameCostModel] = None,
                 max_seq_len: int = 4096, num_layers: int = 16,
                 sink: Optional[Callable] = None,
                 spec_acceptance: float = 0.7,
                 idle_poll_s: float = 0.002,
                 kv_swap=None, name: str = ""):
        self._config = config or RaggedInferenceEngineConfig()
        self._clock = clock or VirtualClock()
        self.cost = cost_model or FrameCostModel()
        self.max_seq_len = int(max_seq_len)
        self.model = SimpleNamespace(
            cfg=SimpleNamespace(num_layers=num_layers))
        self.name = name
        self.local_t = float(self._clock())
        self.sink = sink
        self.spec_acceptance = float(spec_acceptance)
        self.idle_poll_s = float(idle_poll_s)
        c = self._config
        n_blocks = c.num_kv_blocks
        if n_blocks is None and c.expected_context and \
                c.expected_concurrency:
            per = -(-(c.expected_context) // c.kv_block_size)
            n_blocks = per * c.expected_concurrency
        if n_blocks is None:
            n_blocks = c.max_ragged_batch_size * \
                (-(-self.max_seq_len // c.kv_block_size))
        self.kv = _SimKV(n_blocks, c.kv_block_size)
        self.state = _SimState(self.kv)
        self.telemetry = ServingTelemetry(enabled=c.telemetry,
                                          clock=self._clock)
        self.kv_swap = kv_swap
        self.last_crash_snapshot = None
        self.fault_log: List[FaultReason] = []
        self._ledger: Dict[int, LedgerEntry] = {}
        self._draining = False
        self._rows: Dict[int, _SimRow] = {}
        # per-engine prefix-cache model: recently published prompt token
        # tuples; admission skips the longest block-aligned common prefix
        self._prefix_store: List[tuple] = []
        self._prefix_blocks = 0
        # frames_executed x steps — the sim's work ledger (and the proof
        # surface that NO real frames ran: serving code asserts on this)
        self.virtual_frames = 0
        self.virtual_steps = 0

    # ------------------------------------------------------------------
    # engine surface the fleet layer calls outside serve()
    # ------------------------------------------------------------------

    def attach_kv_tier(self, tier, tag: Optional[str] = None) -> None:
        self.kv_swap = tier

    def begin_drain(self) -> None:
        self._draining = True

    def end_drain(self) -> None:
        self._draining = False

    def set_role(self, role: str) -> None:
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role={role!r}: expected 'unified', "
                             "'prefill' or 'decode'")
        if role == "prefill" and self.kv_swap is None:
            raise ValueError("set_role('prefill') needs a KV swap tier")
        self._config.role = role

    def cancel_request(self, uid: int) -> bool:
        ent = self._ledger.get(uid)
        if ent is None:
            return False
        ent.cancelled = True
        ent.deadline_at = self._clock()
        return True

    def snapshot_serving_state(self) -> Dict:
        return snapshot_ledger(self._ledger, self.state.seqs, self._clock,
                               swap_tier=self.kv_swap)

    def serve_stats(self) -> Dict:
        return self.telemetry.serve_view

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _emit_event(self, kind: str, uid=None, **kw) -> None:
        if self.sink is not None:
            self.sink(kind, uid=uid, t=self.local_t, engine=self.name,
                      **kw)

    def _validate_arrival(self, uid, toks, limit, in_flight: bool) -> int:
        if uid < 0:
            raise ValueError(f"uid={uid}: serve() uids must be >= 0")
        if in_flight or uid in self.state.seqs:
            raise ValueError(f"uid={uid} is already in flight")
        if len(toks) + 2 > self.max_seq_len:
            raise ValueError(
                f"uid={uid}: prompt of {len(toks)} tokens can never fit "
                f"max_seq_len={self.max_seq_len}")
        if len(toks) + limit + 1 > self.max_seq_len:
            limit = self.max_seq_len - len(toks) - 1
        return limit

    def _prefix_hit(self, toks) -> int:
        """Longest block-aligned published-prefix match (the local
        prefix-cache model; 0 when the cache is off)."""
        if not self._config.prefix_cache or not self._prefix_store:
            return 0
        best = 0
        t = tuple(int(x) for x in toks)
        for stored in self._prefix_store:
            n = 0
            for a, b in zip(stored, t):
                if a != b:
                    break
                n += 1
            best = max(best, n)
        bs = self.kv.block_size
        best = (best // bs) * bs
        return min(best, len(t) - 1)

    def _publish_prefix(self, toks) -> None:
        if not self._config.prefix_cache:
            return
        cap = self._config.prefix_cache_max_blocks
        t = tuple(int(x) for x in toks)
        if not t or t in self._prefix_store:
            return
        self._prefix_store.append(t)
        self._prefix_blocks += self.kv.blocks_for(len(t))
        if cap is not None:
            while self._prefix_blocks > cap and len(self._prefix_store) > 1:
                old = self._prefix_store.pop(0)
                self._prefix_blocks -= self.kv.blocks_for(len(old))
        self.telemetry.gauges["prefix_blocks_resident"] = \
            self._prefix_blocks

    def _admit_capacity(self, uid: int, seq: _SimSeq, toks, limit: int,
                        resumed: bool) -> Optional[int]:
        """KV reservation + cached-prefix discovery (the ``try_reserve``
        the real admission passes the scheduler). Returns ``cached0`` or
        None when the pool can't hold the request."""
        need = self.kv.blocks_for(len(toks) + limit + 1)
        if not self.kv.reserve(need):
            return None
        seq.blocks += need
        cached0 = 0
        if resumed and self.kv_swap is not None:
            rec = self.kv_swap.request_record(uid)
            if rec:
                cached0 = min(int(rec.get("watermark", 0)), len(toks) - 1)
                if cached0:
                    self.telemetry.on_kv_swap_in(
                        self.kv.blocks_for(cached0), resume=True)
        if cached0 == 0:
            cached0 = self._prefix_hit(toks)
            if self._config.prefix_cache:
                self.telemetry.on_prefix_lookup(
                    cached0, self.kv.blocks_for(cached0) if cached0
                    else 0, cow=False)
        return cached0

    def _fault_retire(self, uid: int, kind: str, frame: int, detail: str,
                      partial=None) -> None:
        ent = self._ledger.pop(uid, None)
        if self.kv_swap is not None:
            self.kv_swap.drop_request(uid)
        self.fault_log.append(FaultReason(
            uid=uid, kind=kind, frame=frame, detail=detail,
            tokens_emitted=len(partial or ()),
            partial=list(partial) if partial else None,
            tenant=ent.tenant if ent else None,
            priority=str(ent.priority) if ent and ent.priority is not None
            else None))
        self.telemetry.on_fault(kind, uid=uid)
        self._emit_event("fault", uid, kind=kind)

    def _expire_deadlines(self, sched, boundary: int) -> None:
        now = self._clock()
        expired = [uid for uid, ent in self._ledger.items()
                   if ent.deadline_at is not None
                   and now >= ent.deadline_at]
        for uid in expired:
            seq = self.state.seqs.get(uid)
            partial = list(seq.generated) if seq is not None else []
            if uid in self._rows:
                del self._rows[uid]
                sched.on_retire(uid)
            else:
                sched.cancel(uid)
            self.state.flush_sequence(uid)
            ent = self._ledger.get(uid)
            kind = "cancelled" if ent is not None and ent.cancelled \
                else "deadline_expired"
            self._fault_retire(uid, kind, boundary, detail=kind,
                               partial=partial)

    def _evict_to_queue(self, uid: int, sched) -> None:
        """Mirror of ``engine_v2._evict_to_queue``: fold emitted tokens,
        free blocks, requeue front; swap tier keeps the watermark so
        re-admission restores instead of re-prefilling."""
        from ..scheduler import PRIORITY_NAMES
        seq = self.state.seqs[uid]
        row = self._rows.pop(uid)
        req = sched.on_evict(uid)
        emitted = seq.generated[req.gen_base:]
        if emitted:
            req.tokens = np.concatenate(
                [np.asarray(req.tokens, np.int32),
                 np.asarray(emitted, np.int32)])
            req.limit -= len(emitted)
        if self.kv_swap is not None and self._config.kv_swap_preempt \
                and 0 < row.cached <= len(req.tokens):
            self.kv_swap.put_request(uid, row.cached)
            self.telemetry.on_kv_swap_out(
                self.kv.blocks_for(row.cached), uid=uid)
        if seq.blocks:
            self.kv.release(seq.blocks)
            seq.blocks = 0
        sched.requeue_front(req)
        self.telemetry.on_preempt(uid, req.tenant,
                                  PRIORITY_NAMES[req.priority])
        self._emit_event("preempt", uid)

    # ------------------------------------------------------------------
    # serve
    # ------------------------------------------------------------------

    def serve(self, arrivals, *, max_new_tokens: int = 32,
              temperature: float = 0.0, eos_token_id: Optional[int] = None,
              frame_steps: Optional[int] = None,
              frame_slots: Optional[int] = None,
              speculate: Optional[bool] = None, gamma: Optional[int] = None,
              rng=None, scheduler=None, faults=None, resume_from=None,
              yield_boundaries: bool = False):
        """Virtual-time ``serve()`` — same contract as the real engine's
        (see module docstring). ``scheduler`` is REQUIRED: the simulator
        exists to exercise the production policy object."""
        if scheduler is None:
            raise ValueError(
                "SimEngine.serve needs scheduler= (pass a "
                "scheduler_factory to the router): the simulator runs "
                "the real RequestScheduler, there is no FIFO twin")
        c = self._config
        steps = frame_steps or c.frame_steps
        adaptive = c.adaptive_frame_steps and frame_steps is None
        if speculate is None:
            speculate = False       # sim has no draft model attached
        gamma = int(gamma if gamma is not None else c.speculate_gamma)
        n_slots = frame_slots or c.max_ragged_batch_size
        arrivals = iter(arrivals)
        self._handoff_mode = c.role == "prefill"
        if self._handoff_mode and self.kv_swap is None:
            raise ValueError("role='prefill' needs a KV swap tier")
        # a closed-mid-flight predecessor generator (role flip / drain
        # abandonment) may have left reserved descriptors behind: release
        # them so the KV accounting starts clean
        for uid in list(self.state.seqs):
            self.state.flush_sequence(uid)
        self._ledger = {}
        self._rows = {}
        self._draining = False
        self.telemetry.begin_serve(
            speculate=bool(speculate), gamma=gamma, adaptive=adaptive,
            n_slots=n_slots, kv_blocks_total=self.kv.num_blocks,
            tp_degree=c.tp, kv_block_bytes=self.kv.block_bytes)
        scheduler.begin_serve(self)
        resume = InferenceEngineV2._resume_entries(self, resume_from)
        return self._serve_loop(arrivals, scheduler, steps,
                                max_new_tokens, temperature, eos_token_id,
                                bool(speculate), gamma, adaptive, resume,
                                yield_boundaries)

    def _serve_loop(self, arrivals, sched, steps, max_new_tokens,
                    temperature, eos_token_id, speculate, gamma, adaptive,
                    resume, boundaries):
        from ..scheduler import (PRIORITY_NAMES, Request,
                                 normalize_priority)
        c = self._config
        tel = self.telemetry
        alpha = c.frame_steps_ewma_alpha
        ewma = 0.0
        exhausted = False
        boundary = -1
        self._clock.seek(self.local_t)
        # ---- crash-recovery ingestion (mirrors _serve_loop_sched) ----
        for (uid, prompt, limit, temp, eos, dl_ms, generated, tenant, prio,
             slo_ms, trace) in resume:
            seq = self.state.get_or_create_sequence(uid)
            seq.generated = list(generated)
            prio = normalize_priority(prio)
            tenant = tenant or "default"
            self._ledger_add(uid, prompt, limit, temp, eos, dl_ms,
                             tenant=tenant, priority=PRIORITY_NAMES[prio],
                             slo_ms=slo_ms, resumed_from=len(generated),
                             trace=trace)
            trace = tel.on_enqueue(uid, tenant=tenant,
                                   pclass=PRIORITY_NAMES[prio],
                                   resumed=len(generated) > 0, trace=trace)
            self._trace_back(uid, trace)
            remaining = limit - len(generated)
            if remaining <= 0:
                out = np.asarray(seq.generated, np.int64)
                self.state.flush_sequence(uid)
                self._ledger.pop(uid, None)
                tel.on_retire(uid)
                yield uid, out
                continue
            folded = list(prompt) + list(generated)
            sched.submit(Request(
                uid=uid, tokens=np.asarray(folded, np.int32),
                limit=remaining, temp=temp, eos=eos, tenant=tenant,
                priority=prio, slo_ms=slo_ms,
                resumed_from=len(generated), resumed=True),
                bypass_quota=True)
        while True:
            boundary += 1
            self._clock.seek(self.local_t)
            # ---- poll the arrival clock ----
            if exhausted:
                batch = None
                ewma = (1.0 - alpha) * ewma
            else:
                try:
                    batch = next(arrivals)
                except StopIteration:
                    exhausted = True
                    batch = None
                ewma = alpha * len(batch or []) + (1.0 - alpha) * ewma
                for item in (batch or []):
                    uid, toks, limit, temp, eos, tenant, prio, slo_ms, \
                        dl_ms, gen, trace = \
                        InferenceEngineV2._norm_arrival(
                            item, max_new_tokens, temperature,
                            eos_token_id)
                    limit = self._validate_arrival(
                        uid, toks, limit,
                        in_flight=uid in self._rows
                        or sched.is_queued(uid))
                    prio = normalize_priority(prio)
                    tenant = tenant or "default"
                    self._ledger_add(uid, toks, limit, temp, eos, dl_ms,
                                     tenant=tenant,
                                     priority=PRIORITY_NAMES[prio],
                                     slo_ms=slo_ms,
                                     resumed_from=len(gen) if gen else 0,
                                     trace=trace)
                    trace = tel.on_enqueue(uid, tenant=tenant,
                                           pclass=PRIORITY_NAMES[prio],
                                           resumed=bool(gen), trace=trace)
                    self._trace_back(uid, trace)
                    if gen is not None:
                        seq = self.state.get_or_create_sequence(uid)
                        seq.generated = list(gen)
                        remaining = limit - len(gen)
                        if remaining <= 0:
                            out = np.asarray(seq.generated, np.int64)
                            self.state.flush_sequence(uid)
                            self._ledger.pop(uid, None)
                            tel.on_retire(uid)
                            yield uid, out
                            continue
                        folded = np.concatenate(
                            [toks, np.asarray(gen, np.int32)]) \
                            if gen else toks
                        sched.submit(Request(
                            uid=uid, tokens=folded, limit=remaining,
                            temp=temp, eos=eos, tenant=tenant,
                            priority=prio, slo_ms=slo_ms,
                            resumed_from=len(gen), resumed=True),
                            bypass_quota=True)
                        continue
                    shed = sched.submit(Request(
                        uid=uid, tokens=toks, limit=limit, temp=temp,
                        eos=eos, tenant=tenant, priority=prio,
                        slo_ms=slo_ms))
                    if shed is not None:
                        tel.on_shed(uid, shed.tenant, shed.priority,
                                    shed.reason)
                        self._ledger.pop(uid, None)
                        self._emit_event("shed", uid, reason=shed.reason)
            # ---- deadlines, control pass, preemption, admission: the
            # exact _serve_loop_sched stage order ----
            self._expire_deadlines(sched, boundary)
            for shed in sched.on_boundary(tel.slo_view(),
                                          live_count=len(self._rows)):
                tel.on_shed(shed.uid, shed.tenant, shed.priority,
                            shed.reason)
                self.state.flush_sequence(shed.uid)
                self._ledger.pop(shed.uid, None)
                if self.kv_swap is not None:
                    self.kv_swap.drop_request(shed.uid)
                self._emit_event("shed", shed.uid, reason=shed.reason)
            tel.gauges["slo_risk"] = round(sched.risk, 4)
            n_slots = tel.gauges["slot_count"] or c.max_ragged_batch_size
            free_slots = int(n_slots) - len(self._rows)
            if not self._draining and sched.preempt_wanted(free_slots):
                committed = {u: r.cached for u, r in self._rows.items()}
                for uid in sched.pick_victims(
                        committed, free_blocks=self.kv.free_blocks):
                    self._evict_to_queue(uid, sched)
                free_slots = int(n_slots) - len(self._rows)

            def try_reserve(req):
                seq = self.state.get_or_create_sequence(req.uid)
                cached0 = self._admit_capacity(req.uid, seq, req.tokens,
                                               req.limit, req.resumed)
                if cached0 is None:
                    return None
                return (seq, cached0)

            admits = []
            if not self._draining:
                for req, res in sched.pick(free_slots, try_reserve,
                                           live_count=len(self._rows)):
                    seq, cached0 = res
                    seq.done = False
                    req.gen_base = len(seq.generated)
                    self._rows[req.uid] = _SimRow(
                        uid=req.uid, plen=len(req.tokens),
                        limit=req.limit, temp=req.temp, eos=req.eos,
                        cached=int(cached0), gen_base=req.gen_base)
                    admits.append(req.uid)
                    tel.on_admit(req.uid)
                    self._emit_event("admit", req.uid, cached0=cached0)
            if sched.queued_count() and not self._draining:
                tel.on_defer(
                    queue_depth=sched.queued_count(),
                    frame_steps=tel.serve_view["frame_steps_last"]
                    or steps,
                    free_slots=int(n_slots) - len(self._rows),
                    free_blocks=self.kv.free_blocks)
            if not self._rows:
                if exhausted and not sched.queued_count():
                    return
                self.local_t += self.idle_poll_s
                self._clock.seek(self.local_t)
                if boundaries:
                    yield ServeBoundary(
                        index=boundary, dispatched=False, live=0,
                        queued=sched.queued_count(),
                        free_slots=int(n_slots), t=self._clock(),
                        queued_tokens=sched.queued_prompt_tokens())
                continue
            # ---- frame plan (real arithmetic, virtual execution) ----
            width = c.prefill_chunk_size if any(
                r.cached < r.plen for r in self._rows.values()) else 1
            cur_steps = steps
            saturated = int(n_slots) == len(self._rows)
            if adaptive:
                cur_steps = InferenceEngineV2._pick_frame_steps(
                    ewma, steps, saturated)
            cur_steps = min(cur_steps, sched.frame_steps_cap(steps))
            tel.on_frame_plan(ewma, saturated, cur_steps)
            emissions, finished, first_uids, delta = \
                self._run_virtual_frame(width, cur_steps, speculate, gamma)
            dt = self.cost.frame_seconds(
                steps=cur_steps, live=len(self._rows),
                n_slots=int(n_slots), width=width,
                spec=speculate and width == 1, tp=c.tp,
                quant=c.weight_dtype == "int8"
                or c.tp_quantized_collectives)
            self.local_t += dt
            self._clock.seek(self.local_t)
            self.virtual_frames += 1
            self.virtual_steps += cur_steps
            tel.on_frame(delta=delta, width=width, steps=cur_steps,
                         live_slots=len(self._rows),
                         kv_blocks_in_use=self.kv.num_blocks
                         - self.kv.free_blocks,
                         arrival_ewma=ewma, recompiled_programs=0,
                         queue_depth=sched.queued_count())
            for uid in first_uids:
                # stamped POST-advance: the first token exists when the
                # frame that computed it completes, not when it starts
                self._emit_event("first_token", uid)
            for uid, new_toks in emissions.items():
                tel.on_emit(uid, len(new_toks))
                self._emit_event("emit", uid, n=len(new_toks))
            for uid in finished:
                seq = self.state.seqs[uid]
                seq.done = True
                out = np.asarray(seq.generated, np.int64)
                row = self._rows.pop(uid)
                self._publish_prefix(self._ledger[uid].prompt
                                     if uid in self._ledger else [])
                self.state.flush_sequence(uid)
                sched.on_retire(uid)
                self._ledger.pop(uid, None)
                if self.kv_swap is not None:
                    self.kv_swap.drop_request(uid)
                tel.on_retire(uid)
                self._emit_event("retire", uid, n=len(out))
                yield uid, out
            if self._handoff_mode:
                yield from self._collect_handoffs(sched, boundary)
            if boundaries:
                yield ServeBoundary(
                    index=boundary, dispatched=True,
                    live=len(self._rows), queued=sched.queued_count(),
                    free_slots=int(n_slots) - len(self._rows),
                    t=self._clock(),
                    queued_tokens=sched.queued_prompt_tokens(),
                    emissions=emissions)

    def _ledger_add(self, uid, toks, limit, temp, eos, deadline_ms,
                    tenant=None, priority=None, slo_ms=None,
                    resumed_from=0, trace=None) -> None:
        self._ledger[uid] = LedgerEntry(
            uid=uid, prompt=[int(t) for t in toks], limit=int(limit),
            temp=float(temp), eos=eos,
            deadline_at=(None if deadline_ms is None
                         else self._clock() + deadline_ms * 1e-3),
            tenant=tenant, priority=priority, slo_ms=slo_ms,
            resumed_from=resumed_from, trace=trace)

    def _trace_back(self, uid, trace) -> None:
        ent = self._ledger.get(uid)
        if ent is not None and trace is not None:
            ent.trace = trace

    def _run_virtual_frame(self, width, cur_steps, speculate, gamma):
        """Advance every live row ``cur_steps`` virtual steps: prefill
        rows commit ``width`` prompt tokens per step (emitting their
        first token at prompt completion), decode rows emit one token
        per step — or ``1 + round(acceptance * gamma)`` per verify
        forward under speculation (width-1 frames only, matching the
        real frame programs). Deterministic synthetic token values."""
        emissions: Dict[int, List[int]] = {}
        finished: List[int] = []
        first_uids: List[int] = []
        delta = np.zeros(N_STATS, np.int64)
        spec_k = int(round(self.spec_acceptance * gamma)) \
            if speculate and gamma > 0 else 0
        for uid, row in self._rows.items():
            seq = self.state.seqs[uid]
            new: List[int] = []
            done = False
            for _ in range(cur_steps):
                if done:
                    break
                delta[STAT_ACTIVE_STEPS] += 1
                if row.cached < row.plen:
                    take = min(width, row.plen - row.cached)
                    row.cached += take
                    delta[STAT_PREFILL_TOKS] += take
                    if row.cached < row.plen:
                        continue
                    emit_n = 1          # prompt-completion token
                elif width == 1 and spec_k:
                    delta[STAT_TARGET_FWD] += 1
                    delta[STAT_DRAFTED] += gamma
                    remaining = row.limit - (len(seq.generated)
                                             - row.gen_base)
                    emit_n = max(1, min(1 + spec_k, remaining))
                    delta[STAT_ACCEPTED] += emit_n - 1
                else:
                    if width == 1:
                        delta[STAT_TARGET_FWD] += 1
                    emit_n = 1
                for _k in range(emit_n):
                    k = len(seq.generated)
                    tok = synth_token(uid, k)
                    seq.generated.append(tok)
                    new.append(tok)
                    row.cached += 1
                    delta[STAT_EMITTED] += 1
                    if row.eos is not None and tok == row.eos:
                        delta[STAT_EOS] += 1
                        done = True
                        break
                    if len(seq.generated) - row.gen_base >= row.limit:
                        done = True
                        break
                seq.seen_tokens = row.cached
            if new:
                emissions[uid] = new
                if len(seq.generated) - row.gen_base == len(new):
                    first_uids.append(uid)
            if done or len(seq.generated) - row.gen_base >= row.limit:
                if not self._handoff_mode:
                    finished.append(uid)
        return emissions, finished, first_uids, delta

    def _collect_handoffs(self, sched, boundary: int):
        """Prefill-role boundary: rows whose watermark covers their
        prompt hand off (mirrors ``engine_v2._collect_handoffs``)."""
        for uid in [u for u, r in self._rows.items()
                    if r.cached >= r.plen]:
            seq = self.state.seqs.get(uid)
            ent = self._ledger.get(uid)
            if seq is None or ent is None or not seq.generated:
                continue
            row = self._rows[uid]
            self.kv_swap.put_request(uid, row.cached)
            self.kv_swap.stamp_request_handoff(
                uid, {"prompt_tokens": len(ent.prompt),
                      "generated": len(seq.generated), "role": "prefill"})
            item = {
                "uid": int(uid),
                "tokens": [int(t) for t in ent.prompt],
                "generated": [int(t) for t in seq.generated],
                "max_new_tokens": int(ent.limit),
                "temperature": float(ent.temp),
                "eos_token_id": -1 if ent.eos is None else int(ent.eos),
            }
            for k, v in (("tenant", ent.tenant),
                         ("priority", ent.priority),
                         ("slo_ms", ent.slo_ms), ("trace", ent.trace)):
                if v is not None:
                    item[k] = v
            if ent.deadline_at is not None:
                item["deadline_ms"] = max(
                    (ent.deadline_at - self._clock()) * 1e3, 1e-3)
            del self._rows[uid]
            sched.on_retire(uid)
            self.state.flush_sequence(uid)
            self._ledger.pop(uid, None)
            self.telemetry.on_handoff_out(uid, pipelined=False)
            self._emit_event("handoff_out", uid)
            yield HandoffEvent(uid=uid, arrival=item, published=True)
