"""Capacity sweeps and knob search over the simulator.

``sweep_capacity`` answers the headline question — how many replicas
does this traffic need at this SLO — by simulating the SAME trace at
each fleet size. ``tune`` searches the serving-knob space (grid or
seeded-random) and returns a ranked table plus a ``serve_config`` JSON
blob ``bin/dstpu_serve --config`` loads directly, so the sim's answer
deploys without transcription.

Both are thin deterministic loops over :class:`~.sim.FleetSimulator`;
with the default (uncalibrated) cost model the answers are RELATIVE —
calibrate against a live run (``cost.calibrate_from_boundaries``) for
absolute percentiles.
"""

import copy
import itertools
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..engine_v2 import RaggedInferenceEngineConfig
from ..scheduler import SchedulerConfig
from ..service.edge import EdgeConfig
from .sim import FleetSimulator, SimConfig, SimResult

SERVE_CONFIG_VERSION = 1

#: the default search space: the knobs the ISSUE names, kept small
#: enough that random sampling covers it meaningfully in ~24 draws
DEFAULT_SPACE: Dict[str, Sequence] = {
    "frame_steps": (2, 4, 8, 16),
    "prefill_chunk_size": (32, 64, 128),
    "speculate_gamma": (0, 2, 4),          # 0 = speculation off
    "prefix_cache_max_blocks": (None, 64, 256),   # None = cache off
    "lookahead_reserve": (False, True),
    "max_queued_tokens": (None, 512, 2048),       # edge admission
}


def apply_knobs(base: SimConfig, knobs: Dict) -> SimConfig:
    """One candidate deployment: ``base`` with ``knobs`` overlaid on the
    real config objects (engine / scheduler / edge)."""
    cfg = copy.deepcopy(base)
    e = cfg.engine or RaggedInferenceEngineConfig()
    cfg.engine = e
    if "frame_steps" in knobs:
        e.frame_steps = int(knobs["frame_steps"])
    if "prefill_chunk_size" in knobs:
        e.prefill_chunk_size = int(knobs["prefill_chunk_size"])
    if "speculate_gamma" in knobs:
        g = int(knobs["speculate_gamma"])
        cfg.speculate = g > 0
        cfg.gamma = g if g > 0 else None
        e.speculate_gamma = max(g, 1)
    if "prefix_cache_max_blocks" in knobs:
        blocks = knobs["prefix_cache_max_blocks"]
        e.prefix_cache = blocks is not None
        e.prefix_cache_max_blocks = blocks
    if "lookahead_reserve" in knobs:
        s = cfg.scheduler or SchedulerConfig()
        s.lookahead_reserve = bool(knobs["lookahead_reserve"])
        cfg.scheduler = s
    if "max_queued_tokens" in knobs or "shed_score" in knobs:
        ec = cfg.edge or EdgeConfig(trace=False)
        if "max_queued_tokens" in knobs:
            ec.max_queued_tokens = knobs["max_queued_tokens"]
        if "shed_score" in knobs:
            ec.shed_score = knobs["shed_score"]
        cfg.edge = ec
    return cfg


def default_score(result: SimResult, n_requests: int) -> float:
    """Lower is better: interactive latency first, with order-of-
    magnitude penalties for dropped/shed work so no latency win can buy
    its way past losing requests."""
    lat = result.latency
    ttft = lat["ttft"]["p90"] if lat["ttft"]["p90"] is not None else 1e6
    itl = lat["itl"]["p90"] or 0.0
    dropped = max(0, n_requests - result.completed)
    return (ttft + 0.5 * itl + 1e4 * dropped
            + 100.0 * result.sheds["engine"]
            + 100.0 * result.sheds["edge_dropped"])


def _result_row(result: SimResult) -> Dict:
    # SimResult.latency is already milliseconds (sim.py converts)
    return {
        "completed": result.completed,
        "tokens_per_s": result.tokens_per_s,
        "duration_s": result.duration_s,
        "ttft_p50_ms": result.latency["ttft"]["p50"],
        "ttft_p90_ms": result.latency["ttft"]["p90"],
        "itl_p50_ms": result.latency["itl"]["p50"],
        "itl_p90_ms": result.latency["itl"]["p90"],
        "e2e_p90_ms": result.latency["e2e"]["p90"],
        "sheds": dict(result.sheds),
        "preempts": result.preempts,
        "virtual_frames": result.virtual_frames,
    }


def sweep_capacity(trace: List[Dict], base: Optional[SimConfig] = None,
                   replica_counts: Sequence[int] = (1, 2, 4),
                   slo_ttft_p90_ms: Optional[float] = None) -> Dict:
    """Simulate ``trace`` at each fleet size; when an SLO is given, also
    report the smallest fleet meeting it (None if none does)."""
    base = base or SimConfig()
    rows = []
    for n in replica_counts:
        cfg = copy.deepcopy(base)
        cfg.replicas = int(n)
        cfg.roles = None           # capacity sweeps are role-uniform
        res = FleetSimulator(cfg).run(trace)
        row = {"replicas": int(n), **_result_row(res)}
        if slo_ttft_p90_ms is not None:
            row["meets_slo"] = (
                row["completed"] == len(trace)
                and row["ttft_p90_ms"] is not None
                and row["ttft_p90_ms"] <= slo_ttft_p90_ms)
        rows.append(row)
    out = {"requests": len(trace), "rows": rows}
    if slo_ttft_p90_ms is not None:
        fit = [r["replicas"] for r in rows if r.get("meets_slo")]
        out["slo_ttft_p90_ms"] = slo_ttft_p90_ms
        out["min_replicas_for_slo"] = min(fit) if fit else None
    return out


def _candidates(space: Dict[str, Sequence], mode: str, samples: int,
                seed: int) -> List[Dict]:
    keys = sorted(space)
    if mode == "grid":
        return [dict(zip(keys, combo))
                for combo in itertools.product(*(space[k] for k in keys))]
    if mode != "random":
        raise ValueError(f"mode={mode!r}: expected 'grid' or 'random'")
    rng = random.Random(seed)
    seen, out = set(), []
    for _ in range(samples * 20):
        combo = tuple(rng.choice(list(space[k])) for k in keys)
        if combo in seen:
            continue
        seen.add(combo)
        out.append(dict(zip(keys, combo)))
        if len(out) >= samples:
            break
    return out


def serve_config_from(cfg: SimConfig, knobs: Dict, row: Dict,
                      score: float) -> Dict:
    """The deployable artifact: the JSON shape ``bin/dstpu_serve
    --config`` overlays onto its engine/scheduler/edge construction."""
    e = cfg.engine or RaggedInferenceEngineConfig()
    s = cfg.scheduler
    ec = cfg.edge
    return {
        "version": SERVE_CONFIG_VERSION,
        "knobs": dict(knobs),
        "engine": {
            "frame_steps": e.frame_steps,
            "prefill_chunk_size": e.prefill_chunk_size,
            "speculate_gamma": e.speculate_gamma,
            "prefix_cache": e.prefix_cache,
            "prefix_cache_max_blocks": e.prefix_cache_max_blocks,
            "max_ragged_batch_size": e.max_ragged_batch_size,
        },
        "speculate": cfg.speculate,
        "scheduler": {
            "lookahead_reserve": bool(s.lookahead_reserve) if s else False,
        },
        "edge": {
            "max_queued_tokens": ec.max_queued_tokens if ec else None,
            "shed_score": ec.shed_score if ec else None,
        },
        "predicted": row,
        "score": round(score, 3),
    }


def tune(trace: List[Dict], base: Optional[SimConfig] = None,
         space: Optional[Dict[str, Sequence]] = None, mode: str = "random",
         samples: int = 24, seed: int = 0,
         score_fn=None) -> Tuple[Dict, List[Dict]]:
    """Search the knob space against ``trace``. Returns ``(serve_config,
    rows)``: the winner as a deployable serve-config blob, and every
    candidate's scored row (ranked best-first) for the frontier table."""
    base = base or SimConfig()
    space = space or DEFAULT_SPACE
    score_fn = score_fn or default_score
    rows = []
    best = None                    # (score, knob-repr, cfg, knobs, row)
    for knobs in _candidates(space, mode, samples, seed):
        cfg = apply_knobs(base, knobs)
        res = FleetSimulator(cfg).run(trace)
        sc = score_fn(res, len(trace))
        row = {"knobs": dict(knobs), "score": round(sc, 3),
               **_result_row(res)}
        rows.append(row)
        key = (sc, repr(sorted(knobs.items())))
        if best is None or key < best[0]:
            best = (key, cfg, knobs, row)
    rows.sort(key=lambda r: (r["score"], repr(sorted(r["knobs"].items()))))
    _, cfg, knobs, row = best
    return serve_config_from(cfg, knobs, row, row["score"]), rows
