"""FleetSimulator: the trace-driven discrete-event harness.

Builds a fleet of :class:`~.engine.SimEngine` replicas and drives them
through the REAL ``EngineRouter`` serial stepping loop — placement,
affinity, failover, drains, rejoins, role flips all run the production
code — with the REAL ``RequestScheduler`` per replica, the REAL
``ServiceEdge.admission_check`` math in front (no HTTP server), and the
REAL ``AutoscaleController`` on the tick path. The only substitutions
are the frame (virtual token arithmetic priced by the committed cost
baseline) and the clock (a shared :class:`~.clock.VirtualClock`).

Time model: each replica keeps its own ``local_t`` timeline (real
fleets step concurrently; the sim steps them in turn) and seeks the
shared clock to it while running. The arrival feeder gates delivery on
``min(local_t)`` over steppable replicas — an event is never delivered
before every replica has simulated past its arrival instant — and
fast-forwards the whole fleet across idle gaps, so simulated seconds
cost microseconds of wall time. Idle replicas are lifted to the fleet
frontier each tick, bounding cross-replica skew at one frame.

Determinism: everything downstream of the trace is pure arithmetic on
seeded/deterministic inputs, so the same (trace, config) pair produces
a byte-identical event log — ``SimResult.checkpoint`` carries the log's
sha256, and ``run(resume_checkpoint=...)`` re-derives the run from t=0
and ASSERTS the prefix digest at the recorded barrier before continuing
(a replay checkpoint: state is recomputed, never serialized).
"""

import copy
import dataclasses
import hashlib
import heapq
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..engine_v2 import RaggedInferenceEngineConfig
from ..faults import snapshot_split
from ..router import DEAD, DRAINING, HEALTHY, EngineRouter, RouterConfig
from ..scheduler import RequestScheduler, SchedulerConfig
from ..service.autoscale import AutoscaleConfig, AutoscaleController
from ..service.edge import EdgeConfig, ServiceEdge
from .clock import VirtualClock
from .cost import CostCalibration, FrameCostModel
from .engine import SimEngine, SimSwapTier
from .traffic import prompt_for, session_prefix_for


class _SimHalt(Exception):
    """Internal: clean mid-run stop (barrier snapshot / safety limit)."""


def _item_tokens(item) -> int:
    if isinstance(item, dict):
        return len(item["tokens"]) + len(item.get("generated") or ())
    return len(item[1])


def _pct(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, -(-int(p * len(xs)) // 100) - 1))
    return xs[k]


@dataclasses.dataclass
class SimConfig:
    """One simulated deployment: fleet shape + every policy config the
    real stack takes, passed through UNMODIFIED to the real objects."""
    replicas: int = 1
    #: per-replica roles ("unified" | "prefill" | "decode"); None = all
    #: unified. Any prefill role gets the fleet one shared SimSwapTier.
    roles: Optional[Sequence[str]] = None
    #: engine config template, copied per replica (role overridden)
    engine: Optional[RaggedInferenceEngineConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    router: Optional[RouterConfig] = None
    #: None = no autoscaler on the tick path
    autoscale: Optional[AutoscaleConfig] = None
    #: None = no edge admission gate in front of the router
    edge: Optional[EdgeConfig] = None
    #: shed clients re-offer after the edge's Retry-After this many times
    edge_max_retries: int = 3
    max_new_tokens: int = 32
    speculate: bool = False
    gamma: Optional[int] = None          # None = engine config's
    calibration: Optional[CostCalibration] = None
    spec_acceptance: float = 0.7
    idle_poll_s: float = 0.002
    max_seq_len: int = 4096
    rate_window_s: float = 10.0
    #: safety rails: a misconfigured sim must fail, not spin
    max_virtual_s: Optional[float] = None
    max_ticks: int = 1_000_000

    def describe(self) -> Dict:
        e = self.engine or RaggedInferenceEngineConfig()
        return {
            "replicas": self.replicas,
            "roles": (list(self.roles) if self.roles
                      else ["unified"] * self.replicas),
            "slots": e.max_ragged_batch_size,
            "frame_steps": e.frame_steps,
            "adaptive_frame_steps": e.adaptive_frame_steps,
            "prefill_chunk_size": e.prefill_chunk_size,
            "prefix_cache": e.prefix_cache,
            "prefix_cache_max_blocks": e.prefix_cache_max_blocks,
            "speculate": self.speculate,
            "gamma": (self.gamma if self.gamma is not None
                      else e.speculate_gamma),
            "max_new_tokens": self.max_new_tokens,
            "edge": self.edge is not None,
            "autoscale": self.autoscale is not None,
        }


class _SimDriver:
    """The fleet-driver facade the edge and autoscaler consume —
    ``queued_tokens_estimate`` / ``best_placement_score`` /
    ``tokens_per_second`` mirror ``service.fleet.FleetDriver``'s
    pressure-cache math exactly (same terms, same windows), computed
    from the serial router's state on the virtual clock."""

    def __init__(self, router: EngineRouter, clock: VirtualClock,
                 rate_window_s: float):
        self.router = router
        self._clock = clock
        self._rate_window_s = rate_window_s
        self._rate_win: List[Tuple[float, int]] = []
        self._queued_tokens_cache = 0
        self._ingress_tokens = 0        # the sim has no HTTP ingress queue
        self._best_score_cache: Optional[float] = None
        self._tps_cache = 0.0

    def refresh(self) -> None:
        rt = self.router
        total = 0
        for r in rt._replicas.values():
            b = r.last_boundary
            if b is not None and r.status in (HEALTHY, DRAINING):
                total += b.queued_tokens or 0
            total += rt._feed_prompt_tokens(r)
        for _, item, _ in rt._deferred:
            total += _item_tokens(item)
        for item, _ in rt._unplaced:
            total += _item_tokens(item)
        self._queued_tokens_cache = total
        scores = [rt._score(r) for r in rt._replicas.values()
                  if r.accepting()]
        self._best_score_cache = min(scores) if scores else None
        now = self._clock()
        while self._rate_win and \
                now - self._rate_win[0][0] > self._rate_window_s:
            self._rate_win.pop(0)
        toks = sum(n for _, n in self._rate_win)
        span = max(now - self._rate_win[0][0], 1e-3) if self._rate_win \
            else 1.0
        self._tps_cache = toks / span if toks else 0.0

    def note_completion(self, n_tokens: int) -> None:
        self._rate_win.append((self._clock(), int(n_tokens)))

    # -- the edge/autoscaler read surface ------------------------------
    def queued_tokens_estimate(self) -> int:
        return self._queued_tokens_cache + self._ingress_tokens

    def best_placement_score(self) -> Optional[float]:
        return self._best_score_cache

    def tokens_per_second(self) -> float:
        return self._tps_cache

    def in_flight(self) -> int:
        return len(self.router._assignment)

    def request_role_flip(self, name: str, role: str) -> bool:
        """Autoscaler surface: the serial-loop equivalent of
        ``FleetDriver.request_role_flip`` — same refusal rules (HEALTHY
        only, never strand decode capacity, pre-validate), then a
        synchronous generator restart with the queue migrated exactly
        like a drain (snapshot -> re-place), so nothing is lost."""
        rt = self.router
        r = rt._replicas.get(name)
        if r is None or r.status != HEALTHY:
            return False
        if role == "prefill":
            eff_nonprefill = [
                n for n, ro in rt._roles.items()
                if ro != "prefill" and n != name
                and rt._replicas[n].status != DEAD]
            if not eff_nonprefill:
                return False
        try:
            rt.validate_replica_role(name, role)
        except (ValueError, KeyError):
            return False
        snap = r.engine.snapshot_serving_state() if r.gen is not None \
            else None
        rt._close_gen(r)
        try:
            r.engine.set_role(role)
            rt.set_replica_role(name, role)
        except Exception:                # noqa: BLE001 — refusal, not crash
            return False
        rt.counters["scale_role_flips"] += 1
        held = list(r.feed)
        r.feed.clear()
        for item in held:
            rt._place(item)
        if snap:
            for item in rt._restamp_affinity(snapshot_split(snap)):
                rt._place(item)
        return True


@dataclasses.dataclass
class SimResult:
    """One simulated run: the capacity answer plus the evidence."""
    config: Dict
    completed: int
    tokens_out: int
    duration_s: float                 # virtual makespan (max local_t)
    tokens_per_s: float
    virtual_frames: int
    virtual_steps: int
    #: schedule-relative fleet percentiles (ms) from the event log:
    #: TTFT/E2E measured from the trace's INTENDED arrival instant
    latency: Dict[str, Dict]
    #: per-replica ServingTelemetry.latency_ms() — the engine-local view
    #: the live fleet exports (the --sim-fidelity comparison surface)
    telemetry: Dict[str, Dict]
    counters: Dict[str, int]          # router counters
    sheds: Dict[str, int]
    preempts: int
    handoffs: int
    faults: int
    autoscale_events: List[Dict]
    events: List[Dict]
    #: replay checkpoint over the full log: {"events": n, "sha256": hex}
    checkpoint: Dict = dataclasses.field(default_factory=dict)

    def event_lines(self) -> List[str]:
        return [json.dumps(e, sort_keys=True) for e in self.events]

    def to_json(self) -> Dict:
        out = dataclasses.asdict(self)
        del out["events"]
        return out


class FleetSimulator:
    """See module docstring. One instance = one deployment under test;
    ``run(trace)`` builds a FRESH fleet each call (no state carries
    over), replays the trace, and returns a :class:`SimResult`."""

    def __init__(self, config: Optional[SimConfig] = None):
        self.cfg = config or SimConfig()
        self.clock: Optional[VirtualClock] = None
        self.router: Optional[EngineRouter] = None
        self.driver: Optional[_SimDriver] = None
        self.edge: Optional[ServiceEdge] = None
        self.autoscaler: Optional[AutoscaleController] = None
        self.engines: Dict[str, SimEngine] = {}

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    def _build(self) -> None:
        cfg = self.cfg
        if cfg.replicas < 1:
            raise ValueError("SimConfig.replicas must be >= 1")
        roles = list(cfg.roles) if cfg.roles else \
            ["unified"] * cfg.replicas
        if len(roles) != cfg.replicas:
            raise ValueError(f"roles has {len(roles)} entries for "
                             f"{cfg.replicas} replicas")
        self.clock = VirtualClock()
        cost = FrameCostModel(calibration=cfg.calibration)
        tier = SimSwapTier() if any(r == "prefill" for r in roles) \
            else None
        template = cfg.engine or RaggedInferenceEngineConfig()
        self.engines = {}
        for i, role in enumerate(roles):
            e_cfg = copy.deepcopy(template)
            e_cfg.role = role
            self.engines[f"sim{i}"] = SimEngine(
                config=e_cfg, clock=self.clock, cost_model=cost,
                max_seq_len=cfg.max_seq_len, sink=self._sink,
                spec_acceptance=cfg.spec_acceptance,
                idle_poll_s=cfg.idle_poll_s, kv_swap=tier,
                name=f"sim{i}")
        r_cfg = cfg.router or RouterConfig()
        if r_cfg.driver != "serial":
            raise ValueError("the simulator drives the serial router "
                             f"loop; RouterConfig.driver={r_cfg.driver!r}")
        self.router = EngineRouter(self.engines, r_cfg, clock=self.clock)
        self.driver = _SimDriver(self.router, self.clock,
                                 cfg.rate_window_s)
        self.edge = ServiceEdge(self.driver, cfg.edge) \
            if cfg.edge is not None else None
        self.autoscaler = AutoscaleController(cfg.autoscale,
                                              clock=self.clock) \
            if cfg.autoscale is not None else None

    # ------------------------------------------------------------------
    # event log
    # ------------------------------------------------------------------

    def _log(self, kind: str, uid=None, t=None, engine="", **kw) -> None:
        ev = {"kind": kind, "t": float(t if t is not None
                                       else self.clock()),
              "engine": engine}
        if uid is not None:
            ev["uid"] = int(uid)
        for k, v in kw.items():
            if v is not None:
                ev[k] = v
        self._events.append(ev)
        self._sha.update((json.dumps(ev, sort_keys=True) + "\n").encode())
        if self._barrier_n is not None and \
                len(self._events) == self._barrier_n:
            self._barrier_digest = self._sha.hexdigest()

    def _sink(self, kind: str, uid=None, t=None, engine="", **kw) -> None:
        self._log(kind, uid=uid, t=t, engine=engine, **kw)

    # ------------------------------------------------------------------
    # the arrival feeder (polled by the router once per tick)
    # ------------------------------------------------------------------

    def _fleet_idle(self, steppable) -> bool:
        rt = self.router
        if rt._assignment or rt._deferred or rt._unplaced:
            return False
        for r in steppable:
            b = r.last_boundary
            if r.feed or (b is not None and (b.live or b.queued)):
                return False
        return True

    def _build_item(self, ev: Dict) -> Dict:
        prefix = session_prefix_for(ev["session"]) \
            if ev.get("session") else None
        item = {"uid": int(ev["uid"]),
                "tokens": prompt_for(int(ev["uid"]),
                                     int(ev["prompt_tokens"]),
                                     session_prefix=prefix)}
        if ev.get("max_new_tokens") is not None:
            item["max_new_tokens"] = int(ev["max_new_tokens"])
        for k in ("tenant", "priority", "slo_ms", "session",
                  "deadline_ms"):
            if ev.get(k) is not None:
                item[k] = ev[k]
        return item

    def _feeder(self, trace: List[Dict]):
        cfg = self.cfg
        i = 0
        retries: List[Tuple[float, int, int, Dict]] = []   # heap
        retry_seq = 0
        tick = -1
        while True:
            tick += 1
            if tick > cfg.max_ticks:
                self._log("halt", reason=f"max_ticks={cfg.max_ticks}")
                raise _SimHalt
            rt = self.router
            steppable = [r for r in rt._replicas.values()
                         if r.status in (HEALTHY, DRAINING)]
            # skew control: idle replicas ride the fleet frontier so the
            # delivery gate tracks the busy replicas, not a 2ms-per-tick
            # idle poll
            if steppable:
                front = max(r.engine.local_t for r in steppable)
                for r in steppable:
                    b = r.last_boundary
                    if not r.feed and (b is None
                                       or (b.live == 0 and b.queued == 0)):
                        r.engine.local_t = max(r.engine.local_t, front)
                gate = min(r.engine.local_t for r in steppable)
            else:
                gate = self.clock()
            self.clock.seek(gate)
            self.driver.refresh()
            if self.autoscaler is not None:
                n0 = len(self.autoscaler.events)
                self.autoscaler.on_tick(self.driver, tick)
                for ev in self.autoscaler.events[n0:]:
                    self._log("autoscale", **ev)
            # next pending instant (trace or client retry)
            nxt = trace[i]["t"] if i < len(trace) else None
            if retries and (nxt is None or retries[0][0] < nxt):
                nxt = retries[0][0]
            # fleet-wide idle fast-forward: nothing in flight anywhere
            # and the next event is in the future — jump to it
            if nxt is not None and nxt > gate and steppable \
                    and self._fleet_idle(steppable):
                for r in steppable:
                    r.engine.local_t = max(r.engine.local_t, nxt)
                gate = nxt
                self.clock.seek(gate)
            if cfg.max_virtual_s is not None and gate > cfg.max_virtual_s:
                self._log("halt",
                          reason=f"max_virtual_s={cfg.max_virtual_s}")
                raise _SimHalt
            # deliver everything due at the gate, in arrival order
            batch = []
            while True:
                due_retry = retries and retries[0][0] <= gate and \
                    (i >= len(trace) or retries[0][0] <= trace[i]["t"])
                if due_retry:
                    _, _, attempt, ev = heapq.heappop(retries)
                elif i < len(trace) and trace[i]["t"] <= gate:
                    ev, attempt = trace[i], 0
                    i += 1
                else:
                    break
                uid = int(ev["uid"])
                if self.edge is not None:
                    self.edge._inc("requests")
                    verdict = self.edge.admission_check()
                    if verdict is not None:
                        self.edge._inc("sheds")
                        will_retry = attempt < cfg.edge_max_retries
                        self._log("edge_shed", uid,
                                  reason=verdict["reason"],
                                  retry_after_s=verdict["retry_after_s"],
                                  attempt=attempt, will_retry=will_retry)
                        if will_retry:
                            retry_seq += 1
                            heapq.heappush(retries, (
                                gate + verdict["retry_after_s"],
                                retry_seq, attempt + 1, ev))
                        continue
                self._log("arrival", uid, sched_t=ev["t"],
                          attempt=attempt,
                          prompt_tokens=int(ev["prompt_tokens"]))
                batch.append(self._build_item(ev))
            if self._stop_n is not None and \
                    len(self._events) >= self._stop_n:
                raise _SimHalt
            if i >= len(trace) and not retries:
                if batch:
                    yield batch
                return
            yield batch

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------

    def run(self, trace: List[Dict], *,
            stop_after_events: Optional[int] = None,
            resume_checkpoint: Optional[Dict] = None,
            faults=None) -> SimResult:
        """Replay ``trace`` (a list of traffic.py arrival events) through
        a fresh fleet. ``stop_after_events`` halts at the first tick with
        that many events logged (the returned checkpoint is the barrier
        snapshot); ``resume_checkpoint`` re-derives the run from t=0 and
        asserts the event-log prefix digest at the recorded barrier.
        ``faults`` takes a ``RouterFaultInjector`` for chaos sims."""
        cfg = self.cfg
        self._build()
        self._events: List[Dict] = []
        self._sha = hashlib.sha256()
        self._stop_n = stop_after_events
        self._barrier_n = resume_checkpoint["events"] \
            if resume_checkpoint else None
        self._barrier_digest: Optional[str] = None
        completions: Dict[int, int] = {}
        gen = self.router.serve(
            self._feeder(trace), max_new_tokens=cfg.max_new_tokens,
            temperature=0.0, eos_token_id=None,
            scheduler_factory=lambda: RequestScheduler(
                cfg.scheduler, clock=self.clock),
            faults=faults,
            engine_kwargs={"speculate": cfg.speculate,
                           "gamma": cfg.gamma})
        try:
            for uid, toks in gen:
                self.driver.note_completion(len(toks))
                self._log("complete", uid, n=len(toks))
                completions[int(uid)] = len(toks)
        except _SimHalt:
            pass
        finally:
            gen.close()
        if resume_checkpoint is not None:
            want = resume_checkpoint["sha256"]
            if self._barrier_digest != want:
                raise RuntimeError(
                    "sim resume divergence: event-log prefix digest at "
                    f"barrier {resume_checkpoint['events']} is "
                    f"{self._barrier_digest}, checkpoint recorded {want}")
        return self._result(trace, completions)

    def _result(self, trace: List[Dict],
                completions: Dict[int, int]) -> SimResult:
        events = self._events
        sched_t: Dict[int, float] = {}
        first_t: Dict[int, float] = {}
        done_t: Dict[int, float] = {}
        done_n: Dict[int, int] = {}
        kinds: Dict[str, int] = {}
        for e in events:
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
            uid = e.get("uid")
            if e["kind"] == "arrival" and uid not in sched_t:
                sched_t[uid] = e["sched_t"]
            elif e["kind"] == "first_token" and uid not in first_t:
                first_t[uid] = e["t"]
            elif e["kind"] == "retire":
                done_t[uid] = e["t"]
                done_n[uid] = e["n"]
        ttft = [(first_t[u] - sched_t[u]) * 1e3
                for u in first_t if u in sched_t]
        e2e = [(done_t[u] - sched_t[u]) * 1e3
               for u in done_t if u in sched_t]
        itl = [(done_t[u] - first_t[u]) / (done_n[u] - 1) * 1e3
               for u in done_t
               if u in first_t and done_n.get(u, 0) > 1]
        latency = {
            name: {"count": len(xs),
                   "p50": _pct(xs, 50), "p90": _pct(xs, 90),
                   "p99": _pct(xs, 99)}
            for name, xs in (("ttft", ttft), ("itl", itl), ("e2e", e2e))}
        duration = max([e.engine.local_t
                        for e in self.router._replicas.values()] or [0.0])
        tokens_out = sum(completions.values())
        edge_sheds = sum(1 for e in events if e["kind"] == "edge_shed")
        edge_dropped = sum(1 for e in events if e["kind"] == "edge_shed"
                           and not e.get("will_retry"))
        return SimResult(
            config=self.cfg.describe(),
            completed=len(completions),
            tokens_out=tokens_out,
            duration_s=round(duration, 9),
            tokens_per_s=round(tokens_out / duration, 3) if duration
            else 0.0,
            virtual_frames=sum(e.virtual_frames
                               for e in self.engines.values()),
            virtual_steps=sum(e.virtual_steps
                              for e in self.engines.values()),
            latency=latency,
            telemetry={name: eng.telemetry.latency_ms()
                       for name, eng in self.engines.items()},
            counters=dict(self.router.counters),
            sheds={"edge": edge_sheds, "edge_dropped": edge_dropped,
                   "engine": kinds.get("shed", 0)},
            preempts=kinds.get("preempt", 0),
            handoffs=kinds.get("handoff_out", 0),
            faults=kinds.get("fault", 0),
            autoscale_events=[dict(e) for e in
                              (self.autoscaler.events
                               if self.autoscaler else [])],
            events=events,
            checkpoint={"events": len(events),
                        "sha256": self._sha.hexdigest()},
        )
