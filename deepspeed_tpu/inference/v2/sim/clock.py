"""Shared seekable virtual clock for the fleet simulator.

One instance is threaded through every time seam the serving stack
exposes (``ServingTelemetry(clock=...)``, ``RequestScheduler(clock=)``,
``EngineRouter(clock=)``, ``AutoscaleController(clock=)``): a plain
zero-argument callable returning seconds, exactly like
``time.monotonic``, plus ``advance``/``seek`` for the simulator to move
time.

``seek`` may move BACKWARD: replicas keep independent local timelines
(replica A can be at t=3.2 while B is still at t=3.0 — real fleets step
concurrently; the sim steps them in turn), and the simulator positions
the shared clock to a replica's local time before touching it so that
telemetry TTFT/ITL and router heartbeat gaps read replica-local time.
``advance`` is the strictly-forward form used while executing one
replica's frame.
"""


class VirtualClock:
    __slots__ = ("t",)

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"advance({dt}): virtual time only moves "
                             "forward; use seek() to reposition")
        self.t += dt
        return self.t

    def seek(self, t: float) -> float:
        self.t = float(t)
        return self.t

    def __repr__(self) -> str:
        return f"VirtualClock(t={self.t:.6f})"
