"""Frame-seconds model over the committed static cost ledger.

The simulator never executes a frame; it prices one. The committed
``.graft-cost-baseline.json`` gives exact static resource counts per
traced frame program (FLOPs, HBM read+write bytes, collective wire
bytes — see ``analysis.cost_model``); this module turns those counts
into virtual SECONDS with a two-parameter affine model:

    seconds = c0 + k * steps * work(program, live_frac)
    work    = flops/F0 + (hbm_read+hbm_write)/B0 + collective_payload/W0

``F0``/``B0``/``W0`` are fixed nominal device rates (they only set the
relative weighting of compute vs memory vs interconnect; any common
scale folds into ``k``), and ``(c0, k)`` — per-frame fixed overhead and
the device's effective speed — are fitted by least squares from a
handful of live boundary timings (``calibrate_from_boundaries``), with
an optional per-ledger-program refinement for boundary overhead that
differs by frame shape. Calibration is optional: the uncalibrated defaults give self-consistent RELATIVE
capacity answers (2x the work is 2x the time), which is what a sweep
frontier needs; the ``--sim-fidelity`` bench calibrates against a live
run before comparing absolute percentiles.

The ledger is keyed by program shape, so the model inherits the cost
characteristics the lint stack enforces: a kernel change that shifts
GL201 shifts the sim's capacity answers with it.
"""

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ....analysis.cost_model import COST_BASELINE_PATH, FrameCostQuery

# nominal device rates (per second). Absolute values are irrelevant —
# they fold into the fitted k — but the RATIOS encode the roofline:
# ~2e14 flop/s, ~8e11 HBM B/s, ~1e11 interconnect B/s is a generic
# inference-accelerator shape (compute-rich, wire-poor).
NOMINAL_FLOPS = 2.0e14
NOMINAL_HBM_BPS = 8.0e11
NOMINAL_WIRE_BPS = 1.0e11

#: uncalibrated defaults: zero fixed overhead, unit speed. Chosen so an
#: uncalibrated sim is deterministic and self-consistent, not accurate.
DEFAULT_C0 = 2.0e-3
DEFAULT_K = 1.0


@dataclasses.dataclass
class CostCalibration:
    """Fitted ``(c0, k)`` plus provenance, JSON round-trippable.

    ``per_program`` optionally refines the global pair per traced
    ledger program: one affine over raw work cannot represent
    host-side boundary overhead that differs by frame SHAPE (a wide
    admission boundary reallocates device buffers and reserves KV
    blocks; a steady decode boundary does neither), so programs with
    enough samples carry their own ``{c0, k}``."""
    c0: float = DEFAULT_C0
    k: float = DEFAULT_K
    n_samples: int = 0
    residual: float = 0.0         # RMS relative residual of the fit
    per_program: Optional[Dict[str, Dict[str, float]]] = None

    def for_program(self, name: str) -> Tuple[float, float]:
        entry = (self.per_program or {}).get(name)
        if entry:
            return float(entry["c0"]), float(entry["k"])
        return self.c0, self.k

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: Dict) -> "CostCalibration":
        return cls(**{f.name: data[f.name]
                      for f in dataclasses.fields(cls) if f.name in data})


def fit_calibration(samples: Sequence[Tuple[float, float]]
                    ) -> CostCalibration:
    """Least-squares fit of ``dt = c0 + k * w`` from ``(w, dt)`` pairs.

    ``w`` is the model's raw work term for the boundary (steps x
    per-step work), ``dt`` the measured wall seconds. Compile-warmup
    outliers must be excluded by the caller (``calibrate_from_boundaries``
    does). Falls back to the defaults when the system is degenerate
    (fewer than two distinct work values)."""
    pts = [(float(w), float(dt)) for w, dt in samples
           if dt > 0 and w > 0]
    if len(pts) < 2 or len({round(w, 12) for w, _ in pts}) < 2:
        return CostCalibration(n_samples=len(pts))
    n = len(pts)
    sw = sum(w for w, _ in pts)
    st = sum(dt for _, dt in pts)
    sww = sum(w * w for w, _ in pts)
    swt = sum(w * dt for w, dt in pts)
    det = n * sww - sw * sw
    if det <= 0:
        return CostCalibration(n_samples=n)
    k = (n * swt - sw * st) / det
    c0 = (st - k * sw) / n
    # a pathological fit (negative slope from noise) is worse than the
    # default: keep c0 >= 0 and k > 0 so virtual time is monotone
    if k <= 0:
        return CostCalibration(n_samples=n)
    c0 = max(0.0, c0)
    res = [abs((c0 + k * w) - dt) / dt for w, dt in pts]
    rms = (sum(r * r for r in res) / n) ** 0.5
    return CostCalibration(c0=c0, k=k, n_samples=n, residual=rms)


class FrameCostModel:
    """Prices one planned frame in virtual seconds (see module doc)."""

    def __init__(self, query: Optional[FrameCostQuery] = None,
                 calibration: Optional[CostCalibration] = None,
                 baseline_path: str = COST_BASELINE_PATH):
        self.query = query or FrameCostQuery.load(baseline_path)
        self.calibration = calibration or CostCalibration()
        self._work_cache: Dict[str, float] = {}

    # -- raw work -----------------------------------------------------
    def _program_work(self, name: str) -> float:
        w = self._work_cache.get(name)
        if w is None:
            m = self.query.metrics(name)
            w = (m["flops"] / NOMINAL_FLOPS
                 + (m["hbm_read"] + m["hbm_write"]) / NOMINAL_HBM_BPS
                 + m["collective_payload"] / NOMINAL_WIRE_BPS)
            self._work_cache[name] = w
        return w

    def _resolve(self, *, steps: int, live: int, n_slots: int,
                 width: int = 1, spec: bool = False, tp: int = 1,
                 quant: bool = False) -> Tuple[str, float]:
        """(ledger program name, raw work) for one frame plan.

        The ledger prices a FULL pool; live rows scale the row-parallel
        portion. ``live_frac`` never drops below one row's worth so an
        almost-empty frame still pays the lockstep dispatch."""
        name = self.query.frame_program(width=width, spec=spec, tp=tp,
                                        quant=quant)
        live_frac = max(1, live) / max(1, n_slots)
        return name, float(steps) * live_frac * self._program_work(name)

    def frame_work(self, **kw) -> float:
        """Raw (unfitted) work for one frame plan."""
        return self._resolve(**kw)[1]

    def frame_seconds(self, **kw) -> float:
        """Calibrated virtual seconds for one frame plan (the fitted
        pair for this frame's ledger program when the calibration
        carries one, else the global pair)."""
        name, work = self._resolve(**kw)
        c0, k = self.calibration.for_program(name)
        return c0 + k * work


def calibrate_from_boundaries(model: FrameCostModel,
                              samples: Sequence[Dict],
                              warmup_factor: float = 5.0
                              ) -> CostCalibration:
    """Fit ``(c0, k)`` from live serial-run boundary observations.

    Each sample: ``{dt, steps, live, n_slots, width, spec, tp, quant}``
    where ``dt`` is the wall-clock gap between consecutive
    ``ServeBoundary.t`` stamps (telemetry records no per-frame wall
    time, so boundary deltas are the only live timing source). Samples
    whose dt exceeds ``warmup_factor`` x median are dropped: the first
    boundary of each (width, steps) bucket pays XLA compilation, which
    the virtual fleet never does.

    Beyond the global affine, each ledger program with >= 2 surviving
    samples gets its own sub-fit (see ``CostCalibration.per_program``).
    A degenerate sub-group — one distinct work value, so no slope
    information — anchors its intercept at the group's mean dt instead,
    borrowing the global slope when that fit is trustworthy (relative
    residual < 0.5) and the unit default otherwise."""
    pts: List[Tuple[float, float]] = []
    groups: Dict[str, List[Tuple[float, float]]] = {}
    dts = sorted(float(s["dt"]) for s in samples if s.get("dt", 0) > 0)
    if not dts:
        return CostCalibration()
    median = dts[len(dts) // 2]
    for s in samples:
        dt = float(s.get("dt", 0))
        if dt <= 0 or dt > warmup_factor * median:
            continue
        name, w = model._resolve(
            steps=int(s.get("steps", 1)), live=int(s.get("live", 1)),
            n_slots=int(s.get("n_slots", 1)),
            width=int(s.get("width", 1)), spec=bool(s.get("spec")),
            tp=int(s.get("tp", 1)), quant=bool(s.get("quant")))
        pts.append((w, dt))
        groups.setdefault(name, []).append((w, dt))
    cal = fit_calibration(pts)
    k_anchor = (cal.k if cal.n_samples and cal.residual < 0.5
                else DEFAULT_K)
    per: Dict[str, Dict[str, float]] = {}
    for name, g in groups.items():
        if len(g) < 2:
            continue
        sub = fit_calibration(g)
        degenerate = (sub.c0 == DEFAULT_C0 and sub.k == DEFAULT_K
                      and sub.residual == 0.0)
        if degenerate:
            mean_w = sum(w for w, _ in g) / len(g)
            mean_dt = sum(dt for _, dt in g) / len(g)
            sub = CostCalibration(
                c0=max(0.0, mean_dt - k_anchor * mean_w), k=k_anchor,
                n_samples=len(g))
        per[name] = {"c0": sub.c0, "k": sub.k,
                     "n_samples": sub.n_samples,
                     "residual": sub.residual}
    if per:
        cal = dataclasses.replace(cal, per_program=per)
    model.calibration = cal
    return cal


def save_calibration(path: str, cal: CostCalibration) -> None:
    with open(path, "w") as fh:
        json.dump(cal.to_json(), fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_calibration(path: str) -> CostCalibration:
    with open(path) as fh:
        return CostCalibration.from_json(json.load(fh))
