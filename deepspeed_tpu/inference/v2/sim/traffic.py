"""Arrival-trace schema and seeded traffic synthesizers.

One trace = a list of arrival events, each a plain dict::

    {"t": float seconds from trace start,   # required
     "uid": int,                            # required, unique
     "prompt_tokens": int,                  # required
     "max_new_tokens": int,
     "tenant": str, "priority": str, "slo_ms": float,
     "session": str, "deadline_ms": float}

— deliberately the same shape ``tracing.extract_workload`` emits from a
recorded ``dstpu_trace`` export, so recorded and synthetic traffic are
interchangeable everywhere downstream. Serialized one JSON object per
line with sorted keys (byte-stable: the determinism tests hash files).

Synthesizers are seeded ``random.Random`` — same seed, same trace,
byte-for-byte. Prompt token VALUES are synthesized deterministically
from the uid (and shared per session prefix, so the prefix-cache model
in the simulator has something real to hit).
"""

import json
import math
import random
from typing import Dict, List, Optional

TRACE_EVENT_KEYS = ("t", "uid", "prompt_tokens", "max_new_tokens",
                    "tenant", "priority", "slo_ms", "session",
                    "deadline_ms")

#: profiles understood by ``synth_trace`` (and ``dstpu_sim --profile``)
PROFILES = ("poisson", "diurnal", "bursty", "heavy_tail")


def save_trace(path: str, events: List[Dict]) -> None:
    with open(path, "w") as fh:
        for ev in events:
            fh.write(json.dumps(ev, sort_keys=True) + "\n")


def load_trace(path: str) -> List[Dict]:
    events = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    _validate(events)
    return events


def _validate(events: List[Dict]) -> None:
    seen = set()
    last_t = -math.inf
    for i, ev in enumerate(events):
        for k in ("t", "uid", "prompt_tokens"):
            if k not in ev:
                raise ValueError(f"trace event {i} missing {k!r}: {ev}")
        if ev["t"] < last_t:
            raise ValueError(f"trace not sorted by t at event {i}")
        last_t = ev["t"]
        if ev["uid"] in seen:
            raise ValueError(f"duplicate uid {ev['uid']} at event {i}")
        seen.add(ev["uid"])


def prompt_for(uid: int, n: int, vocab: int = 32000,
               session_prefix: Optional[List[int]] = None) -> List[int]:
    """Deterministic prompt token values for a trace event.

    A session-shared prefix (same for every request in the session)
    followed by uid-derived filler — gives the prefix cache real common
    prefixes to discover without storing token arrays in the trace."""
    prefix = list(session_prefix or [])[:max(0, n - 1)]
    body = [((uid * 2654435761 + 97 + i * 31) % (vocab - 2)) + 2
            for i in range(n - len(prefix))]
    return prefix + body


def session_prefix_for(session: str, n: int = 24,
                       vocab: int = 32000) -> List[int]:
    h = 2166136261
    for ch in session:
        h = ((h ^ ord(ch)) * 16777619) & 0xFFFFFFFF
    return [((h + i * 131) % (vocab - 2)) + 2 for i in range(n)]


def synth_trace(profile: str = "poisson", *, rate: float = 4.0,
                duration_s: float = 30.0, seed: int = 0,
                prompt_mean: int = 48, prompt_max: int = 192,
                new_tokens_mean: int = 24, new_tokens_max: int = 96,
                tenants: int = 2, sessions: int = 0,
                interactive_frac: float = 0.5,
                slo_ms: Optional[float] = None,
                uid_base: int = 1) -> List[Dict]:
    """Seeded synthetic arrival trace (see PROFILES).

    * ``poisson`` — homogeneous Poisson at ``rate`` req/s.
    * ``diurnal`` — sinusoidal rate between 0.25x and 1.75x ``rate``
      over one period = ``duration_s`` (a compressed day).
    * ``bursty`` — Poisson background plus square bursts at 4x rate for
      10% of each quarter-period (thundering herds).
    * ``heavy_tail`` — Poisson arrivals, but prompt and output lengths
      drawn log-normal: a few giants among many dwarves (the
      adversarial case for frame-lockstep schedulers).
    """
    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r}; one of {PROFILES}")
    rng = random.Random(seed)
    events: List[Dict] = []
    t = 0.0
    uid = uid_base

    def local_rate(now: float) -> float:
        if profile == "diurnal":
            return rate * (1.0 + 0.75 * math.sin(
                2 * math.pi * now / max(1e-9, duration_s)))
        if profile == "bursty":
            q = max(1e-9, duration_s / 4.0)
            return rate * (4.0 if (now % q) < 0.1 * q else 1.0)
        return rate

    def draw_len(mean: int, cap: int) -> int:
        if profile == "heavy_tail":
            # log-normal with sigma=1: median well under the mean, tail
            # out to the cap
            v = int(rng.lognormvariate(math.log(max(2, mean * 0.6)), 1.0))
        else:
            v = int(rng.expovariate(1.0 / max(1, mean))) + 1
        return max(1, min(cap, v))

    while True:
        # thinning: sample at the peak rate, accept at local/peak
        peak = rate * (4.0 if profile == "bursty" else
                       1.75 if profile == "diurnal" else 1.0)
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() > local_rate(t) / peak:
            continue
        ev: Dict = {
            "t": round(t, 9),
            "uid": uid,
            "prompt_tokens": draw_len(prompt_mean, prompt_max),
            "max_new_tokens": draw_len(new_tokens_mean, new_tokens_max),
            "tenant": f"tenant{rng.randrange(max(1, tenants))}",
            "priority": ("interactive"
                         if rng.random() < interactive_frac else "batch"),
        }
        if slo_ms is not None:
            ev["slo_ms"] = float(slo_ms)
        if sessions > 0 and rng.random() < 0.5:
            ev["session"] = f"sess{rng.randrange(sessions)}"
        events.append(ev)
        uid += 1
    return events
