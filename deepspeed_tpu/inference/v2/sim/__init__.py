"""Trace-driven fleet simulator (ROADMAP item 6).

Replays recorded or synthesized arrival traces against the REAL policy
stack — ``EngineRouter`` placement/failover/drain, ``RequestScheduler``
admission/preemption/shed, ``ServiceEdge`` admission math,
``AutoscaleController`` scale/flip laws all run unmodified — under a
deterministic virtual clock, with per-frame cost read from the committed
``.graft-cost-baseline.json`` instead of executing frames. A capacity
question ("how many replicas for this traffic at this SLO?") answers in
seconds on a laptop CPU; the ``--sim-fidelity`` bench row gates the
model against a live threaded fleet on the same schedule.

Layout::

    clock.py    VirtualClock — shared seekable virtual time
    cost.py     FrameCostModel — baseline metrics -> calibrated seconds
    traffic.py  trace schema + seeded synthesizers (poisson/diurnal/...)
    engine.py   SimEngine — the real serve-loop protocol, no frames
    sim.py      FleetSimulator — real router/edge/autoscaler harness
    tune.py     grid/random search over serving knobs
"""

from .clock import VirtualClock
from .cost import CostCalibration, FrameCostModel
from .engine import SimEngine
from .sim import FleetSimulator, SimConfig, SimResult
from .traffic import (load_trace, save_trace, synth_trace,
                      TRACE_EVENT_KEYS)

__all__ = [
    "VirtualClock", "CostCalibration", "FrameCostModel", "SimEngine",
    "FleetSimulator", "SimConfig", "SimResult",
    "load_trace", "save_trace", "synth_trace", "TRACE_EVENT_KEYS",
]
